"""Benchmark: threshold-encoded gradient exchange vs the uncompressed
sharded rung (parallel.zero ENCODED — ISSUE 20).

The claim the acceptance bar checks has two halves, both measured on
the virtual 8-device CPU mesh:

- **wire**: per-replica update-exchange bytes under ENCODED (ring
  model over the codec's serialized payload, at the OBSERVED sparsity
  after real steps) are strictly below the dense counterfactual the
  same step would have moved uncompressed — `compression_ratio` > 1.
- **convergence**: error-feedback residuals keep the encoded loss
  trajectory within tolerance of the uncompressed run over the same
  20 steps (the curves are printed so the BENCH record carries them).

Step wall time rides along for the record; on the CPU proxy it only
says "the compressed tail did not explode", not a TPU claim.

Prints ONE JSON line:
  {"metric": "encoded", "meta": {"proxy": ...},
   "sharded": {...}, "encoded": {...},
   "compression_ratio": R, "encoded_beats_dense_wire": true}
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

STEPS = 20


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=256, n_out=512,
                              activation=Activation.RELU))
            .layer(DenseLayer(n_out=512, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(256))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.RandomState(0)
    x = rng.randn(n, 256).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return DataSet(x, y)


def _run(mode: str, ds):
    from deeplearning4j_tpu.parallel import ParallelWrapper
    net = _net()
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange(mode).build()
    pw.fit_batch(ds)                           # place + compile
    jax.block_until_ready(net.params)
    curve = [round(float(net.score(ds)), 5)]
    t0 = time.perf_counter()
    for _ in range(STEPS - 1):
        pw.fit_batch(ds)
    jax.block_until_ready(net.params)
    step_s = (time.perf_counter() - t0) / (STEPS - 1)
    curve.append(round(float(net.score(ds)), 5))
    return pw, {"step_seconds": round(step_s, 5),
                "loss_first": curve[0], "final_loss": curve[-1]}


def main():
    from deeplearning4j_tpu.common.telemetry import MetricsRegistry
    from deeplearning4j_tpu.parallel.zero import (UpdateExchange,
                                                  exchange_report)

    MetricsRegistry.get().set_enabled(False)   # measure the step, not
    ds = _data()                               # the telemetry spine
    on_tpu = jax.devices()[0].platform == "tpu"
    out = {"metric": "encoded", "workers": 8, "steps": STEPS,
           "updater": "Adam", "unit": "bytes|s",
           "meta": {"proxy": not on_tpu}}

    pw_s, rec_s = _run("sharded", ds)
    rec_s["wire_bytes"] = int(pw_s._exchange_bytes)
    out["sharded"] = rec_s

    pw_e, rec_e = _run("encoded", ds)
    sp = pw_e._observed_encoding_sparsity()
    rep = exchange_report(pw_e.model.params, 8, UpdateExchange.ENCODED,
                          encoding=pw_e.encoding, observed_sparsity=sp)
    rec_e["wire_bytes"] = int(rep["encoded_wire_bytes"])
    rec_e["bytes_per_step"] = int(rep["encoded_wire_bytes"])
    rec_e["observed_sparsity"] = round(float(sp), 5)
    out["encoded"] = rec_e

    out["dense_wire_bytes"] = int(rep["dense_wire_bytes"])
    out["compression_ratio"] = round(float(rep["compression_ratio"]), 3)
    # the two claims, as checkable booleans
    out["encoded_beats_dense_wire"] = bool(
        rep["encoded_wire_bytes"] < rep["dense_wire_bytes"])
    out["loss_within_tolerance"] = bool(
        rec_e["final_loss"] <= rec_s["final_loss"] * 1.25 + 0.05)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
