"""Profiler evidence for the ResNet-50 benchmark (BASELINE config #2).

Captures a jax.profiler device trace of the train step, aggregates
device-op time by HLO category, and prints:
  - step time + throughput,
  - XLA cost-analysis FLOPs/bytes -> achieved TFLOP/s, %-of-peak,
    HBM GB/s vs peak (the roofline),
  - top device ops by total time.

The output of this script is the basis of BENCH_notes_r02.md.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import sys
import tempfile
import time
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.cost_util import (V5E_BF16_PEAK_TFLOPS,  # noqa: E402
                                  V5E_HBM_GBPS, graph_step_cost)


def categorize(name: str) -> str:
    base = re.sub(r"[.\d]+$", "", name)
    if "convolution" in base or base == "fusion":
        # TPU XLA fuses each conv with its epilogue into a generic
        # "fusion.N" computation — the unnamed fusions ARE the convs
        return "conv + fused epilogue (fwd/bwd)"
    if "select_and_scatter" in base:
        return "maxpool backward"
    if "reduce_window" in base:
        return "maxpool forward"
    if "multiply_reduce" in base or "convert_reduce" in base:
        return "BN statistics / weight-grad reductions"
    if "fusion" in base:
        return f"fused elementwise ({base})"
    if base in ("copy", "copy-start", "copy-done"):
        return "copy"
    if "all-reduce" in base or "psum" in base:
        return "collective"
    return base


def fused_attribution(batch, hw, steps, on_tpu):
    """ISSUE-13 roofline attribution: the SAME model costed and timed
    under both settings of the DL4J_TPU_FUSED_CONV gate (fresh net per
    leg — jit freezes the kernel-select decision at trace time).
    Prints bytes / step time / %-of-roof before and after the Pallas
    epilogue family, i.e. how much of the conv-path roofline gap the
    fused kernels close.  Off-TPU this runs the kernels in interpret
    mode on a reduced-stage net: structurally the same program, not a
    representative speed — read the bytes column there, not the ms."""
    from deeplearning4j_tpu.common import diagnostics
    from deeplearning4j_tpu.common.environment import Environment
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    kw = dict(num_classes=1000, height=hw, width=hw,
              compute_dtype="bfloat16" if on_tpu else None)
    if not on_tpu:
        kw.update(STAGES=((2, 16), (2, 32)))
    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    ds = DataSet(jax.device_put(jnp.asarray(x)),
                 jax.device_put(jnp.asarray(y)))

    env = Environment.get()
    saved = env.extra.get("fused_conv")
    legs = {}
    print("\nfused-conv roofline attribution "
          f"({'tpu' if on_tpu else 'cpu proxy, interpret mode'}):")
    try:
        for name, gate in (("unfused", "0"), ("fused", "1")):
            env.extra["fused_conv"] = gate
            net = ResNet50(**kw).init()
            net.fit(ds)                       # build + trace the step
            float(net.score())
            flops, byts = graph_step_cost(net, x, y)
            t0 = time.perf_counter()
            net.fit_steps(ds, steps)
            assert np.isfinite(float(net.score()))
            step_s = (time.perf_counter() - t0) / steps
            roof = diagnostics.roofline(
                flops, byts, step_s,
                peak_tflops=V5E_BF16_PEAK_TFLOPS,
                peak_hbm_gbps=V5E_HBM_GBPS)
            legs[name] = {"bytes": byts, "step_s": step_s,
                          "roof": roof}
            print(f"  {name:8s} {byts / 1e9:7.2f} GB/step  "
                  f"{step_s * 1e3:8.2f} ms  "
                  f"{roof.get('pct_of_roof', 0):5.1f}% of "
                  f"{roof.get('bound', '?')} roof")
    finally:
        if saved is None:
            env.extra.pop("fused_conv", None)
        else:
            env.extra["fused_conv"] = saved
    if len(legs) == 2 and legs["fused"]["bytes"]:
        print(f"  bytes ratio (unfused/fused): "
              f"{legs['unfused']['bytes'] / legs['fused']['bytes']:.3f}")
    return legs


def main(batch=256, hw=224, steps=60):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        batch, hw, steps = 8, 64, 3

    net = ResNet50(num_classes=1000, height=hw, width=hw,
                   compute_dtype="bfloat16").init()
    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    ds = DataSet(jax.device_put(jnp.asarray(x)),
                 jax.device_put(jnp.asarray(y)))

    # -- cost analysis (on the optimized HLO) --------------------------
    net.fit(ds)
    float(net.score())
    flops, byts = graph_step_cost(net, x, y)

    # -- timed steady-state run ----------------------------------------
    net.fit_steps(ds, steps)
    float(net.score())
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        net.fit_steps(ds, steps)
        assert np.isfinite(float(net.score()))
        best = max(best, steps * batch / (time.perf_counter() - t0))
    step_s = batch / best

    print(f"throughput: {best:.0f} img/s  (step {step_s * 1e3:.1f} ms, "
          f"batch {batch})")
    print(f"cost analysis: {flops / 1e9:.0f} GFLOP/step, "
          f"{byts / 1e9:.1f} GB accessed/step")
    tf = flops / step_s / 1e12
    gbps = byts / step_s / 1e9
    print(f"achieved: {tf:.1f} TFLOP/s = {tf / V5E_BF16_PEAK_TFLOPS:.1%} "
          f"of bf16 peak; {gbps:.0f} GB/s = {gbps / V5E_HBM_GBPS:.1%} of "
          f"HBM peak  <-- the binding roofline")

    # -- device trace ---------------------------------------------------
    tdir = tempfile.mkdtemp(prefix="jaxtrace")
    jax.profiler.start_trace(tdir)
    for _ in range(3):
        net.fit(ds)
    float(net.score())
    jax.profiler.stop_trace()

    f = glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True)[0]
    d = json.load(gzip.open(f))
    cats = defaultdict(float)
    for e in d.get("traceEvents", []):
        if e.get("ph") != "X" or e.get("dur", 0) <= 0:
            continue
        name = e.get("name", "?")
        # keep device-lane HLO ops only: skip python/host spans and the
        # whole-module step markers (purely numeric names)
        if name.isdigit() or \
                name.startswith(("$", "jit_", "Pjit", "np.", "b'")) or \
                "/" in name or " " in name:
            continue
        cats[categorize(name)] += e["dur"] / 1e3  # -> ms
    total = sum(cats.values())
    print(f"\ndevice-op time over 3 traced steps: {total:.1f} ms")
    for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1])[:14]:
        print(f"  {ms / 3:7.2f} ms/step  {ms / total:6.1%}  {cat}")

    # -- fused-kernel A/B (ISSUE-13) -----------------------------------
    try:
        fused_attribution(batch, hw, steps if on_tpu else 2, on_tpu)
    except Exception as e:                       # noqa: BLE001
        print(f"fused attribution skipped: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
