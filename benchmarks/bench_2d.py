"""Benchmark: 2D (data/fsdp x tensor) training modes vs dp-only
(parallel.speclayout + the 2D step tails).

ISSUE 12 acceptance: the (dp, tp) and (fsdp, tp) modes train on the
real fit path with the update exchange confined to the ``data`` axis.
We report, per mode: step wall time and throughput, the per-axis wire
accounting from ``zero.exchange_report`` (the ``model`` axis must move
ZERO update bytes; ``cross_axis_bytes`` is what a naive flat ravel of
the tp leaves would have moved across ``model``), and the measured
per-chip param residency after placement.

Runs on the virtual 8-device CPU mesh (the same proxy the parallel
test suite uses), so the byte accounting is exact and the step-time
deltas are smoke numbers, not TPU claims.

Prints ONE JSON line:
  {"metric": "scaling_2d", "dp8_dense": {...}, "dp4_tp2_sharded":
   {...}, "fsdp4_tp2": {...}}
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=256, n_out=512,
                              activation=Activation.RELU))
            .layer(DenseLayer(n_out=512, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(256))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.RandomState(0)
    x = rng.randn(n, 256).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return DataSet(x, y)


def _bytes_on_chip0(tree) -> int:
    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        for sh in getattr(leaf, "addressable_shards", ()):
            if sh.device == dev0:
                total += sh.data.nbytes
    return total


def _time_steps(pw, ds, steps: int) -> float:
    """Median-of-3 wall time per fit_batch, compile excluded."""
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            pw.fit_batch(ds)
        jax.block_until_ready(pw.model.params)
        trials.append((time.perf_counter() - t0) / steps)
    return sorted(trials)[1]


def main():
    from deeplearning4j_tpu.common.telemetry import MetricsRegistry
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.zero import exchange_report

    MetricsRegistry.get().set_enabled(False)   # measure the step, not
    ds = _data()                               # the telemetry spine
    batch = int(ds.features.shape[0])
    out = {"metric": "scaling_2d", "devices": 8,
           "updater": "Adam", "unit": "bytes|s"}

    #: (label, update_exchange, dp workers, tp)
    modes = (("dp8_dense", "dense", 8, 1),
             ("dp4_tp2_sharded", "sharded", 4, 2),
             ("fsdp4_tp2", "fsdp", 4, 2))
    for label, exchange, workers, tp in modes:
        net = _net()
        b = ParallelWrapper.Builder(net).workers(workers) \
            .update_exchange(exchange)
        if tp > 1:
            b = b.tensor_parallel(tp)
        pw = b.build()
        pw.fit_batch(ds)                       # place + compile
        jax.block_until_ready(net.params)
        step_s = _time_steps(pw, ds, steps=5)
        rep = exchange_report(net.dense_params()
                              if hasattr(net, "dense_params")
                              else net.params,
                              workers, pw.update_exchange,
                              model_shards=tp, tp_specs=pw._tp_specs)
        mode_out = {
            "step_seconds": round(step_s, 5),
            "throughput_sps": round(batch / step_s, 1),
            "param_bytes_per_chip": _bytes_on_chip0(net.params),
            "dp_wire_bytes": rep["wire_bytes_per_replica"],
        }
        if tp > 1:
            ax = rep["axis_bytes"]
            mode_out.update({
                "model_axis_update_bytes": ax["model"],
                "cross_axis_bytes": ax["cross_axis_bytes"],
                "naive_ravel_cross_axis_bytes":
                    ax["naive_ravel_cross_axis_bytes"],
                "tp_resident_bytes_per_replica":
                    rep["tp_resident_bytes_per_replica"],
            })
        out[label] = mode_out

    # the 2D wire invariant, as a checkable claim: the update exchange
    # must move ZERO bytes across the model axis in every 2D mode
    out["update_crosses_model_axis"] = any(
        out[label].get("model_axis_update_bytes", 0) or
        out[label].get("cross_axis_bytes", 0)
        for label, _, _, tp in modes if tp > 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
