#!/bin/bash
# r5 chip-benchmark queue: waits for the imported sweep, then runs
# each leg sequentially (one chip, no contention)
while pgrep -f "bench_bert_imported" > /dev/null; do sleep 20; done
cd /root/repo
echo "=== real-decode ETL ($(date)) ==="
python benchmarks/bench_pipeline.py --real-decode --threads 16 2>/dev/null | grep "^{"
echo "=== charrnn roofline probe ($(date)) ==="
python benchmarks/profile_charrnn.py 2>/dev/null | grep "^{"
echo "=== charrnn batch sweep ($(date)) ==="
for b in 64 128 256 512; do
  python benchmarks/bench_charrnn.py --batch $b --steps 1500 --trials 5 2>/dev/null | grep "^{" | sed "s/^/b=$b /"
done
echo "=== inference serving ($(date)) ==="
python benchmarks/bench_inference.py 2>/dev/null | grep "^{"
echo "=== queue done ($(date)) ==="
