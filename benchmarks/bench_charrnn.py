"""Secondary benchmark: GravesLSTM 2x512 char-RNN training throughput
(BASELINE config #3, reference example LSTMCharModellingExample with the
CudnnLSTMHelper fast path; SURVEY.md D4/D9).

The LSTM fast path (the CudnnLSTMHelper equivalent) is structural:
the 4 gate matmuls are one fused [H, 4H] weight, and the input
projection x @ W for ALL timesteps is hoisted out of the scan as one
MXU matmul (layers_recurrent.py) — only the [b, 4H] recurrent matmul
runs per step. Round 1 recorded 21.7k chars/s for this config; that
number amortized first-call compilation into the steady-state loop.
Measured correctly (warm, synced on the loss scalar — NOT
block_until_ready, which does not flush through the axon tunnel),
the same config runs in the hundreds of thousands of chars/s.

Prints ONE JSON line: {"metric": "charrnn_train_throughput", ...}.
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(batch=64, seq_len=64, hidden=512, vocab=80, steps=1500,
         n_trials=7):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.layers_recurrent import GravesLSTM

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        batch, seq_len, hidden, steps = 8, 16, 64, 3

    conf = (NeuralNetConfiguration.Builder()
            .seed(12345)
            .updater(Adam(5e-3))
            .compute_data_type("bfloat16")
            .list()
            .layer(GravesLSTM(n_out=hidden, activation=Activation.TANH))
            .layer(GravesLSTM(n_out=hidden, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=vocab,
                                  loss_function=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(vocab, seq_len))
            .build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (batch, seq_len + 1))
    eye = np.eye(vocab, dtype=np.float32)
    ds = DataSet(jax.device_put(jnp.asarray(eye[ids[:, :-1]])),
                 jax.device_put(jnp.asarray(eye[ids[:, 1:]])))

    net.fit_steps(ds, steps)  # warmup/compile
    jax.block_until_ready(net.params)
    float(net.score())

    from benchmarks.timing import median_throughput

    def run_once():
        net.fit_steps(ds, steps)
        jax.block_until_ready(net.params)
        s = float(net.score())      # sync must survive python -O
        assert np.isfinite(s)

    # 1500 steps/trial (ONE fit_steps dispatch + one loss sync per
    # trial), median-of-7: the r3 200-step/5-trial protocol left ±8%
    # spread against the ≤5% target (r3 verdict Weak #3). Measured
    # ladder: 200 steps → 1.27M ±8%; 600 → 1.71M ±10% (one outlier);
    # 1500 → 1.88M ±4.0% — the per-trial dispatch+sync tax through
    # the axon tunnel is fixed, so longer fori-loop trials asymptote
    # to device-limited throughput AND tighten the spread
    stats = median_throughput(run_once, steps * batch * seq_len,
                              n_trials=n_trials if on_tpu else 3)
    print(json.dumps({
        "metric": "charrnn_train_throughput"
                  + ("" if on_tpu else "_cpu_proxy"),
        **stats,
        "unit": "chars/sec/chip",
    }))

    # -- sampling leg: generate characters from the trained net with
    # the shared ops.sampling primitives (the same sampler the
    # generative serving decode loop threads through its fused step)
    from deeplearning4j_tpu.ops.sampling import sample_logits

    sample_steps = 32 if on_tpu else 8
    key = jax.random.PRNGKey(0)
    window = jnp.asarray(eye[ids[:, :seq_len]])

    def sample_once():
        k, w = key, window
        for i in range(sample_steps):
            probs = net.output(w)               # [b, t, vocab] softmax
            logits = jnp.log(probs[:, -1, :] + 1e-9)
            k = jax.random.fold_in(k, i)
            nxt = sample_logits(logits, k, temperature=0.8, top_k=40)
            w = jnp.concatenate(
                [w[:, 1:], jnp.asarray(eye)[nxt][:, None]], axis=1)
        jax.block_until_ready(w)

    sample_once()                               # warmup/compile
    sstats = median_throughput(sample_once, sample_steps * batch,
                               n_trials=3)
    print(json.dumps({
        "metric": "charrnn_sample_throughput"
                  + ("" if on_tpu else "_cpu_proxy"),
        **sstats,
        "unit": "chars/sec/chip",
    }))


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--steps", type=int, default=1500)
    ap.add_argument("--trials", type=int, default=7)
    a = ap.parse_args()
    main(batch=a.batch, seq_len=a.seq, steps=a.steps,
         n_trials=a.trials)
