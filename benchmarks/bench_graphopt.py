"""GraphOptimizer + flash-attention bench leg.

Two measurements, one JSON line (``{"metric": "graph_optimizer"}``):

1. **Imported-BERT pass payoff** — a frozen toy-dim TF BERT imported
   twice (``optimize=False`` vs the default pipeline) with the MLM
   head attached; reports the per-pass rewrite counts and the median
   ``fit_steps`` dispatch time of each program. On CPU this is a
   proxy (dispatch-dominated at toy dims); the real-dim
   imported-vs-native gap is bench_bert_imported.py's job on TPU.

2. **Flash memory floor** — XLA ``memory_analysis()`` of a compiled
   long-sequence sdpa: dense einsum attention materializes the
   ``[b, h, t, t]`` scores tensor in temp HBM; the Pallas kernel
   (interpret-mode compile off-TPU, same code path) never does. Temp
   bytes for both at the long-seq shape quantify the floor the
   backend removes; falls back to the analytic scores-tensor size if
   ``memory_analysis`` is unavailable on the backend.

Flags: --batch --seq --layers --steps --flash-seq
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _median_step_ms(sd, feeds, steps, trials=5):
    sd.fit_steps(feeds, steps)                    # compile + warm
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(sd.fit_steps(feeds, steps))         # syncs final loss
        times.append((time.perf_counter() - t0) / steps * 1e3)
    return round(statistics.median(times), 3)


def _imported_bert_leg(batch, seq, layers, steps):
    from benchmarks.tf_bert_builder import (build_frozen_bert,
                                            import_and_attach_mlm)
    from deeplearning4j_tpu.learning import Adam
    vocab, hidden, heads = 50, 32, 2
    gd, _ = build_frozen_bert(seq, batch, vocab=vocab, hidden=hidden,
                              heads=heads, layers=layers,
                              intermediate=64)
    rs = np.random.RandomState(0)
    feeds = {
        "ids": rs.randint(0, vocab, (batch, seq)).astype(np.int32),
        "seg": np.zeros((batch, seq), np.int32),
        "mask": np.ones((batch, seq), np.int32),
        "mlm_labels": np.where(rs.rand(batch, seq) < 0.3,
                               rs.randint(0, vocab, (batch, seq)),
                               -1).astype(np.int32)}
    plain, _ = import_and_attach_mlm(gd, batch, seq, vocab=vocab,
                                     hidden=hidden, updater=Adam(1e-3),
                                     optimize=False)
    opt, _ = import_and_attach_mlm(gd, batch, seq, vocab=vocab,
                                   hidden=hidden, updater=Adam(1e-3))
    t_plain = _median_step_ms(plain, feeds, steps)
    t_opt = _median_step_ms(opt, feeds, steps)
    return {"batch": batch, "seq": seq, "layers": layers,
            "counts": dict(opt.graphopt_counts),
            "step_ms_unoptimized": t_plain,
            "step_ms_optimized": t_opt,
            "speedup": round(t_plain / t_opt, 3) if t_opt else None}


def _flash_memory_leg(flash_seq):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.attention import dot_product_attention
    from deeplearning4j_tpu.ops.attention_pallas import flash_sdpa
    b, h, t, d = 1, 4, flash_seq, 64
    q = jnp.zeros((b, h, t, d), jnp.float32)
    scores_bytes = 4 * b * h * t * t

    def _temp_bytes(fn):
        c = jax.jit(fn).lower(q, q, q).compile()
        return int(c.memory_analysis().temp_size_in_bytes)

    leg = {"shape": [b, h, t, d],
           "dense_scores_bytes_analytic": scores_bytes}
    try:
        dense = _temp_bytes(
            lambda q, k, v: dot_product_attention(q, k, v))
        flash = _temp_bytes(
            lambda q, k, v: flash_sdpa(q, k, v, block_q=1024,
                                       block_k=1024))
        leg.update(dense_temp_bytes=dense, flash_temp_bytes=flash,
                   temp_ratio=round(dense / flash, 2) if flash
                   else None, source="memory_analysis")
    except Exception as e:
        print(f"memory_analysis unavailable ({e!r}); analytic only",
              file=sys.stderr)
        leg["source"] = "analytic"
    return leg


def main(batch=4, seq=64, layers=2, steps=8, flash_seq=4096):
    line = {"metric": "graph_optimizer"}
    try:
        line["imported_bert"] = _imported_bert_leg(batch, seq, layers,
                                                   steps)
    except Exception as e:
        print(f"imported-bert leg failed: {e!r}", file=sys.stderr)
    try:
        line["flash_memory"] = _flash_memory_leg(flash_seq)
    except Exception as e:
        print(f"flash-memory leg failed: {e!r}", file=sys.stderr)
    print(json.dumps(line))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--flash-seq", type=int, default=4096)
    a = ap.parse_args()
    main(a.batch, a.seq, a.layers, a.steps, a.flash_seq)
