"""Conv/BN/ReLU epilogue-fusion bench leg (ISSUE-13 tentpole evidence).

A ResNet-50 stage-style bottleneck tower (1x1 -> 3x3 -> 1x1, each
conv followed by BN and ReLU, residual add) trained for one step
under both settings of the ``DL4J_TPU_FUSED_CONV`` gate:

  unfused — the dense ``lax.conv_general_dilated`` + XLA-fused
            epilogue lowering the layers always used
  fused   — the Pallas epilogue family (ops/conv_pallas.py): BN
            statistics and scale/shift/act inside output tiles, the
            1x1 convs on the matmul+epilogue kernel when aligned

Per leg: median train-step ms, compiled ``memory_analysis`` temp
bytes, XLA cost-analysis flops / bytes accessed, and the roofline
classification (``diagnostics.roofline``) — pct_of_roof is the
acceptance number.  Off-TPU the kernels run in Pallas interpret mode
(same code path, not representative speed) and the line is marked
``meta.proxy``; the roofline is still computed against the v5e peaks
so the before/after structure is identical on both rigs.

The gate is trace-time (jit freezes the kernel-select decision), so
each leg builds and traces its OWN step function while the
``Environment.extra['fused_conv']`` override is set.

Prints ONE JSON line: ``{"metric": "conv_kernels", ...}``.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _build_step(batch, hw, channels, dtype):
    """Bottleneck tower as pure layer calls (no network plumbing):
    returns (params, states, x, step_fn) with step_fn a fresh
    un-jitted train step closing over the layer objects."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                   ConvolutionLayer)
    from deeplearning4j_tpu.nn.conf.layers import ConvolutionMode

    width = channels // 4
    specs = [
        # biased-ReLU stem: the conv-epilogue site proper (bias+act
        # streamed into the conv output tiles when fused)
        (ConvolutionLayer(kernel_size=(3, 3), n_in=channels,
                          n_out=channels, has_bias=True,
                          convolution_mode=ConvolutionMode.SAME,
                          activation=Activation.RELU), None),
        (ConvolutionLayer(kernel_size=(1, 1), n_in=channels,
                          n_out=width, has_bias=False,
                          convolution_mode=ConvolutionMode.SAME,
                          activation=Activation.IDENTITY), None),
        (BatchNormalization(activation=Activation.RELU), width),
        (ConvolutionLayer(kernel_size=(3, 3), n_in=width, n_out=width,
                          has_bias=False,
                          convolution_mode=ConvolutionMode.SAME,
                          activation=Activation.IDENTITY), None),
        (BatchNormalization(activation=Activation.RELU), width),
        (ConvolutionLayer(kernel_size=(1, 1), n_in=width,
                          n_out=channels, has_bias=False,
                          convolution_mode=ConvolutionMode.SAME,
                          activation=Activation.IDENTITY), None),
        (BatchNormalization(activation=Activation.IDENTITY), channels),
        # biased-ReLU 1x1 head at 128-lane-aligned channels: the
        # matmul+epilogue kernel site when fused
        (ConvolutionLayer(kernel_size=(1, 1), n_in=channels,
                          n_out=channels, has_bias=True,
                          convolution_mode=ConvolutionMode.SAME,
                          activation=Activation.RELU), None),
    ]
    key = jax.random.PRNGKey(0)
    params, states = [], []
    for layer, nf in specs:
        if isinstance(layer, BatchNormalization):
            itype = InputType.convolutional(hw, hw, nf)
            layer.set_n_in(itype, True)
            params.append(layer.init_params(key, itype, dtype))
            states.append(layer.init_state(itype, dtype))
        else:
            itype = InputType.convolutional(hw, hw, layer.n_in)
            key, sub = jax.random.split(key)
            params.append(layer.init_params(sub, itype, dtype))
            states.append(None)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, hw, hw, channels) * 0.1, dtype)

    def step(params, states, x):
        def loss(params):
            h, new_states = x, []
            for (layer, _), p, st in zip(specs, params, states):
                h, st = layer.forward(p, h, training=True, state=st)
                new_states.append(st)
            out = jax.nn.relu(h + x)          # residual close
            return jnp.sum(out.astype(jnp.float32) ** 2), new_states
        (l, new_states), grads = jax.value_and_grad(
            loss, has_aux=True)(params)
        return l, grads, new_states

    return params, states, x, step


def _leg(gate, batch, hw, channels, dtype, trials, steps):
    import jax

    from benchmarks.cost_util import V5E_BF16_PEAK_TFLOPS, V5E_HBM_GBPS
    from deeplearning4j_tpu.common import diagnostics
    from deeplearning4j_tpu.common.environment import Environment
    from deeplearning4j_tpu.ops import kernel_select

    env = Environment.get()
    saved = env.extra.get("fused_conv")
    env.extra["fused_conv"] = gate
    before = {fam: kernel_select.decisions(fam)
              for fam in ("conv_epilogue", "bn_fwd")}
    try:
        params, states, x, step = _build_step(batch, hw, channels,
                                              dtype)
        jitted = jax.jit(step)
        l, grads, new_states = jitted(params, states, x)  # trace here
        jax.block_until_ready(grads)
        assert bool(jax.numpy.isfinite(l))
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(steps):
                l, grads, _ = jitted(params, states, x)
            jax.block_until_ready(grads)
            times.append((time.perf_counter() - t0) / steps * 1e3)
        leg = {"step_ms": round(statistics.median(times), 3)}
        leg["kernel_select"] = {
            fam: {d: n - before[fam].get(d, 0)
                  for d, n in kernel_select.decisions(fam).items()
                  if n != before[fam].get(d, 0)}
            for fam in before}
        try:
            compiled = jitted.lower(params, states, x).compile()
            leg["temp_bytes"] = int(
                compiled.memory_analysis().temp_size_in_bytes)
            ca = compiled.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0]
            flops = float(ca.get("flops", 0.0))
            byts = float(ca.get("bytes accessed", 0.0))
            leg["flops"] = flops
            leg["bytes_accessed"] = byts
            step_s = leg["step_ms"] / 1e3
            leg["roofline"] = diagnostics.roofline(
                flops, byts, step_s,
                peak_tflops=V5E_BF16_PEAK_TFLOPS,
                peak_hbm_gbps=V5E_HBM_GBPS)
        except Exception as e:
            print(f"cost/memory analysis unavailable ({e!r})",
                  file=sys.stderr)
    finally:
        if saved is None:
            env.extra.pop("fused_conv", None)
        else:
            env.extra["fused_conv"] = saved
    return leg


def main(batch=None, hw=None, channels=None, dtype_name=None,
         trials=3, steps=5):
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    if batch is None:
        batch = 32 if on_tpu else 2
    if hw is None:
        hw = 32 if on_tpu else 8
    if channels is None:
        # 256 keeps the 1x1 convs on the matmul+epilogue kernel
        # (128-lane aligned); the CPU proxy uses the same so both
        # fused sites are exercised in interpret mode
        channels = 256 if on_tpu else 128
    if dtype_name is None:
        dtype_name = "bfloat16" if on_tpu else "float32"
    import jax.numpy as jnp
    dtype = jnp.dtype(dtype_name)

    line = {"metric": "conv_kernels",
            "shape": [batch, hw, hw, channels], "dtype": dtype_name,
            "meta": {"proxy": not on_tpu,
                     "platform": jax.devices()[0].platform}}
    for name, gate in (("unfused", "0"), ("fused", "1")):
        try:
            line[name] = _leg(gate, batch, hw, channels, dtype,
                              trials, steps)
        except Exception as e:
            print(f"{name} leg failed: {e!r}", file=sys.stderr)
            line[name] = {"error":
                          f"{type(e).__name__}: {str(e)[:160]}"}
    u, f = line.get("unfused", {}), line.get("fused", {})
    if "bytes_accessed" in u and "bytes_accessed" in f and \
            f["bytes_accessed"]:
        line["bytes_ratio"] = round(
            u["bytes_accessed"] / f["bytes_accessed"], 3)
    if "step_ms" in u and "step_ms" in f and f["step_ms"]:
        line["speedup"] = round(u["step_ms"] / f["step_ms"], 3)
    print(json.dumps(line))
    return line


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--hw", type=int, default=None)
    ap.add_argument("--channels", type=int, default=None)
    ap.add_argument("--dtype", default=None)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--steps", type=int, default=5)
    a = ap.parse_args()
    main(a.batch, a.hw, a.channels, a.dtype, a.trials, a.steps)
