"""Generative serving benchmark: decode goodput, streaming latency
percentiles, KV-pool occupancy vs shed rate, and the paged-vs-dense
decode-attention A/B.

Measures the ISSUE-16 claims the way an operator would check them:

- **Decode goodput** — N closed-loop clients stream completions
  through one :class:`~deeplearning4j_tpu.serving.generative
  .DecodeEngine` (iteration-level continuous batching: every live
  sequence advances one token per fused step). Reports generated
  tokens/s, client-observed TTFT p50/p99 and inter-token p50/p99 —
  the streaming SLO surface.
- **Occupancy vs shed** — the same workload against a deliberately
  small KV pool: mean block occupancy over the run next to the shed
  rate (PoolExhausted → 429 at submit). The pair says whether the
  pool is sized to its load or shedding while half empty.
- **Paged vs dense A/B** — the fused decode step with the Pallas
  ``paged_decode_attention`` kernel vs the dense-gather reference at
  equal batch, median step time each, plus the greedy token-equality
  check (the conformance gate's claim, measured here as perf).

Bench honesty: off-TPU the Pallas kernel runs in interpret mode, so
the A/B is a *correctness* proxy there, not a perf claim —
``meta.proxy`` marks those rounds (``scripts/check_bench_regression``
skips proxy-vs-tpu comparisons).

Prints ONE JSON line (``bench.py`` folds it into its ``generative``
block):

  {"metric": "generative", "goodput_tokens_per_s": ...,
   "ttft_p50_ms": ..., "ttft_p99_ms": ..., "intertoken_p50_ms": ...,
   "intertoken_p99_ms": ..., "occupancy_mean": ...,
   "shed_rate": ..., "paged": {...}, "meta": {...}}

Run: JAX_PLATFORMS=cpu python benchmarks/bench_generative.py
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

if os.environ.get("DL4J_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else None


def _engine(conf, *, kv_blocks, block=8, decode_buckets=(8,),
            max_seq_len=64):
    from deeplearning4j_tpu.models.decoder import DecoderLM
    from deeplearning4j_tpu.serving.generative import DecodeEngine
    from deeplearning4j_tpu.serving.kvcache import KVBlockPool
    model = DecoderLM(conf)
    pool = KVBlockPool(conf.n_layers, kv_blocks, block, conf.n_heads,
                       conf.head_dim, name="bench")
    eng = DecodeEngine(model, model.init(), pool, name="bench",
                       prompt_buckets=(16,),
                       decode_buckets=decode_buckets,
                       max_seq_len=max_seq_len)
    eng.warmup()
    return model, pool, eng


def _stream_clients(eng, pool, *, n_clients, prompt_len, max_tokens,
                    rng):
    """Closed-loop streaming clients; returns (ttfts, gaps, sheds,
    occupancy samples, tokens, wall)."""
    from deeplearning4j_tpu.serving.kvcache import PoolExhausted
    ttfts, gaps, occ = [], [], []
    sheds = [0]
    tokens = [0]
    lock = threading.Lock()

    def client(i):
        prompt = rng.integers(2, 60, size=prompt_len)
        t_sub = time.perf_counter()
        try:
            stream = eng.submit(prompt, max_tokens)
        except PoolExhausted:
            with lock:
                sheds[0] += 1
            return
        t_prev, first = t_sub, True
        try:
            for _ in stream:
                now = time.perf_counter()
                with lock:
                    if first:
                        ttfts.append((now - t_sub) * 1e3)
                        first = False
                    else:
                        gaps.append((now - t_prev) * 1e3)
                    tokens[0] += 1
                    occ.append(pool.occupancy)
                t_prev = now
        except PoolExhausted:
            # retired mid-decode when extend() found the pool dry —
            # tokens already streamed still count; the end is a shed
            with lock:
                sheds[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return ttfts, gaps, sheds[0], occ, tokens[0], wall


def _paged_ab(conf, model, eng, pool, *, batch, steps=8):
    """Median fused-step time, paged kernel vs dense gather, plus the
    greedy token-equality check at the decode protocol's own state."""
    import jax as _jax

    tokens = np.arange(2, 2 + batch, dtype=np.int32)
    positions = np.full((batch,), 3, np.int32)
    tables = np.zeros((batch, eng.max_blocks), np.int32)
    for i in range(batch):
        tables[i, 0] = 1 + (i % max(pool.num_blocks - 1, 1))
    out = {}
    ids_by_mode = {}
    for mode, paged in (("paged", True), ("dense", False)):
        fn = _jax.jit(lambda p, kf, vf, t, pos, tab: model.decode_step(
            p, t, pos, kf, vf, tab, paged=paged))
        ids, kp, vp = fn(eng.params, pool.k, pool.v, tokens, positions,
                         tables)
        _jax.block_until_ready(ids)
        times = []
        for _ in range(steps):
            t0 = time.perf_counter()
            ids, kp, vp = fn(eng.params, pool.k, pool.v, tokens,
                             positions, tables)
            _jax.block_until_ready(ids)
            times.append((time.perf_counter() - t0) * 1e3)
        ids_by_mode[mode] = np.asarray(np.argmax(ids, axis=-1))
        out[f"{mode}_step_ms"] = round(float(np.median(times)), 3)
    out["greedy_tokens_equal"] = bool(
        np.array_equal(ids_by_mode["paged"], ids_by_mode["dense"]))
    return out


def main():
    from deeplearning4j_tpu.models.decoder import DecoderConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    conf = DecoderConfig.tiny()
    rng = np.random.default_rng(0)
    n_clients = 24 if on_tpu else 12
    max_tokens = 16 if on_tpu else 8

    # -- goodput + streaming percentiles (roomy pool: no shedding) ----
    model, pool, eng = _engine(conf, kv_blocks=128,
                               decode_buckets=(8, 16))
    ttfts, gaps, sheds, occ, tokens, wall = _stream_clients(
        eng, pool, n_clients=n_clients, prompt_len=8,
        max_tokens=max_tokens, rng=rng)
    line = {
        "metric": "generative",
        "n_clients": n_clients,
        "max_tokens": max_tokens,
        "goodput_tokens_per_s": round(tokens / wall, 1),
        "ttft_p50_ms": round(_pct(ttfts, 50) or 0.0, 2),
        "ttft_p99_ms": round(_pct(ttfts, 99) or 0.0, 2),
        "intertoken_p50_ms": round(_pct(gaps, 50) or 0.0, 3),
        "intertoken_p99_ms": round(_pct(gaps, 99) or 0.0, 3),
        "occupancy_mean": round(float(np.mean(occ)) if occ else 0.0,
                                3),
        "retraces_since_warmup": eng.retraces_since_warmup(),
    }

    # -- paged vs dense fused-step A/B on the same engine -------------
    line["paged"] = _paged_ab(conf, model, eng, pool,
                              batch=8 if on_tpu else 4)
    eng.shutdown()

    # -- occupancy vs shed against a deliberately small pool ----------
    _, spool, seng = _engine(conf, kv_blocks=8, decode_buckets=(8,))
    _, _, ssheds, socc, stoks, _ = _stream_clients(
        seng, spool, n_clients=n_clients, prompt_len=8,
        max_tokens=max_tokens, rng=rng)
    line["small_pool"] = {
        "shed_rate": round(ssheds / n_clients, 3),
        "occupancy_mean": round(float(np.mean(socc)) if socc
                                else 0.0, 3),
        "tokens": stoks,
    }
    seng.shutdown()

    try:
        from deeplearning4j_tpu.common import diagnostics
        line["meta"] = diagnostics.bench_meta()
        line["meta"]["proxy"] = not on_tpu
    except Exception as e:       # noqa: BLE001
        print(f"meta block failed: {e!r}", file=sys.stderr)
        line["meta"] = {"proxy": not on_tpu}
    print(json.dumps(line))


if __name__ == "__main__":
    main()
