"""Secondary benchmark: BERT-base MLM pretraining throughput
(BASELINE config #4). bf16 + per-layer FULL remat + XLA fused
attention, batch 128 x seq 128, fit_steps fori-loop protocol — the
late-r4 sweep's winner (BENCH_notes_r04.md: once the per-step
dispatch+sync tax is amortized by the fori loop, SMALL batches win —
b128 49.2% of bf16 peak vs b1024's 43.9%; the earlier "batch is the
MFU lever" finding was partly that tax). Full remat still beats
dots_saveable/no-remat at every batch. XLA fused attention measured
1.33x over the Pallas flash kernel at BERT shapes and 1.8x at seq
512 (kernel-backward era re-measurement); flash remains the
long-context/CP path (crossover ~2k tokens).

Prints ONE JSON line: {"metric": "bert_mlm_train_throughput", ...}.
CLI flags reproduce the published A/B legs:
  --seq 512 --batch 12 --max-predictions 76      (seq-512 leg — the
      r5 sweep's winner: full remat + b12 = 140.6k tokens/s, 41.9%
      bf16 peak; see BENCH_notes_r05.md for the remat x batch grid.
      At seq 512 SMALL batches win — attention memory is O(b*t^2))
  --flash                                        (Pallas kernel leg)
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.cost_util import V5E_BF16_PEAK_TFLOPS  # noqa: E402


def main(batch=128, seq=128, steps=60, max_predictions=32,
         flash=False, remat="full", fused_qkv=False):
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.models.bert import Bert, BertConfig

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        batch, seq, steps = 4, 128, 2
        conf = BertConfig.tiny(compute_dtype="bfloat16",
                               hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0)
    else:
        # use_flash_attention=False by default: at seq 128 (and 512)
        # XLA's fused attention beats the Pallas flash kernel on v5e —
        # 109k vs 82k tokens/s measured (BENCH_notes_r03.md). The
        # flash kernel's domain is LONG sequences (ring-attention CP),
        # not BERT-base shapes.
        # remat policy from the r4 MFU sweep (BENCH_notes_r04.md):
        # "full" recomputes the whole layer, "dots" saves matmul
        # outputs, "none" stores everything (needs a smaller batch)
        conf = BertConfig(compute_dtype="bfloat16",
                          remat=False if remat == "none" else remat,
                          use_flash_attention=flash,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          max_predictions_per_seq=max_predictions,
                          fused_qkv=fused_qkv,
                          max_position_embeddings=max(512, seq))

    model = Bert(conf, Adam(1e-4)).init()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, conf.vocab_size, (batch, seq)).astype(np.int32)
    mlm_labels = np.where(rng.rand(batch, seq) < 0.15,
                          rng.randint(0, conf.vocab_size, (batch, seq)),
                          -1).astype(np.int32)
    batch_d = {"input_ids": jax.device_put(jnp.asarray(ids)),
               "mlm_labels": jax.device_put(jnp.asarray(mlm_labels))}

    model.fit_steps(batch_d, steps)   # compile; syncs on final loss

    from benchmarks.timing import median_throughput

    def run_once():
        # ONE fori-loop dispatch + one loss sync per trial: the
        # per-step dispatch+sync tax through the axon tunnel is fixed,
        # so amortizing it measures device-limited throughput (the
        # char-RNN protocol, BENCH_notes_r04.md)
        loss = model.fit_steps(batch_d, steps)
        assert np.isfinite(loss)

    stats = median_throughput(run_once, steps * batch * seq,
                              n_trials=5 if on_tpu else 3)
    best = stats["value"]
    line = {"metric": "bert_mlm_train_throughput"
                      + ("" if on_tpu else "_cpu_proxy"),
            **stats,
            "unit": "tokens/sec/chip"}

    # Analytic matmul FLOPs (XLA's cost_analysis undercounts dot FLOPs
    # inside fusions and cannot see the Pallas flash custom call —
    # see BENCH_notes_r02.md). fwd multiply-adds x2, train = 3x fwd
    # (+1 fwd again under remat, counted separately as recompute).
    H, I = conf.hidden_size, conf.intermediate_size
    L, V = conf.num_hidden_layers, conf.vocab_size
    k = conf.max_predictions_per_seq or seq
    per_layer = 4 * 2 * H * H + 2 * 2 * H * I + 4 * seq * H
    head = (2 * H * H + 2 * H * V) * (k / seq)
    fwd_per_token = L * per_layer + head
    train_flops_per_token = 3 * fwd_per_token
    tf = best * train_flops_per_token / 1e12
    line["tflops_analytic"] = round(tf, 1)
    if on_tpu:
        line["pct_bf16_peak"] = round(100 * tf / V5E_BF16_PEAK_TFLOPS, 1)
    print(json.dumps(line))


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--max-predictions", type=int, default=32)
    ap.add_argument("--flash", action="store_true",
                    help="use the Pallas flash-attention kernel "
                         "instead of XLA fused attention")
    ap.add_argument("--fused-qkv", action="store_true",
                    help="q/k/v as one [H,3H] GEMM (A/B flag)")
    ap.add_argument("--remat", default="full",
                    choices=["full", "dots", "none"],
                    help="activation rematerialization policy")
    a = ap.parse_args()
    main(batch=a.batch, seq=a.seq, steps=a.steps,
         max_predictions=a.max_predictions, flash=a.flash,
         remat=a.remat, fused_qkv=a.fused_qkv)
