"""Serving benchmark (r4 verdict Weak #4: ParallelInference had never
been measured). Reference role: org.deeplearning4j.parallelism.
ParallelInference exists for exactly this — request batching for
throughput without unbounded latency.

Legs (each printed as one JSON line):
  resnet50_serving_latency     — single-request (b=1) p50/p95/p99 ms
  resnet50_serving_throughput  — SEQUENTIAL large-batch img/s
  resnet50_serving_batched     — BATCHED mode: many b=1 requests
                                 aggregated, batch_limit sweep
  bert_imported_serving        — the S6-imported BERT-base served via
                                 SameDiff.output: b=1 latency
                                 percentiles + large-batch tokens/s
On the axon rig every request crosses the HTTP tunnel, so the
latency percentiles INCLUDE a fixed ~100-200 ms tunnel round-trip —
they are an upper bound; the throughput legs amortize it.
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _percentiles(times_s):
    a = np.asarray(times_s) * 1e3
    return {"p50_ms": round(float(np.percentile(a, 50)), 2),
            "p95_ms": round(float(np.percentile(a, 95)), 2),
            "p99_ms": round(float(np.percentile(a, 99)), 2),
            "n": len(a)}


def bench_resnet(on_tpu, n_lat=100):
    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                       ParallelInference)
    hw = 224 if on_tpu else 64
    kw = {} if on_tpu else {"STAGES": ((1, 8), (1, 16))}
    net = ResNet50(num_classes=1000, height=hw, width=hw,
                   compute_dtype="bfloat16", **kw).init()
    pi = (ParallelInference.Builder(net)
          .inference_mode(InferenceMode.BATCHED)
          .batch_limit(32).build())
    rng = np.random.RandomState(0)
    one = rng.randn(1, hw, hw, 3).astype(np.float32)

    pi.output(one)                       # compile b=1
    times = []
    for _ in range(n_lat if on_tpu else 10):
        t0 = time.perf_counter()
        pi.output(one)                   # np.asarray inside = sync
        times.append(time.perf_counter() - t0)
    print(json.dumps({"metric": "resnet50_serving_latency_b1",
                      "unit": "ms", **_percentiles(times)}))

    big_n = 256 if on_tpu else 16
    big = rng.randn(big_n, hw, hw, 3).astype(np.float32)
    pi.output(big)                       # compile big batch
    t0 = time.perf_counter()
    trials = 5 if on_tpu else 2
    for _ in range(trials):
        pi.output(big)
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": "resnet50_serving_throughput",
                      "value": round(trials * big_n / dt, 1),
                      "unit": "images/sec/chip", "batch": big_n,
                      "note": "wire-inclusive (host->device transfer "
                              "per request; the axon tunnel on this "
                              "rig)"}))

    # device-resident leg: the CHIP's serving ceiling — input already
    # on device, time the jitted forward alone (what a co-located
    # host sees, plus ~0.1 ms dispatch)
    import jax.numpy as jnp
    xd = jax.device_put(jnp.asarray(big, jnp.bfloat16))
    np.asarray(pi._fwd(net.params, net.states, xd))   # warm
    t0 = time.perf_counter()
    for _ in range(trials):
        out = pi._fwd(net.params, net.states, xd)
    np.asarray(out)                      # one final sync
    dt = time.perf_counter() - t0
    print(json.dumps({"metric":
                      "resnet50_serving_throughput_device_resident",
                      "value": round(trials * big_n / dt, 1),
                      "unit": "images/sec/chip", "batch": big_n}))

    reqs = [rng.randn(1, hw, hw, 3).astype(np.float32)
            for _ in range(big_n)]
    sweep = {}
    for bl in (8, 32, 128, 256) if on_tpu else (4, 16):
        pi.batch_limit = bl
        pi.output_batched(reqs[:bl])     # compile this window size
        t0 = time.perf_counter()
        out = pi.output_batched(reqs)
        dt = time.perf_counter() - t0
        assert len(out) == len(reqs)
        sweep[bl] = round(len(reqs) / dt, 1)
    print(json.dumps({"metric": "resnet50_serving_batched_reqs_per_s",
                      "unit": "requests/sec (b=1 each)",
                      "by_batch_limit": sweep}))

    # async observable path: concurrent submits through the batching
    # worker, latency under load + sustained req/s per window setting
    pi.batch_limit = 32
    for window_ms in (2.0, 10.0) if on_tpu else (5.0,):
        pi.batch_window_ms = window_ms
        futs = [pi.submit(r) for r in reqs[:8]]   # warm worker+compile
        [f.result(timeout=300) for f in futs]
        t0 = time.perf_counter()
        lat = []

        def one(r):
            s = time.perf_counter()
            pi.submit(r).result(timeout=300)
            lat.append(time.perf_counter() - s)

        import concurrent.futures as cf
        with cf.ThreadPoolExecutor(16) as ex:
            list(ex.map(one, reqs))
        dt = time.perf_counter() - t0
        print(json.dumps({
            "metric": "resnet50_serving_async_submit",
            "window_ms": window_ms,
            "reqs_per_s": round(len(reqs) / dt, 1),
            **_percentiles(lat)}))
    pi.shutdown()


def bench_bert_imported(on_tpu, n_lat=50):
    from deeplearning4j_tpu.learning import Adam
    from benchmarks.tf_bert_builder import (build_frozen_bert,
                                            import_and_attach_mlm)
    if on_tpu:
        seq, vocab, hidden, heads, layers, inter = \
            128, 30522, 768, 12, 12, 3072
    else:
        seq, vocab, hidden, heads, layers, inter = 16, 50, 16, 2, 2, 32
    # a frozen GraphDef bakes its batch dim into reshape consts, so
    # the b=1 latency leg and the large-batch throughput leg each
    # import at their own batch
    def import_at(b):
        gd, _ = build_frozen_bert(seq, b, vocab=vocab, hidden=hidden,
                                  heads=heads, layers=layers,
                                  intermediate=inter)
        sd, _ = import_and_attach_mlm(gd, b, seq, vocab=vocab,
                                      hidden=hidden,
                                      updater=Adam(1e-4))
        return sd

    rng = np.random.RandomState(0)

    def feeds(b):
        return {"ids": rng.randint(0, vocab, (b, seq), dtype=np.int32),
                "seg": np.zeros((b, seq), np.int32),
                "mask": np.ones((b, seq), np.int32)}
    def cls_var(sd_model, b):
        """Serve the CLS vector [b, H], not the full [b, T, H] hidden
        states — a realistic serving head; the full tensor would ship
        ~50 MB back across the wire per request and measure only the
        link."""
        out_var = ("encoder_out"
                   if sd_model.has_variable("encoder_out") else
                   [n for n in sd_model.vars if "Identity" in n][0])
        v = sd_model._op("slice", [sd_model.get_variable(out_var)],
                         {"begin": [0, 0, 0], "size": [b, 1, hidden]})
        return v.name

    sd = import_at(1)
    cv = cls_var(sd, 1)
    one = feeds(1)
    sd.output(one, [cv])                 # compile b=1
    times = []
    for _ in range(n_lat if on_tpu else 5):
        t0 = time.perf_counter()
        np.asarray(sd.output(one, [cv])[cv])
        times.append(time.perf_counter() - t0)
    print(json.dumps({"metric": "bert_imported_serving_latency_b1",
                      "seq": seq, "unit": "ms",
                      **_percentiles(times)}))

    b = 128 if on_tpu else 4
    sd = import_at(b)
    cv = cls_var(sd, b)
    big = feeds(b)
    sd.output(big, [cv])                 # compile big batch
    trials = 5 if on_tpu else 2
    t0 = time.perf_counter()
    for _ in range(trials):
        np.asarray(sd.output(big, [cv])[cv])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bert_imported_serving_throughput",
        "value": round(trials * b * seq / dt, 1),
        "unit": "tokens/sec/chip", "batch": b, "seq": seq,
        "served_output": "CLS vector [b, hidden]"}))


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    bench_resnet(on_tpu)
    bench_bert_imported(on_tpu)


if __name__ == "__main__":
    main()
