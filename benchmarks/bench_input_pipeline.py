"""Feeding-ladder benchmark: sync vs host-async vs device-prefetch.

Measures the per-step input-pipeline stall (host wait % — everything
that is not pure device compute, charged to the pipeline even when the
H2D copy hides inside the jit dispatch; see
``benchmarks.timing.feed_stall_report``) for the three feeding rungs
(``datasets/iterators.py`` module docstring):

  sync            ETL + H2D + step serialized on the fit thread
  host_async      AsyncDataSetIterator: ETL on a feeder thread
  device_prefetch DevicePrefetcher: ETL AND the device_put on the
                  feeder thread, double-buffered

Workload: a large-batch conv stub (conv/stride-4 -> global pool ->
softmax — the LeNet/ResNet skeleton at minimum depth) on 3-channel
images: per-batch bytes are large relative to compute, so this is the
transfer-bound regime where feeding strategy is the step time
(BENCH_notes_r02.md: on the tunneled rig the host link IS the wall;
this bench reproduces that regime at CPU scale).

Device emulation on CPU: host/device overlap requires the device to be
INDEPENDENT hardware, which the CPU backend is not (on this 1-core rig
XLA compute and the feeder thread share the core, so a "real-compute"
ladder only measures thread contention). The CPU leg therefore runs
the real ETL + the real jnp conversion/H2D analogue against a
fixed-latency GIL-releasing device step (sleep — the core is free for
the feeder exactly as it is while a TPU steps), which measures the
thing that matters: WHAT REMAINS ON THE CRITICAL PATH per feeding
rung. On TPU the step is the real jitted train step.

Separately verifies real training is NUMERICALLY IDENTICAL across
feeding modes (same seed, same batches -> bit-equal params): staging
must change timing only, never results.

Prints one JSON line per mode plus a final summary line
(``input_pipeline_stall_pct``) that bench.py folds into its record.
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


class _EtlIterator:
    """Deterministic uint8 pool -> float32 normalize in next() — the
    decode/augment/normalize cost a real image pipeline pays per batch,
    identical across feeding modes (so results can be compared
    bit-for-bit)."""

    def __init__(self, pool_u8, labels, batch, n_batches):
        self.pre_processor = None
        self._pool = pool_u8
        self._labels = labels
        self._batch = batch
        self._n = n_batches
        self._i = 0

    def set_pre_processor(self, p):
        self.pre_processor = p

    def reset(self):
        self._i = 0

    def has_next(self):
        return self._i < self._n

    def next(self):  # noqa: A003
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if not self.has_next():
            raise StopIteration
        b, n = self._batch, self._pool.shape[0]
        lo = (self._i * b) % n
        idx = (np.arange(b) + lo) % n
        x = self._pool[idx].astype(np.float32) * np.float32(1 / 255.0)
        y = self._labels[idx]
        self._i += 1
        return DataSet(x, y)

    def batch(self):
        return self._batch

    def batches(self):
        """Materialized batch list (for the identity check)."""
        self.reset()
        out = []
        while self.has_next():
            out.append(self.next())
        self.reset()
        return out


def _stub_conf(hw: int, seed: int = 7):
    """conv(8, 3x3, stride 2) -> global avg pool -> softmax10: the
    conv-net skeleton with compute shrunk until the batch transfer is
    the dominant term."""
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (
        ConvolutionLayer, GlobalPoolingLayer, OutputLayer, PoolingType)
    from deeplearning4j_tpu.nn.weights import WeightInit
    return (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(Sgd(1e-2))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(ConvolutionLayer.Builder(3, 3)
                   .n_out(4).stride((4, 4))
                   .activation(Activation.RELU).build())
            .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
            .layer(OutputLayer.Builder(LossFunction.MCXENT)
                   .n_out(10).activation(Activation.SOFTMAX).build())
            .set_input_type(InputType.convolutional(hw, hw, 3))
            .build())


def main():
    from benchmarks.timing import feed_stall_report, median_throughput
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.datasets.prefetch import DevicePrefetcher
    from deeplearning4j_tpu.nn import MultiLayerNetwork

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = 512 if on_tpu else 256
    hw = 96 if on_tpu else 64
    n_batches = 12 if on_tpu else 8
    n_trials = 5

    def make_net():
        return MultiLayerNetwork(_stub_conf(hw)).init()

    rng = np.random.RandomState(0)
    pool = rng.randint(0, 255, (2 * batch, hw, hw, 3), np.uint8)
    labels = np.eye(10, dtype=np.float32)[rng.randint(0, 10, 2 * batch)]

    def make_base():
        return _EtlIterator(pool, labels, batch, n_batches)

    net = make_net()

    # warmup/compile + device-resident pure step time
    first = make_base().next()
    dev = DataSet(jax.device_put(jnp.asarray(first.features)),
                  jax.device_put(jnp.asarray(first.labels)))
    net.fit(dev)
    jax.block_until_ready(net.params)

    if on_tpu:
        def pure_once():
            net.fit(dev)
            jax.block_until_ready(net.params)

        pure = median_throughput(pure_once, 1.0, n_trials=5)
        pure_step_s = 1.0 / pure["value"]

        def step_fn(ds):
            net.fit(ds)
            jax.block_until_ready(net.params)
    else:
        # emulated independent device (see module docstring): the
        # conversion/H2D analogue is real and synchronous; the device
        # step releases the GIL and the core, like a TPU would
        import time as _time
        pure_step_s = 0.03

        def step_fn(ds):
            x = jnp.asarray(ds.features)
            y = jnp.asarray(ds.labels)
            jax.block_until_ready((x, y))
            _time.sleep(pure_step_s)

    modes = {
        "sync": make_base,
        "host_async": lambda: AsyncDataSetIterator(make_base(),
                                                   queue_size=3),
        # thread_put=True: the accelerator-default configuration
        # (feeder-thread device_put) — what production TPU runs use
        "device_prefetch": lambda: DevicePrefetcher(
            make_base(), depth=2, dtype=net._dtype, thread_put=True),
    }
    reports = {}
    for name, make_it in modes.items():
        it = make_it()
        # throwaway walk: thread spin-up / first-touch stays out of
        # the measured epochs; then median over n_trials epochs
        feed_stall_report(it, step_fn, pure_step_s=pure_step_s,
                          n_batches=n_batches)
        trials = [feed_stall_report(it, step_fn,
                                    pure_step_s=pure_step_s,
                                    n_batches=n_batches)
                  for _ in range(n_trials)]
        rep = sorted(trials,
                     key=lambda r: r["host_wait_pct"])[n_trials // 2]
        rep["host_wait_pct_spread"] = [
            t["host_wait_pct"] for t in trials]
        rep["ips"] = round(n_batches * batch / rep["total_s"], 1)
        reports[name] = rep
        print(json.dumps({"metric": f"input_pipeline_feed_{name}",
                          "unit": "images/sec", **rep}))

    # numeric identity: same seed + same batches, sync vs prefetch
    batches = make_base().batches()
    net_a, net_b = make_net(), make_net()
    for ds in batches[:3]:
        net_a.fit(ds)
    pf = DevicePrefetcher(make_base(), depth=2, dtype=net_b._dtype,
                          thread_put=True)
    n_fed = 0
    pf.reset()
    while pf.has_next() and n_fed < 3:
        net_b.fit(pf.next())
        n_fed += 1
    identical = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(net_a.params),
                        jax.tree_util.tree_leaves(net_b.params)))

    print(json.dumps({
        "metric": "input_pipeline_stall_pct",
        "value": reports["device_prefetch"]["host_wait_pct"],
        "unit": "%",
        "sync_pct": reports["sync"]["host_wait_pct"],
        "host_async_pct": reports["host_async"]["host_wait_pct"],
        "pure_step_ms": round(1e3 * pure_step_s, 2),
        "identical_to_sync": bool(identical),
    }))


if __name__ == "__main__":
    main()
