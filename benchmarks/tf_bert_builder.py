"""Real-dimension BERT-base GraphDef builder (BASELINE config #4:
"BERT-base via SameDiff TF import").

Builds the canonical encoder — token/position/segment embeddings, 12
transformer blocks (post-LN, GELU via erf, additive attention mask),
returning the full SEQUENCE tensor [b, s, H] — with the in-image TF,
then freezes it through ``convert_variables_to_constants_v2`` (the
same pipeline ``tests/test_tf_import.py::TestBertImport`` uses at toy
dimensions).  Reference: the TF BERT graphs the reference's
``TensorflowFrameworkImporter`` imports (SURVEY.md S6, BASELINE.md
config #4).

Shared by the real-dim conformance test and the imported-model MLM
benchmark so both exercise the IDENTICAL graph bytes.
"""
from __future__ import annotations

import numpy as np

# canonical BERT-base dimensions
BERT_BASE = dict(vocab=30522, hidden=768, heads=12, layers=12,
                 intermediate=3072)


def build_frozen_bert(seq: int, batch: int, *, vocab=30522, hidden=768,
                      heads=12, layers=12, intermediate=None, seed=0):
    """Returns (graphdef_bytes, run_tf) — ``run_tf(ids, seg, mask)``
    evaluates the frozen graph in TF for ground truth."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import \
        convert_variables_to_constants_v2

    intermediate = intermediate or hidden * 4
    hd = hidden // heads
    rs = np.random.RandomState(seed)

    def w(*shape, scale=0.02):
        return tf.Variable((rs.randn(*shape) * scale)
                           .astype(np.float32))

    p = {"tok": w(vocab, hidden), "pos": w(seq, hidden),
         "seg": w(2, hidden)}
    for i in range(layers):
        for nm in ("q", "k", "v", "o"):
            p[f"l{i}_{nm}w"] = w(hidden, hidden)
            p[f"l{i}_{nm}b"] = tf.Variable(np.zeros(hidden, np.float32))
        p[f"l{i}_ffw1"] = w(hidden, intermediate)
        p[f"l{i}_ffb1"] = tf.Variable(np.zeros(intermediate, np.float32))
        p[f"l{i}_ffw2"] = w(intermediate, hidden)
        p[f"l{i}_ffb2"] = tf.Variable(np.zeros(hidden, np.float32))
        for ln in ("ln1", "ln2"):
            p[f"l{i}_{ln}g"] = tf.Variable(np.ones(hidden, np.float32))
            p[f"l{i}_{ln}b"] = tf.Variable(np.zeros(hidden, np.float32))

    def layer_norm(x, g, b):
        mu = tf.reduce_mean(x, axis=-1, keepdims=True)
        var = tf.reduce_mean(tf.math.squared_difference(x, mu),
                             axis=-1, keepdims=True)
        return (x - mu) * tf.math.rsqrt(var + 1e-12) * g + b

    def f(ids, seg, mask):
        x = (tf.gather(p["tok"], ids) + p["pos"][None]
             + tf.gather(p["seg"], seg))
        neg = (1.0 - tf.cast(mask, tf.float32)) * -1e9
        neg = neg[:, None, None, :]
        for i in range(layers):
            def proj(nm, t):
                y = tf.matmul(t, p[f"l{i}_{nm}w"]) + p[f"l{i}_{nm}b"]
                s = tf.shape(y)
                y = tf.reshape(y, tf.stack([s[0], s[1], heads, hd]))
                return tf.transpose(y, [0, 2, 1, 3])

            q, k, v = proj("q", x), proj("k", x), proj("v", x)
            scores = tf.matmul(q, k, transpose_b=True) \
                / np.float32(np.sqrt(hd))
            probs = tf.nn.softmax(scores + neg, axis=-1)
            ctxv = tf.transpose(tf.matmul(probs, v), [0, 2, 1, 3])
            s = tf.shape(ctxv)
            ctxv = tf.reshape(ctxv, tf.stack([s[0], s[1], hidden]))
            att = tf.matmul(ctxv, p[f"l{i}_ow"]) + p[f"l{i}_ob"]
            x = layer_norm(x + att, p[f"l{i}_ln1g"], p[f"l{i}_ln1b"])
            h = tf.matmul(x, p[f"l{i}_ffw1"]) + p[f"l{i}_ffb1"]
            h = 0.5 * h * (1.0 + tf.math.erf(
                h / np.float32(np.sqrt(2.0))))
            h = tf.matmul(h, p[f"l{i}_ffw2"]) + p[f"l{i}_ffb2"]
            x = layer_norm(x + h, p[f"l{i}_ln2g"], p[f"l{i}_ln2b"])
        return x                                   # [b, s, hidden]

    spec = [tf.TensorSpec((batch, seq), tf.int32) for _ in range(3)]
    cf = tf.function(f).get_concrete_function(*spec)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def().SerializeToString()

    def run_tf(ids, seg, mask):
        res = frozen(tf.constant(ids), tf.constant(seg),
                     tf.constant(mask))
        if isinstance(res, (list, tuple)):
            res = res[0]
        return np.asarray(res)

    return gd, run_tf


def import_and_attach_mlm(gd_bytes, batch, seq, *, vocab, hidden,
                          updater=None, dtype=None,
                          max_predictions=None, optimize=None):
    """Import the frozen encoder, promote every frozen weight to a
    trainable VARIABLE, and attach a weight-tied MLM objective:
    logits = seq_out @ tok_embedding^T, sparse softmax xent over the
    positions whose label >= 0 (-1 = unmasked, ignored).  Returns
    (sd, loss_name).  ``dtype`` (e.g. ``"bfloat16"``) casts the
    promoted weights so the whole imported program runs in that
    compute dtype — master-weight semantics are NOT preserved; it is
    the honest 'imported graph, bf16 math' configuration.

    ``max_predictions=k`` gathers k positions per sequence (the
    ``mlm_positions`` [b, k] placeholder) before the decode matmul —
    the same gathered head the native ``models/bert.py`` uses, so the
    imported-vs-native comparison is FLOP-matched; labels are then
    [b, k].  ``None`` decodes every position (labels [b, seq])."""
    import numpy as _np

    from deeplearning4j_tpu.autodiff.samediff import VariableType
    from deeplearning4j_tpu.modelimport.tensorflow import \
        TensorflowFrameworkImporter

    shapes = {"ids": (batch, seq), "seg": (batch, seq),
              "mask": (batch, seq)}
    sd = TensorflowFrameworkImporter.run_import(gd_bytes, shapes,
                                                optimize=optimize)
    wnames = [n for n, v in sd.vars.items()
              if v.var_type == VariableType.CONSTANT
              and ("ReadVariableOp" in n or n.endswith("/resource"))]
    values = None
    if dtype is not None:
        values = {n: _np.asarray(sd.vars[n].get_arr()).astype(dtype)
                  for n in wnames}
        # weights alone are not enough: every f32 graph CONSTANT
        # (mask -1e9, LN eps, 1/sqrt(hd), ...) would upcast the
        # activations right back to f32 — cast them all, so the whole
        # imported program computes in `dtype`
        for n, v in sd.vars.items():
            if (v.var_type == VariableType.CONSTANT
                    and n not in wnames):
                arr = sd._arrays.get(n)
                if arr is not None and arr.dtype == _np.float32:
                    import jax.numpy as _jnp
                    sd._arrays[n] = _jnp.asarray(arr, dtype)
                    v.dtype = sd._arrays[n].dtype
        sd._exec_cache.clear()
    sd.convert_to_variables(wnames, values)
    out = sorted(n for n in sd.vars if n.startswith("Identity"))[0]
    tok = [n for n in wnames if sd.vars[n].shape == (vocab, hidden)]
    if len(tok) != 1:
        raise RuntimeError(f"expected one (vocab, hidden) weight, "
                           f"found {tok}")
    seq_out = sd.vars[out]
    if max_predictions is not None:
        positions = sd.placeholder("mlm_positions",
                                   shape=(batch, max_predictions))
        seq_out = sd._op("gather", [seq_out, positions],
                         {"axis": 1, "batch_dims": 1})
    logits = sd._op("matmul", [seq_out, sd.vars[tok[0]]],
                    {"transpose_b": True})
    labels = sd.placeholder(
        "mlm_labels",
        shape=(batch, seq if max_predictions is None
               else max_predictions))
    zero = sd.constant("mlm_zero", np.asarray(0, np.int32))
    safe = sd._op("maximum", [labels, zero])
    xent = sd._op("sparse_softmax_cross_entropy", [safe, logits],
                  {"reduction": "none"})
    valid = sd._op("cast", [sd._op("gte", [labels, zero])],
                   {"dtype": "float32"})
    if dtype is not None:
        xent = sd._op("cast", [xent], {"dtype": "float32"})
    num = sd._op("reduce_sum", [sd._op("mul", [xent, valid])],
                 {"axis": None})
    den = sd._op("maximum", [
        sd._op("reduce_sum", [valid], {"axis": None}),
        sd.constant("mlm_one", np.asarray(1.0, np.float32))])
    sd._op("div", [num, den]).rename("mlm_loss")
    sd.set_loss_variables(["mlm_loss"])
    if updater is not None:
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        sd.set_training_config(
            TrainingConfig.Builder().updater(updater).build())
    return sd, "mlm_loss"
