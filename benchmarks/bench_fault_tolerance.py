"""Benchmark: fault-tolerance costs (ISSUE 11).

Measures the two latencies the elastic/preemption-tolerant machinery
must keep small:

- **snapshot stall**: the time a checkpoint blocks the step loop.
  The synchronous path (``asynchronous=False``) does the device->host
  copy, serialization AND the zip write inline; the async path with
  deferred snapshots (``DL4J_TPU_ASYNC_SNAPSHOT``, default on) forks
  donation-safe on-device copies and moves everything else onto the
  checkpoint worker — the acceptance bar is the deferred stall <= 20%
  of the synchronous one at the same cadence.  The eager-copy async
  stall (device->host copy inline, write on the worker) is reported as
  an informational third series;
- **resume latency**: ``load_checkpoint`` wall time from a warm page
  cache — the fixed cost every auto-resume pays.

CPU-proxy subprocess on the virtual 8-device mesh like the other legs;
ratios are the claim, absolute times are smoke numbers.

Prints ONE JSON line:
  {"metric": "fault_tolerance", "sync_stall_mean_seconds": ...,
   "async_stall_mean_seconds": ..., "async_to_sync_stall_ratio": ...,
   "resume_latency_seconds": ..., ...}
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

SAVES = 8


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=512, n_out=1024,
                              activation=Activation.RELU))
            .layer(DenseLayer(n_out=1024, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(512))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.RandomState(0)
    x = rng.randn(n, 512).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return DataSet(x, y)


def _stalls(net, ds, base_dir, mode: str):
    """Per-save step-loop stall: time ONLY _save (what runs on the
    step path); any worker flush/join happens outside the timed
    region.  mode: 'sync' (fully synchronous write), 'eager' (async
    write, inline device->host copy), 'defer' (async write, on-device
    fork only)."""
    from deeplearning4j_tpu.utils import CheckpointListener
    d = os.path.join(base_dir, mode)
    lis = CheckpointListener(d, asynchronous=(mode != "sync"),
                             keep_last=2,
                             defer_snapshot=(mode == "defer"))
    samples = []
    for _ in range(SAVES):
        net.fit(ds)                 # mutate so every snapshot is fresh
        jax.block_until_ready(net.params)
        t0 = time.perf_counter()
        lis._save(net)
        samples.append(time.perf_counter() - t0)
        lis.flush()                 # drain the worker between samples
    return samples


def main():
    from deeplearning4j_tpu.common.telemetry import MetricsRegistry
    from deeplearning4j_tpu.utils import CheckpointListener

    MetricsRegistry.get().set_enabled(False)
    base = tempfile.mkdtemp(prefix="bench_ft_")
    try:
        net = _net()
        ds = _data()
        net.fit(ds)                           # compile once up front
        jax.block_until_ready(net.params)
        n_params = sum(int(np.prod(a.shape)) for a in
                       jax.tree_util.tree_leaves(net.params)
                       if hasattr(a, "shape"))

        sync = _stalls(net, ds, base, "sync")
        eager = _stalls(net, ds, base, "eager")
        async_ = _stalls(net, ds, base, "defer")

        # resume latency: newest checkpoint -> live model (warm cache)
        trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            CheckpointListener.load_checkpoint(
                os.path.join(base, "defer"))
            trials.append(time.perf_counter() - t0)
        resume_s = sorted(trials)[1]

        sync_mean = float(np.mean(sync))
        async_mean = float(np.mean(async_))
        out = {
            "metric": "fault_tolerance",
            "unit": "s",
            "model_params": n_params,
            "saves_per_mode": SAVES,
            "sync_stall_mean_seconds": round(sync_mean, 6),
            "sync_stall_p99_seconds": round(float(max(sync)), 6),
            "eager_copy_stall_mean_seconds": round(
                float(np.mean(eager)), 6),
            "async_stall_mean_seconds": round(async_mean, 6),
            "async_stall_p99_seconds": round(float(max(async_)), 6),
            "async_to_sync_stall_ratio": round(
                async_mean / max(sync_mean, 1e-9), 4),
            "resume_latency_seconds": round(resume_s, 5),
            # ISSUE 11 acceptance: deferred snapshot stall <= 20% of
            # the synchronous path at the same cadence
            "async_stall_fifth_of_sync": bool(
                async_mean * 5 <= sync_mean),
        }
        print(json.dumps(out))
    finally:
        shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
