"""Microbench: telemetry spine overhead.

Two questions the ISSUE's acceptance bar asks:

1. raw primitive cost — ns per ``Counter.inc`` / ``Histogram.observe``
   / ``span()`` with telemetry ON and OFF (OFF must be a bare
   attribute check);
2. end-to-end — step time of a tiny CPU ``fit()`` loop with the gate
   on vs off; the delta must stay under 1% (at real accelerator step
   times — milliseconds — the margin is orders larger).

Prints ONE JSON line:
  {"metric": "telemetry_overhead", "counter_inc_ns_on": ...,
   "fit_overhead_pct": ...}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ns_per_op(fn, n: int = 100_000) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n * 1e9


def _fit_seconds(net, ds, iters: int) -> float:
    import jax
    t0 = time.perf_counter()
    for _ in range(iters):
        net.fit(ds)
    jax.block_until_ready(net.params)
    return time.perf_counter() - t0


def main():
    from deeplearning4j_tpu.common import telemetry

    reg = telemetry.MetricsRegistry.get()
    c = telemetry.counter("dl4j_bench_counter_total", "microbench")
    h = telemetry.histogram("dl4j_bench_hist_seconds", "microbench")

    out = {"metric": "telemetry_overhead", "unit": "ns/op"}
    for on in (True, False):
        reg.set_enabled(on)
        sfx = "on" if on else "off"
        out[f"counter_inc_ns_{sfx}"] = round(
            _ns_per_op(lambda: c.inc(model="bench")), 1)
        out[f"hist_observe_ns_{sfx}"] = round(
            _ns_per_op(lambda: h.observe(0.001, model="bench")), 1)

        def spanop():
            with telemetry.span("bench"):
                pass
        out[f"span_ns_{sfx}"] = round(_ns_per_op(spanop, 20_000), 1)
        telemetry._trace_buffer.clear()

    # tiny fit() loop, telemetry on vs off (median of 3 passes each,
    # interleaved so drift hits both arms equally)
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    rng = np.random.RandomState(0)
    x = rng.randn(32, 16).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 32)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3))
         .list()
         .layer(DenseLayer(n_out=32, activation=Activation.RELU))
         .layer(OutputLayer(n_out=4, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(16)).build())).init()
    ds = DataSet(x, y)
    net.fit(ds)                      # compile outside the clock
    iters = 200
    on_times, off_times = [], []
    for _ in range(6):               # interleaved, min-of-N: machine
        reg.set_enabled(True)        # load noise at the ~700us step
        on_times.append(_fit_seconds(net, ds, iters))   # scale dwarfs
        reg.set_enabled(False)       # the ~5us true cost, so only the
        off_times.append(_fit_seconds(net, ds, iters))  # floors compare
    telemetry._trace_buffer.clear()
    reg.set_enabled(True)
    on_s, off_s = min(on_times), min(off_times)
    out["fit_step_us_on"] = round(on_s / iters * 1e6, 1)
    out["fit_step_us_off"] = round(off_s / iters * 1e6, 1)
    out["fit_overhead_pct_measured"] = round(
        (on_s - off_s) / off_s * 100, 2)
    # the reliable number: deterministic per-step record cost (one
    # step_span + one RetraceGuard counter inc) over the measured step
    # time — immune to the load noise the e2e delta is buried in
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with telemetry.step_span("bench"):
            pass
    span_cost = (time.perf_counter() - t0) / n
    telemetry._trace_buffer.clear()
    per_step_cost = span_cost + out["counter_inc_ns_on"] / 1e9
    out["fit_overhead_pct_analytic"] = round(
        per_step_cost / (on_s / iters) * 100, 2)

    # diagnostics leg: the PR-7 layer rides the same <1% budget.
    # (a) per-op cost of one flight-recorder record (the only
    # per-step diagnostics work on a clean run: counter reads, one
    # dict, ring append — HBM sampled every Nth);
    # (b) e2e fit() with the recorder on vs off, same interleaved
    # min-of-N protocol as above;
    # (c) the analytic ratio the acceptance bar reads.
    from deeplearning4j_tpu.common import diagnostics
    rec = diagnostics.FlightRecorder.get()
    rec.enabled = True
    loss = 0.5
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        rec.record(net, "bench", i, loss)
    record_cost = (time.perf_counter() - t0) / n
    out["flightrec_record_ns"] = round(record_cost * 1e9, 1)
    rec_on, rec_off = [], []
    for _ in range(6):
        rec.enabled = True
        rec_on.append(_fit_seconds(net, ds, iters))
        rec.enabled = False
        rec_off.append(_fit_seconds(net, ds, iters))
    rec.enabled = True
    telemetry._trace_buffer.clear()
    d_on, d_off = min(rec_on), min(rec_off)
    out["diag_fit_step_us_on"] = round(d_on / iters * 1e6, 1)
    out["diag_fit_step_us_off"] = round(d_off / iters * 1e6, 1)
    out["diag_overhead_pct_measured"] = round(
        (d_on - d_off) / d_off * 100, 2)
    out["diag_overhead_pct_analytic"] = round(
        record_cost / (d_on / iters) * 100, 2)

    # scaling-observatory leg (PR 9): the stepstats layer rides the
    # same <1% budget. (a) per-op cost of one breakdown close (the
    # only per-step observatory work on a clean run: accumulator swap,
    # phase dict, ring append, one histogram observe); (b) e2e fit()
    # with the collector on vs off; (c) the analytic ratio.
    from deeplearning4j_tpu.common import stepstats
    ss = stepstats.collector()
    ss.set_enabled(True)
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        ss.close_step("bench", i, 0.001)
    close_cost = (time.perf_counter() - t0) / n
    out["stepstats_close_ns"] = round(close_cost * 1e9, 1)
    ss_on, ss_off = [], []
    for _ in range(6):
        ss.set_enabled(True)
        ss_on.append(_fit_seconds(net, ds, iters))
        ss.set_enabled(False)
        ss_off.append(_fit_seconds(net, ds, iters))
    ss.set_enabled(True)
    telemetry._trace_buffer.clear()
    s_on, s_off = min(ss_on), min(ss_off)
    out["stepstats_fit_step_us_on"] = round(s_on / iters * 1e6, 1)
    out["stepstats_fit_step_us_off"] = round(s_off / iters * 1e6, 1)
    out["stepstats_overhead_pct_measured"] = round(
        (s_on - s_off) / s_off * 100, 2)
    out["stepstats_overhead_pct_analytic"] = round(
        close_cost / (s_on / iters) * 100, 2)

    # layer-attribution leg: the layerprof named-scope annotations ride
    # the same <1% budget. The gate is TRACE-time only — a disabled
    # scope is a no-op object, an enabled one costs one
    # jax.named_scope during the single trace — so steady-state steps
    # run the same compiled artifact. Two identical nets built under
    # gate on/off, interleaved min-of-N like the legs above.
    from deeplearning4j_tpu.common import layerprof
    from deeplearning4j_tpu.common.environment import Environment

    def _mk_net():
        return MultiLayerNetwork(
            (NeuralNetConfiguration.Builder().seed(1)
             .updater(Adam(1e-3)).list()
             .layer(DenseLayer(n_out=32, activation=Activation.RELU))
             .layer(OutputLayer(n_out=4, activation=Activation.SOFTMAX,
                                loss_function=LossFunction.MCXENT))
             .set_input_type(InputType.feed_forward(16))
             .build())).init()

    envx = Environment.get().extra
    envx["layerprof"] = True
    net_on = _mk_net()
    net_on.fit(ds)                   # trace with scopes on
    envx["layerprof"] = False
    net_off = _mk_net()
    net_off.fit(ds)                  # trace with scopes off
    envx.pop("layerprof", None)
    lp_on, lp_off = [], []
    for _ in range(6):
        lp_on.append(_fit_seconds(net_on, ds, iters))
        lp_off.append(_fit_seconds(net_off, ds, iters))
    telemetry._trace_buffer.clear()
    l_on, l_off = min(lp_on), min(lp_off)
    out["layerprof_fit_step_us_on"] = round(l_on / iters * 1e6, 1)
    out["layerprof_fit_step_us_off"] = round(l_off / iters * 1e6, 1)
    out["layerprof_overhead_pct_measured"] = round(
        (l_on - l_off) / l_off * 100, 2)
    # per-step cost of the only possibly-hot layerprof call (scope()
    # enter/exit outside a trace) over the measured step time; steady
    # state executes zero of these, so this is an upper bound
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with layerprof.scope("bench"):
            pass
    scope_cost = (time.perf_counter() - t0) / n
    out["layerprof_scope_ns"] = round(scope_cost * 1e9, 1)
    out["layerprof_overhead_pct_analytic"] = round(
        scope_cost / (l_on / iters) * 100, 2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
