"""End-to-end input-pipeline benchmark (SURVEY.md V3 / call stack 3.1
"async prefetch"): host batches -> AsyncDataSetIterator (native-queue
feeder thread) -> uint8 host->device transfer -> device-side
normalize -> jitted train step, double-buffered by dispatching step N
while batch N+1 transfers.

Prints TWO JSON lines:
  resnet50_train_throughput_e2e      — the full host path, this rig
  input_pipeline_overhead_pct        — e2e vs device-resident on the
                                       same backend (the pipeline cost
                                       with the link taken out of the
                                       equation on CPU; on the axon
                                       rig the tunnel IS the number —
                                       see BENCH_notes_r02.md)

TPU-first design note: pixels cross the link as uint8 (4x less wire
traffic than f32) and are cast/normalized ON DEVICE (the reference's
ImagePreProcessingScaler runs host-side). The normalize is a small
eagerly-dispatched device op ahead of the jitted step — it costs one
f32 copy of the batch in HBM, negligible next to the transfer it
quarters; fusing it into the step proper is a possible further step.
"""
from __future__ import annotations

import json
import os
import sys
import time

if "--pp" in sys.argv and "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the model-parallel leg wants a multi-device mesh; on a CPU-only
    # host virtualize 8 devices BEFORE jax initializes its backend
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


class _SyntheticU8Images:
    """Host-side producer standing in for the datavec image-reader ETL
    (decode+augment happen in the feeder thread at this rate or
    better; the pipeline cost being measured is queue + transfer)."""

    def __init__(self, batch, hw, n_batches, seed=0):
        rng = np.random.RandomState(seed)
        # a small pool re-indexed per batch: realistic unique-batch
        # traffic without burning bench time in the host RNG
        self._pool = rng.randint(0, 255,
                                 (4 * batch, hw, hw, 3), np.uint8)
        self._labels = np.eye(1000, dtype=np.float32)[
            rng.randint(0, 1000, 4 * batch)]
        self._batch = batch
        self._n = n_batches
        self._i = 0

    def reset(self):
        self._i = 0

    def has_next(self):
        return self._i < self._n

    def next(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        i = self._i
        self._i += 1
        sl = slice((i % 4) * self._batch, (i % 4 + 1) * self._batch)
        return DataSet(self._pool[sl], self._labels[sl])


def _make_net(hw, on_tpu):
    from deeplearning4j_tpu.models.zoo import ResNet50
    if on_tpu:
        return ResNet50(num_classes=1000, height=hw, width=hw,
                        compute_dtype="bfloat16").init()
    # CPU proxy: small stages so compute is fast enough that the
    # pipeline (not the model) is what the comparison can see
    return ResNet50(num_classes=1000, height=hw, width=hw,
                    compute_dtype="bfloat16",
                    STAGES=((1, 8), (1, 16))).init()


def _consume(net, make_producer, batch):
    """Warm the compile+transfer path on one producer, then time a
    fresh producer through the async queue: u8 across the link,
    normalize on device (eager dispatch — one extra f32 batch copy,
    overlapped with the async step). Shared by the synthetic and
    real-decode legs so the two metrics stay comparable."""
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import \
        AsyncDataSetIterator

    def fit_u8(ds):
        x = jax.device_put(ds.features)
        y = jax.device_put(ds.labels)
        xf = (x.astype(jnp.float32) / 255.0 - 0.5) * 2.0
        net.fit(DataSet(xf, y))

    warm = make_producer(2)
    warm.reset()
    while warm.has_next():
        fit_u8(warm.next())          # compile + warm transfer path
    float(net.score())
    if hasattr(warm, "close"):
        warm.close()                 # don't keep its feeder pool alive

    producer = make_producer(None)
    it = AsyncDataSetIterator(producer, queue_size=4)
    it.reset()
    t0 = time.perf_counter()
    n = 0
    while it.has_next():
        fit_u8(it.next())            # async dispatch: step N runs
        n += 1                       # while batch N+1 transfers
    assert np.isfinite(float(net.score()))   # sync the whole chain
    dt = time.perf_counter() - t0
    if hasattr(producer, "close"):
        producer.close()
    return n * batch / dt


def run(batch, hw, n_batches, device_resident_ips, on_tpu):
    net = _make_net(hw, on_tpu)
    e2e = _consume(
        net, lambda n: _SyntheticU8Images(batch, hw, n or n_batches),
        batch)
    overhead = 100.0 * (1.0 - e2e / device_resident_ips)
    return e2e, overhead


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    batch, hw, n_batches = (256, 224, 8) if on_tpu else (16, 64, 12)

    # device-resident reference on THIS backend (same protocol as
    # bench.py, short run)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    net = _make_net(hw, on_tpu)
    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(
        rng.randn(batch, hw, hw, 3).astype(np.float32)))
    y = jax.device_put(jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]))
    ds = DataSet(x, y)
    # per-fit dispatch, matching the e2e path's dispatch style — the
    # overhead metric then isolates the PIPELINE (queue + transfer +
    # normalize), not fit() vs fit_steps() dispatch differences
    # (fit_steps' fused loop is separately benchmarked in bench.py)
    net.fit(ds)
    float(net.score())
    t0 = time.perf_counter()
    for _ in range(n_batches):
        net.fit(ds)
    assert np.isfinite(float(net.score()))
    resident = n_batches * batch / (time.perf_counter() - t0)

    e2e, overhead = run(batch, hw, n_batches, resident, on_tpu)
    suffix = "" if on_tpu else "_cpu_proxy"
    print(json.dumps({
        "metric": f"resnet50_train_throughput_e2e{suffix}",
        "value": round(e2e, 2), "unit": "images/sec/chip",
        "device_resident": round(resident, 2)}))
    print(json.dumps({
        "metric": f"input_pipeline_overhead_pct{suffix}",
        "value": round(overhead, 1), "unit": "%"}))




# -- real-decode leg (r4 verdict Weak #3: the host ETL rate was
# asserted by a comment, never measured) ------------------------------------
def write_jpeg_corpus(dirpath, n=512, size=256, quality=85):
    """N synthetic JPEGs across 4 class dirs (Pillow encode). Content
    is band-limited noise over a gradient — compresses like a photo,
    so decode cost is realistic rather than best-case."""
    from PIL import Image
    rng = np.random.RandomState(0)
    base_y, base_x = np.mgrid[0:size, 0:size]
    paths = []
    for i in range(n):
        cls = i % 4
        d = os.path.join(dirpath, f"class_{cls}")
        os.makedirs(d, exist_ok=True)
        smooth = (base_y * (0.3 + 0.1 * cls) + base_x * 0.4) % 256
        noise = rng.randint(-40, 40, (size, size, 1))
        img = np.clip(smooth[:, :, None] + noise +
                      rng.randint(0, 60, 3)[None, None, :],
                      0, 255).astype(np.uint8)
        p = os.path.join(d, f"img_{i}.jpg")
        Image.fromarray(img).save(p, quality=quality)
        paths.append(p)
    return paths


def _decode_one(path, reader):
    img = reader.loader.load(path)
    if reader.image_transform is not None:
        img = reader.image_transform.transform(img)
    return img


def measure_host_decode_rate(paths, hw=224, threads=1, seconds=6.0):
    """Sustained ImageRecordReader-equivalent decode+augment rate
    (img/s) on this host with a pool of ``threads`` feeder workers —
    Pillow releases the GIL during JPEG decode, so threads scale."""
    import concurrent.futures
    import itertools

    from deeplearning4j_tpu.datavec.image import (FlipImageTransform,
                                                  ImageRecordReader)
    # the loader decodes + resizes to hw x hw; flip is the augment
    # stage — the SAME pipeline _JpegBatchProducer feeds e2e, so the
    # two metrics describe one path
    reader = ImageRecordReader(
        hw, hw, 3, image_transform=FlipImageTransform(mode=1))
    cyc = itertools.cycle(paths)
    done = 0
    t0 = time.perf_counter()
    if threads == 1:
        while time.perf_counter() - t0 < seconds:
            _decode_one(next(cyc), reader)
            done += 1
        dt = time.perf_counter() - t0
    else:
        with concurrent.futures.ThreadPoolExecutor(threads) as ex:
            pending = {ex.submit(_decode_one, next(cyc), reader)
                       for _ in range(threads * 2)}
            while time.perf_counter() - t0 < seconds:
                finished, pending = concurrent.futures.wait(
                    pending,
                    return_when=concurrent.futures.FIRST_COMPLETED)
                for f in finished:
                    f.result()
                    done += 1
                    pending.add(ex.submit(_decode_one, next(cyc),
                                          reader))
            # stop the clock BEFORE pool shutdown joins the ~2*threads
            # uncounted in-flight decodes (they would bias the
            # by-threads curve downward at large pools)
            dt = time.perf_counter() - t0
    return done / dt


class _JpegBatchProducer:
    """DataSetIterator over REAL decoded JPEG batches: a feeder pool
    decodes+augments ahead of consumption (the datavec image path,
    measured rather than vouched for)."""

    def __init__(self, paths, batch, hw, n_batches, threads):
        self._paths = paths
        self._batch = batch
        self._hw = hw
        self._n = n_batches
        self._threads = threads
        self._labels = np.eye(1000, dtype=np.float32)[
            np.random.RandomState(1).randint(0, 1000,
                                             batch * n_batches)]
        self.reset()

    def reset(self):
        self._i = 0

    def has_next(self):
        return self._i < self._n

    def next(self):
        import concurrent.futures
        import itertools

        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datavec.image import (
            FlipImageTransform, ImageRecordReader)
        if not hasattr(self, "_reader"):
            self._reader = ImageRecordReader(
                self._hw, self._hw, 3,
                image_transform=FlipImageTransform(mode=1))
            self._pool = concurrent.futures.ThreadPoolExecutor(
                self._threads)
            self._cyc = itertools.cycle(self._paths)
        i = self._i
        self._i += 1
        imgs = list(self._pool.map(
            lambda p: _decode_one(p, self._reader),
            [next(self._cyc) for _ in range(self._batch)]))
        x = np.stack(imgs).astype(np.uint8)
        y = self._labels[i * self._batch:(i + 1) * self._batch]
        return DataSet(x, y)

    def close(self):
        if hasattr(self, "_pool"):
            self._pool.shutdown(wait=False)


def main_real_decode(threads):
    """--real-decode: host decode rates at several pool sizes, then
    the e2e leg with REAL decoded JPEGs feeding the async queue."""
    import tempfile
    on_tpu = jax.devices()[0].platform == "tpu"
    batch, hw, n_batches = (256, 224, 8) if on_tpu else (16, 64, 6)
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        paths = write_jpeg_corpus(d, n=512 if on_tpu else 64)
        enc_s = time.perf_counter() - t0
        rates = {}
        for th in (1, 4, 8, 16, 32):
            rates[th] = round(measure_host_decode_rate(
                paths, hw=hw, threads=th,
                seconds=6.0 if on_tpu else 2.0), 1)
        print(json.dumps({
            "metric": "image_etl_host_decode_rate",
            "unit": "images/sec/host",
            "jpeg_encode_setup_s": round(enc_s, 1),
            "by_threads": rates}))

        # e2e: real decode in the feeder, device consumes (same
        # _consume loop as the synthetic leg — one comparable path)
        net = _make_net(hw, on_tpu)
        e2e = _consume(
            net, lambda n: _JpegBatchProducer(
                paths, batch, hw, n or n_batches, threads), batch)
        suffix = "" if on_tpu else "_cpu_proxy"
        print(json.dumps({
            "metric": f"resnet50_train_throughput_e2e_realdecode{suffix}",
            "value": round(e2e, 2), "unit": "images/sec/chip",
            "feeder_threads": threads}))


# -- pipeline-parallel leg (ISSUE 18: the promoted real fit path) -----------
def main_pp():
    """--pp: pipeline-parallel training bench on the ``pipe`` mesh
    axis — analytic bubble-vs-n_micro sweep, gpipe-vs-1f1b peak
    activation residency (schedule counts + measured bytes), and
    measured pp2 / pp2xdp2 legs through ``ParallelWrapper``'s real fit
    path. Emits ONE ``{"metric": "pipeline"}`` JSON line for bench.py
    to fold in (check_bench_regression.py holds bubble_fraction,
    residency and stage idle down, throughput up)."""
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    from deeplearning4j_tpu.parallel import (ParallelWrapper,
                                             bubble_fraction,
                                             build_schedule,
                                             peak_residency, zero)

    def net():
        conf = (NeuralNetConfiguration.Builder().seed(42)
                .updater(Sgd(0.1)).weight_init(WeightInit.XAVIER).list()
                .layer(DenseLayer(n_in=64, n_out=128,
                                  activation=Activation.TANH))
                .layer(DenseLayer(n_out=128, activation=Activation.TANH))
                .layer(DenseLayer(n_out=128, activation=Activation.TANH))
                .layer(OutputLayer(n_out=10,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(64)).build())
        return MultiLayerNetwork(conf).init()

    def data(seed):
        r = np.random.RandomState(seed)
        x = r.randn(64, 64).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[r.randint(0, 10, 64)]
        return DataSet(x, y)

    def run_leg(workers, schedule, n_batches=6):
        m = net()
        pw = (ParallelWrapper.Builder(m).workers(workers)
              .pipeline_stages(2).pipeline_schedule(schedule)
              .update_exchange("dense").build())
        pw.fit_batch(data(0))            # compile + place
        t0 = time.perf_counter()
        for i in range(n_batches):
            pw.fit_batch(data(i + 1))
        dt = time.perf_counter() - t0
        rep = dict(pw._pipeline.last_report)
        pw.shutdown()
        leg = {
            "step_seconds": round(dt / n_batches, 4),
            "throughput_rows_per_s": round(64 * n_batches / dt, 1),
            "bubble_fraction": rep["bubble_fraction"],
            "stage_idle_ms": [round(1e3 * s, 2)
                              for s in rep["stage_idle_seconds"]],
            "peak_residency_microbatches":
                rep["peak_residency_microbatches"],
            "peak_residency_bytes": rep["peak_residency_bytes"],
            "pipe_wire_bytes": rep["pipe_wire_bytes"],
            "n_micro": rep["n_micro"],
        }
        return leg, m, rep

    rec = {"metric": "pipeline",
           "bubble_fraction_sweep_s2": {
               f"m{m}": round(bubble_fraction(2, m), 4)
               for m in (2, 4, 8, 16)}}

    leg_1f1b, m1, rep_1f1b = run_leg(1, "1f1b")
    leg_gpipe, _, rep_gpipe = run_leg(1, "gpipe")
    leg_2d, _, _ = run_leg(2, "1f1b")
    rec["pp2_1f1b"] = leg_1f1b
    rec["pp2_gpipe"] = leg_gpipe
    rec["pp2_dp2_1f1b"] = leg_2d
    rec["residency"] = {
        "gpipe_peak_microbatches": peak_residency(
            build_schedule(2, 8, "gpipe"), 2),
        "1f1b_peak_microbatches": peak_residency(
            build_schedule(2, 8, "1f1b"), 2),
        "gpipe_peak_bytes": rep_gpipe["peak_residency_bytes"],
        "1f1b_peak_bytes": rep_1f1b["peak_residency_bytes"],
    }
    rec["update_exchange"] = zero.exchange_report(
        m1.params, 2, "dense", pipe_shards=2,
        stage_param_bytes=rep_1f1b["stage_param_bytes"])
    print(json.dumps(rec))


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--real-decode", action="store_true",
                    help="measure the REAL JPEG decode+augment host "
                         "path instead of the synthetic producer")
    ap.add_argument("--threads", type=int, default=16,
                    help="feeder pool size for the real-decode e2e leg")
    ap.add_argument("--pp", action="store_true",
                    help="pipeline-parallel leg: bubble/residency/"
                         "throughput over a pipe-axis mesh")
    a = ap.parse_args()
    if a.pp:
        main_pp()
    elif a.real_decode:
        main_real_decode(a.threads)
    else:
        main()
