"""End-to-end input-pipeline benchmark (SURVEY.md V3 / call stack 3.1
"async prefetch"): host batches -> AsyncDataSetIterator (native-queue
feeder thread) -> uint8 host->device transfer -> device-side
normalize -> jitted train step, double-buffered by dispatching step N
while batch N+1 transfers.

Prints TWO JSON lines:
  resnet50_train_throughput_e2e      — the full host path, this rig
  input_pipeline_overhead_pct        — e2e vs device-resident on the
                                       same backend (the pipeline cost
                                       with the link taken out of the
                                       equation on CPU; on the axon
                                       rig the tunnel IS the number —
                                       see BENCH_notes_r02.md)

TPU-first design note: pixels cross the link as uint8 (4x less wire
traffic than f32) and are cast/normalized ON DEVICE (the reference's
ImagePreProcessingScaler runs host-side). The normalize is a small
eagerly-dispatched device op ahead of the jitted step — it costs one
f32 copy of the batch in HBM, negligible next to the transfer it
quarters; fusing it into the step proper is a possible further step.
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


class _SyntheticU8Images:
    """Host-side producer standing in for the datavec image-reader ETL
    (decode+augment happen in the feeder thread at this rate or
    better; the pipeline cost being measured is queue + transfer)."""

    def __init__(self, batch, hw, n_batches, seed=0):
        rng = np.random.RandomState(seed)
        # a small pool re-indexed per batch: realistic unique-batch
        # traffic without burning bench time in the host RNG
        self._pool = rng.randint(0, 255,
                                 (4 * batch, hw, hw, 3), np.uint8)
        self._labels = np.eye(1000, dtype=np.float32)[
            rng.randint(0, 1000, 4 * batch)]
        self._batch = batch
        self._n = n_batches
        self._i = 0

    def reset(self):
        self._i = 0

    def has_next(self):
        return self._i < self._n

    def next(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        i = self._i
        self._i += 1
        sl = slice((i % 4) * self._batch, (i % 4 + 1) * self._batch)
        return DataSet(self._pool[sl], self._labels[sl])


def _make_net(hw, on_tpu):
    from deeplearning4j_tpu.models.zoo import ResNet50
    if on_tpu:
        return ResNet50(num_classes=1000, height=hw, width=hw,
                        compute_dtype="bfloat16").init()
    # CPU proxy: small stages so compute is fast enough that the
    # pipeline (not the model) is what the comparison can see
    return ResNet50(num_classes=1000, height=hw, width=hw,
                    compute_dtype="bfloat16",
                    STAGES=((1, 8), (1, 16))).init()


def run(batch, hw, n_batches, device_resident_ips, on_tpu):
    from deeplearning4j_tpu.datasets.iterators import \
        AsyncDataSetIterator

    net = _make_net(hw, on_tpu)

    def fit_u8(ds):
        # u8 across the link; normalize on device (eager dispatch —
        # one extra f32 batch copy, overlapped with the async step)
        x = jax.device_put(ds.features)
        y = jax.device_put(ds.labels)
        xf = (x.astype(jnp.float32) / 255.0 - 0.5) * 2.0
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.fit(DataSet(xf, y))

    warm = _SyntheticU8Images(batch, hw, 2)
    warm.reset()
    while warm.has_next():
        fit_u8(warm.next())          # compile + warm transfer path
    float(net.score())

    it = AsyncDataSetIterator(_SyntheticU8Images(batch, hw, n_batches),
                              queue_size=4)
    it.reset()
    t0 = time.perf_counter()
    n = 0
    while it.has_next():
        fit_u8(it.next())            # async dispatch: step N runs
        n += 1                       # while batch N+1 transfers
    assert np.isfinite(float(net.score()))   # sync the whole chain
    dt = time.perf_counter() - t0
    e2e = n * batch / dt
    overhead = 100.0 * (1.0 - e2e / device_resident_ips)
    return e2e, overhead


def main():
    on_tpu = jax.devices()[0].platform == "tpu"
    batch, hw, n_batches = (256, 224, 8) if on_tpu else (16, 64, 12)

    # device-resident reference on THIS backend (same protocol as
    # bench.py, short run)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    net = _make_net(hw, on_tpu)
    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(
        rng.randn(batch, hw, hw, 3).astype(np.float32)))
    y = jax.device_put(jnp.asarray(
        np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]))
    ds = DataSet(x, y)
    # per-fit dispatch, matching the e2e path's dispatch style — the
    # overhead metric then isolates the PIPELINE (queue + transfer +
    # normalize), not fit() vs fit_steps() dispatch differences
    # (fit_steps' fused loop is separately benchmarked in bench.py)
    net.fit(ds)
    float(net.score())
    t0 = time.perf_counter()
    for _ in range(n_batches):
        net.fit(ds)
    assert np.isfinite(float(net.score()))
    resident = n_batches * batch / (time.perf_counter() - t0)

    e2e, overhead = run(batch, hw, n_batches, resident, on_tpu)
    suffix = "" if on_tpu else "_cpu_proxy"
    print(json.dumps({
        "metric": f"resnet50_train_throughput_e2e{suffix}",
        "value": round(e2e, 2), "unit": "images/sec/chip",
        "device_resident": round(resident, 2)}))
    print(json.dumps({
        "metric": f"input_pipeline_overhead_pct{suffix}",
        "value": round(overhead, 1), "unit": "%"}))


if __name__ == "__main__":
    main()
