"""Benchmark: full FSDP (ZeRO-3) vs ZeRO-1 sharded vs dense
(parallel.zero + the fsdp step tails).

ISSUE 10 acceptance: under fsdp, per-chip param + updater-state
residency must be <= 1/4 of the dense replicated footprint. Measured,
not estimated: after placement each jax.Array's ``addressable_shards``
say exactly how many bytes sit on chip 0 — replicated leaves put their
full size there, P(data) flats 1/N. We report that residency plus the
step wall time for dense / sharded / fsdp, and the fsdp step time
under gradient accumulation windows of 1/2/4.

Runs on the virtual 8-device CPU mesh (the same proxy the parallel
test suite uses), so the residency ratios are exact and the step-time
deltas are smoke numbers, not TPU claims.

Prints ONE JSON line:
  {"metric": "fsdp", "dense": {...}, "sharded": {...}, "fsdp": {...},
   "hbm_total_savings_ratio": N, "accumulation": {...}}
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-3))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=256, n_out=512,
                              activation=Activation.RELU))
            .layer(DenseLayer(n_out=512, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(256))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.RandomState(0)
    x = rng.randn(n, 256).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return DataSet(x, y)


def _bytes_on_chip0(tree) -> int:
    """Measured residency of ``tree`` on device 0 (replicated leaves
    count full size, P(data) flats 1/N)."""
    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        for sh in getattr(leaf, "addressable_shards", ()):
            if sh.device == dev0:
                total += sh.data.nbytes
    return total


def _time_steps(pw, ds, steps: int) -> float:
    """Median-of-3 wall time per fit_batch, compile excluded."""
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            pw.fit_batch(ds)
        jax.block_until_ready(pw.model.params)
        trials.append((time.perf_counter() - t0) / steps)
    return sorted(trials)[1]


def main():
    from deeplearning4j_tpu.common.telemetry import MetricsRegistry
    from deeplearning4j_tpu.parallel import ParallelWrapper

    MetricsRegistry.get().set_enabled(False)   # measure the step, not
    ds = _data()                               # the telemetry spine
    out = {"metric": "fsdp", "workers": 8,
           "updater": "Adam", "unit": "bytes|s"}

    for mode in ("dense", "sharded", "fsdp"):
        net = _net()
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange(mode).build()
        pw.fit_batch(ds)                       # place + compile
        jax.block_until_ready(net.params)
        step_s = _time_steps(pw, ds, steps=5)
        out[mode] = {
            "param_bytes_per_chip": _bytes_on_chip0(net.params),
            "updater_state_bytes_per_chip":
                _bytes_on_chip0(net.updater_states),
            "step_seconds": round(step_s, 5),
        }

    def _resident(mode):
        return (out[mode]["param_bytes_per_chip"] +
                out[mode]["updater_state_bytes_per_chip"])

    dense_b, fsdp_b = _resident("dense"), _resident("fsdp")
    out["hbm_total_savings_ratio"] = round(dense_b / max(fsdp_b, 1), 2)
    # the ISSUE 10 acceptance bar: fsdp param+state residency <= 1/4
    # of the dense replicated footprint (it is ~1/8 on this mesh)
    out["fsdp_resident_quarter_of_dense"] = bool(fsdp_b * 4 <= dense_b)

    # gradient accumulation on top of fsdp: per-micro-batch step time
    # for windows of 1/2/4 (backward-only micro steps gather params
    # but skip the update tail)
    accum = {}
    for k in (1, 2, 4):
        net = _net()
        pw = ParallelWrapper.Builder(net).workers(8) \
            .update_exchange("fsdp").accumulation_steps(k).build()
        for _ in range(k):                     # compile both step kinds
            pw.fit_batch(ds)
        jax.block_until_ready(net.params)
        accum[str(k)] = {"micro_step_seconds":
                         round(_time_steps(pw, ds, steps=2 * k), 5)}
    out["accumulation"] = accum
    print(json.dumps(out))


if __name__ == "__main__":
    main()
