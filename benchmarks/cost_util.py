"""Shared XLA cost-analysis helper for the benchmark scripts.

One home for the fragile coupling to the private ComputationGraph
train-step signature, so bench.py and benchmarks/profile_resnet.py
cannot drift apart. Byte accounting from XLA cost analysis is
accurate on TPU (it predicts the ResNet-50 step time at the HBM
roofline to ~1%); flops for dots inside fusions undercount, so treat
the returned flops as a floor (BENCH_notes_r02.md).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# TPU v5e single-chip peaks
V5E_BF16_PEAK_TFLOPS = 197.0
V5E_HBM_GBPS = 819.0


def graph_step_cost(net, x, y) -> tuple[float, float]:
    """(flops, bytes accessed) of one optimized ComputationGraph train
    step. ``net`` must be initialized with its train step built (one
    ``fit`` call suffices)."""
    ca = net._train_step.lower(
        net.params, net.states, net.updater_states,
        [jnp.asarray(x)], [jnp.asarray(y)], None, None,
        jnp.asarray(0), jax.random.PRNGKey(0)).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return ca.get("flops", 0.0), ca.get("bytes accessed", 0.0)
