"""BASELINE config #4 AS WRITTEN: "BERT-base via SameDiff TF import".

The r3 headline (105.9k tokens/s) measured the hand-built native
model; THIS benchmark measures the import path end-to-end: a
real-dimension BERT-base GraphDef frozen by the in-image TF, imported
through S6, every frozen weight promoted to a trainable VARIABLE, a
weight-tied MLM head attached, and the whole thing trained as ONE
jitted program on the chip.  Reported next to the native number in
BENCH_notes so the import-path tax is quantified (round-3 verdict
ask #1).

Prints ONE JSON line:
  {"metric": "bert_imported_mlm_train_throughput", ...}

Defaults reproduce the adopted headline (BENCH_notes_r04.md): true
bf16 full-constant cast, gathered-32 MLM head (FLOP-matched to the
native bench), batch 128 (the fori-protocol sweep's winner, matching
the native model's optimum), SameDiff.fit_steps fori-loop protocol —
170.2k tokens/s, 0.94x native same-batch.

Flags: --batch N --seq N --dtype bfloat16|float32 --steps N
       --max-predictions K   (gathered-K decode head; 0 = decode
                              every position, the full-decode leg)
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _frozen_graph_cached(seq, batch, cache_dir="/tmp/dl4j_tpu_bench"):
    """Freezing a 110M-param graph takes ~1 min of TF time; cache the
    bytes so repeated bench runs skip it.  The graph is deterministic
    (seeded), so the cache key is just the shape tuple."""
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"bert_base_{batch}x{seq}.pb")
    if os.path.exists(path):
        with open(path, "rb") as fh:
            return fh.read()
    from benchmarks.tf_bert_builder import build_frozen_bert
    gd, _ = build_frozen_bert(seq, batch)
    with open(path, "wb") as fh:
        fh.write(gd)
    return gd


def main(batch=128, seq=128, steps=48, dtype="bfloat16",
         max_predictions=32, remat_segments=0, fuse_attention=False):
    import jax

    from benchmarks.tf_bert_builder import (BERT_BASE,
                                            import_and_attach_mlm)
    from deeplearning4j_tpu.learning import Adam

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        batch, steps = 2, 2

    gd = _frozen_graph_cached(seq, batch)
    sd, _ = import_and_attach_mlm(
        gd, batch, seq, vocab=BERT_BASE["vocab"],
        hidden=BERT_BASE["hidden"], updater=Adam(1e-4),
        dtype=None if dtype == "float32" else dtype,
        max_predictions=max_predictions)
    if remat_segments:
        sd.set_remat_segments(remat_segments)
    fused = 0
    if fuse_attention:
        fused = sd.fuse_attention_patterns()

    rs = np.random.RandomState(0)
    ids = rs.randint(0, BERT_BASE["vocab"],
                     (batch, seq)).astype(np.int32)
    seg = np.zeros((batch, seq), np.int32)
    mask = np.ones((batch, seq), np.int32)
    b = {"ids": ids, "seg": seg, "mask": mask}
    if max_predictions is None:
        b["mlm_labels"] = np.where(
            rs.rand(batch, seq) < 0.15,
            rs.randint(0, BERT_BASE["vocab"], (batch, seq)),
            -1).astype(np.int32)
    else:
        # the native bench's shape: k gathered positions per sequence
        b["mlm_positions"] = np.stack(
            [rs.choice(seq, max_predictions, replace=False)
             for _ in range(batch)]).astype(np.int32)
        b["mlm_labels"] = rs.randint(
            0, BERT_BASE["vocab"],
            (batch, max_predictions)).astype(np.int32)

    # compile + warm (sd.fit builds the jitted step on first batch)
    hist = sd.fit([b], n_epochs=1, placeholders_fn=lambda x: x)
    first_loss = hist.final_loss()
    assert np.isfinite(first_loss)

    from benchmarks.timing import median_throughput

    sd.fit_steps(b, steps)  # compile the fori-loop program

    def run_once():
        # ONE fori-loop dispatch + one loss sync per trial (the
        # char-RNN protocol): per-step dispatch+sync through the axon
        # tunnel is a fixed tax the loop amortizes
        loss = sd.fit_steps(b, steps)
        assert np.isfinite(loss)

    stats = median_throughput(run_once, steps * batch * seq,
                              n_trials=5 if on_tpu else 3)
    # the timed steps must have TRAINED (same batch -> memorization);
    # a wiring bug that zeroes gradients times a lie otherwise
    last = sd.fit([b], n_epochs=1,
                  placeholders_fn=lambda x: x).final_loss()
    assert last < first_loss, (last, first_loss)
    line = {"metric": "bert_imported_mlm_train_throughput"
                      + ("" if on_tpu else "_cpu_proxy"),
            **stats,
            "unit": "tokens/sec/chip",
            "batch": batch, "seq": seq, "dtype": dtype,
            "mlm_head": ("full-decode" if max_predictions is None
                         else f"gathered-{max_predictions}"),
            "remat_segments": remat_segments,
            "fused_attention_sites": fused,
            "import_path": "TF GraphDef -> S6 -> one jitted program"}
    print(json.dumps(line))
    return line


if __name__ == "__main__":
    import inspect
    # single source of truth for defaults: main()'s signature
    d = {k: p.default
         for k, p in inspect.signature(main).parameters.items()}
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=d["batch"])
    ap.add_argument("--seq", type=int, default=d["seq"])
    ap.add_argument("--steps", type=int, default=d["steps"])
    ap.add_argument("--dtype", default=d["dtype"])
    ap.add_argument("--remat-segments", type=int,
                    default=d["remat_segments"],
                    help="sqrt(N)-checkpoint the imported op walk "
                         "in this many segments (the flat-graph "
                         "memory lever; 0 = off)")
    ap.add_argument("--fuse-attention", action="store_true",
                    help="run the importer's attention-pattern "
                         "fusion pass (sdpa_core) before training")
    ap.add_argument("--max-predictions", type=int,
                    default=d["max_predictions"],
                    help="gather this many positions per sequence "
                         "before the decode matmul (the native "
                         "bench's FLOP-matched head); 0 decodes "
                         "every position (the r4-early full-decode "
                         "leg)")
    a = ap.parse_args()
    main(batch=a.batch, seq=a.seq, steps=a.steps, dtype=a.dtype,
         max_predictions=a.max_predictions or None,
         remat_segments=a.remat_segments,
         fuse_attention=a.fuse_attention)
