"""BERT seq-512 MFU sweep: remat policy x batch under the fori-loop
protocol (VERDICT r4 weak #1 / next-round #1) — the same grid the
seq-128 leg got in round 4 (BENCH_notes_r04.md).

Runs every (remat, batch) leg of bench_bert.py at seq 512 /
max_predictions 76 in a FRESH subprocess (so an HBM OOM in one leg
cannot poison the next, and each leg gets a clean compile cache),
collects the JSON lines, and prints a markdown table.

Usage:  python benchmarks/sweep_bert512.py [--steps 60] [--out FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CONFIGS = [
    # (remat, batch) — mirror of the r4 seq-128 grid, seq-512 sized.
    # b256/none will likely OOM (29G-class activations); the sweep
    # records that as a data point rather than crashing.
    ("full", 32), ("full", 64), ("full", 128), ("full", 256),
    ("dots", 32), ("dots", 64), ("dots", 128),
    ("none", 32), ("none", 64), ("none", 128),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--out", default="benchmarks/sweep_bert512_results.jsonl")
    a = ap.parse_args()

    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(here)
    results = []
    with open(a.out, "a") as f:
        for remat, batch in CONFIGS:
            cmd = [sys.executable, os.path.join(here, "bench_bert.py"),
                   "--seq", "512", "--max-predictions", "76",
                   "--batch", str(batch), "--remat", remat,
                   "--steps", str(a.steps)]
            t0 = time.time()
            p = subprocess.run(cmd, capture_output=True, text=True,
                               cwd=root, timeout=1800)
            wall = round(time.time() - t0, 1)
            line = None
            for ln in p.stdout.splitlines():
                if ln.startswith("{"):
                    line = json.loads(ln)
            if line is None:
                err = (p.stderr or "")[-400:]
                oom = "Ran out of memory" in (p.stderr or "")
                line = {"error": "oom" if oom else "fail", "detail": err}
            line.update({"remat": remat, "batch": batch, "wall_s": wall})
            results.append(line)
            f.write(json.dumps(line) + "\n")
            f.flush()
            print(json.dumps(line), flush=True)

    print("\n| remat | batch | tokens/s | % bf16 peak |")
    print("|---|---|---|---|")
    for r in results:
        if "error" in r:
            print(f"| {r['remat']} | {r['batch']} | {r['error']} | — |")
        else:
            print(f"| {r['remat']} | {r['batch']} | "
                  f"{r['value']:,.0f} | {r.get('pct_bf16_peak', '—')} |")


if __name__ == "__main__":
    main()
