"""Long-context attention TRAIN-step A/B (round-3 verdict ask #3).

The r3 forward tuning gave the Pallas flash kernel 4.2x over its
untuned self at seq 8k — but training pays fwd+bwd, and the flash
BACKWARD is jax.vjp through the blockwise-attention reference
(parallel/sequence.py _flash_bwd), not a hand kernel.  This benchmark
measures what long-context TRAINING actually costs per step for:

  xla    — dense jnp attention (materializes [b,h,t,t] scores)
  flash  — Pallas forward + blockwise-autodiff backward (current)
  block  — blockwise_attention fwd+bwd (pure lax.scan, no Pallas)

Each leg times grad(loss) of one attention call at [b, h, t, d],
median of n_trials, synced on the loss scalar.  Prints ONE JSON line
per leg.  Use --seq for a single point, or --sweep for the committed
8192 / 16384 / 32768 ladder (one JSON summary line,
``{"metric": "longcontext"}``, that bench.py folds in) — the shapes
where the kernel-select auto rung (t_k >= 4096 + HBM headroom,
ops/attention_pallas.py) picks flash on its own.  Off-TPU the sweep
collapses to one seq-512 proxy point, but each entry still records
the analytic TPU-platform ladder decision for its nominal shape.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def xla_attention(q, k, v):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(
        jnp.asarray(d, q.dtype))
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def main(seq=8192, batch=1, heads=8, d=128, dtype="bfloat16",
         trials=5, steps=10, legs=("xla", "flash", "block")):
    from benchmarks.timing import median_throughput
    from deeplearning4j_tpu.parallel.sequence import (
        blockwise_attention, flash_attention)

    on_tpu = jax.devices()[0].platform == "tpu"
    if not on_tpu:
        seq, trials = 512, 2
    dt = jnp.dtype(dtype)
    rng = np.random.RandomState(0)
    shape = (batch, heads, seq, d)
    q = jax.device_put(jnp.asarray(
        rng.randn(*shape) * 0.1, dt))
    k = jax.device_put(jnp.asarray(rng.randn(*shape) * 0.1, dt))
    v = jax.device_put(jnp.asarray(rng.randn(*shape) * 0.1, dt))

    fns = {
        "xla": xla_attention,
        "flash": functools.partial(flash_attention, causal=False),
        "block": lambda q, k, v: blockwise_attention(q, k, v),
    }
    results = {}
    for leg in legs:
        fn = fns[leg]

        @jax.jit
        def train_step(q, k, v, fn=fn):
            def loss(q, k, v):
                o = fn(q, k, v)
                return jnp.sum(o.astype(jnp.float32) ** 2)

            l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                q, k, v)
            return l, grads

        try:
            l, g = train_step(q, k, v)          # compile
            jax.block_until_ready(g)
            assert np.isfinite(float(l))

            def run_once():
                # dispatch `steps` independent steps, sync ONCE on the
                # last loss: a per-step float() sync through the axon
                # tunnel costs ~200 ms and would swamp the kernel time
                l = None
                for _ in range(steps):
                    l, _g = train_step(q, k, v)
                assert np.isfinite(float(l))

            stats = median_throughput(run_once, steps,
                                      n_trials=trials)
            step_ms = 1000.0 / stats["value"]
            line = {"metric": f"longcontext_attn_train_step_{leg}",
                    "value": round(step_ms, 2), "unit": "ms/step",
                    "seq": seq, "batch": batch, "heads": heads,
                    "d": d, "dtype": dtype,
                    "min_ms": round(1000.0 / stats["max"], 2),
                    "max_ms": round(1000.0 / stats["min"], 2),
                    "steps_per_trial": steps,
                    "n_trials": stats["n_trials"]}
        except Exception as e:                   # OOM legs are data too
            line = {"metric": f"longcontext_attn_train_step_{leg}",
                    "value": None, "seq": seq,
                    "error": f"{type(e).__name__}: {str(e)[:160]}"}
        results[leg] = line
        print(json.dumps(line))
    return results


def sweep(seqs=(8192, 16384, 32768), batch=1, heads=8, d=128,
          dtype="bfloat16", trials=5, steps=10,
          legs=("xla", "flash")):
    """The committed long-context ladder: one measured point per seq
    (xla-OOM legs are data too), each stamped with the decision the
    kernel-select auto rung would take for that shape ON TPU — the
    evidence that the t_k >= 4096 heuristic fires exactly where the
    measured win is."""
    import jax

    from deeplearning4j_tpu.ops.attention_pallas import \
        select_attention_backend

    on_tpu = jax.devices()[0].platform == "tpu"
    out = {"metric": "longcontext", "batch": batch, "heads": heads,
           "d": d, "dtype": dtype, "proxy": not on_tpu, "sweep": []}
    for seq in (seqs if on_tpu else seqs[:1]):
        res = main(seq=seq, batch=batch, heads=heads, d=d,
                   dtype=dtype, trials=trials, steps=steps, legs=legs)
        entry = {"seq": seq if on_tpu else 512,
                 "legs": {leg: {k: v for k, v in line.items()
                               if k != "metric"}
                          for leg, line in res.items()}}
        qk = (batch, heads, seq, d)
        backend, reason = select_attention_backend(
            qk, qk, platform="tpu", override=None,
            use_env_override=False)
        entry["auto_backend_on_tpu"] = backend
        entry["auto_reason"] = reason
        out["sweep"].append(entry)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--d", type=int, default=128)
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--legs", default="xla,flash,block")
    ap.add_argument("--sweep", action="store_true",
                    help="run the 8192/16384/32768 ladder and print "
                         "the one-line summary bench.py folds in")
    a = ap.parse_args()
    if a.sweep:
        sweep(batch=a.batch, heads=a.heads, d=a.d, trials=a.trials,
              steps=a.steps,
              legs=tuple(l for l in a.legs.split(",")
                         if l != "block"))
    else:
        main(seq=a.seq, batch=a.batch, heads=a.heads, d=a.d,
             trials=a.trials, steps=a.steps,
             legs=tuple(a.legs.split(",")))
