"""Serving-path benchmark: request latency + throughput.

Measures what a serving operator tunes:

- **batch_window_ms sweep** — the latency/throughput knob of the
  dynamic batcher. Concurrent clients drive a warmed
  ``ServingBatcher``; per-request submit→result latency is reported
  as p50/p95/p99 alongside throughput.
- **warm vs cold first request** — the stall shape-bucketed warmup
  exists to remove: first request into a cold batcher pays the XLA
  compile; into a warmed one it pays only queue + compute.

Prints ONE JSON line (``bench.py`` folds it into its ``serving``
block):

  {"metric": "serving_latency", "windows": {...},
   "first_request_ms": {"warm": ..., "cold": ...}, ...}

Run: JAX_PLATFORMS=cpu python benchmarks/bench_serving.py
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

N_CLIENTS = 4
REQS_PER_CLIENT = 40
WINDOWS_MS = (0.5, 2.0, 8.0)
BUCKETS = (8, 32)


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    return MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=16, activation=Activation.RELU))
         .layer(OutputLayer(n_out=3,
                            loss_function=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
         .set_input_type(InputType.feed_forward(8)).build())).init()


def _batcher(net, window_ms: float):
    from deeplearning4j_tpu.serving.batcher import ServingBatcher
    return ServingBatcher(net, BUCKETS, name="bench",
                          batch_window_ms=window_ms)


def _drive(batcher, reqs) -> list:
    """N client threads, each timing submit→result per request."""
    lats, lock = [], threading.Lock()

    def client(seed):
        rng = np.random.RandomState(seed)
        mine = []
        for _ in range(reqs):
            x = rng.randn(1, 8).astype(np.float32)
            t0 = time.perf_counter()
            batcher.submit(x).result(timeout=60)
            mine.append(time.perf_counter() - t0)
        with lock:
            lats.extend(mine)

    threads = [threading.Thread(target=client, args=(s,))
               for s in range(N_CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lats


def main():
    from deeplearning4j_tpu.common import telemetry

    net = _net()
    line = {"metric": "serving_latency",
            "clients": N_CLIENTS, "reqs_per_client": REQS_PER_CLIENT,
            "buckets": list(BUCKETS)}

    # warm vs cold first request (the warmup payoff)
    cold = _batcher(net, 2.0)
    t0 = time.perf_counter()
    cold.submit(np.zeros((1, 8), np.float32)).result(timeout=120)
    cold_ms = (time.perf_counter() - t0) * 1e3
    cold.shutdown()
    warm = _batcher(net, 2.0)
    warm.warmup((8,))
    t0 = time.perf_counter()
    warm.submit(np.zeros((1, 8), np.float32)).result(timeout=120)
    warm_ms = (time.perf_counter() - t0) * 1e3
    warm.shutdown()
    line["first_request_ms"] = {"cold": round(cold_ms, 2),
                                "warm": round(warm_ms, 2)}

    # batch-window sweep on warmed batchers
    windows = {}
    for w in WINDOWS_MS:
        b = _batcher(net, w)
        b.warmup((8,))
        t0 = time.perf_counter()
        lats = _drive(b, REQS_PER_CLIENT)
        wall = time.perf_counter() - t0
        b.shutdown()
        ms = np.asarray(lats) * 1e3
        windows[str(w)] = {
            "p50_ms": round(float(np.percentile(ms, 50)), 2),
            "p95_ms": round(float(np.percentile(ms, 95)), 2),
            "p99_ms": round(float(np.percentile(ms, 99)), 2),
            "throughput_rps": round(len(lats) / wall, 1),
        }
    line["windows"] = windows
    # the live registry's own quantile estimate (bucket-resolution)
    # for the aggregate queue stage — what /metrics scrapers see
    h = telemetry.histogram("dl4j_serving_latency_seconds")
    line["queue_p95_ms_registry"] = round(
        h.quantile(0.95, model="bench", stage="queue") * 1e3, 2)
    # memory headroom next to the latency percentiles: the dl4j_hbm_*
    # gauges a /metrics scrape of the serving endpoint reports (empty
    # on backends without allocator stats, e.g. this CPU proxy)
    try:
        from deeplearning4j_tpu.common import diagnostics
        devs = diagnostics.update_hbm_gauges()
        if devs:
            live = sum(d["bytes_in_use"] for d in devs)
            limit = sum(d["bytes_limit"] for d in devs)
            line["memory"] = {
                "hbm_live_bytes": live,
                "hbm_peak_bytes": sum(d["peak_bytes_in_use"]
                                      for d in devs),
                "hbm_limit_bytes": limit,
                "headroom_pct": (round(100 * (1 - live / limit), 1)
                                 if limit else None),
            }
    except Exception as e:
        print(f"memory-headroom leg failed: {e!r}", file=sys.stderr)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
