"""Serving-path benchmark: continuous vs fixed-window batching,
SLO-adaptive admission, the zero-copy serialization tax, and
sharded-model residency.

Measures the ISSUE-15 claims the way an operator would check them:

- **Open-loop Poisson A/B** — ``flush_policy="continuous"`` vs the
  fixed ``batch_window_ms`` seed at EQUAL offered load. Arrivals are
  pre-scheduled from an exponential inter-arrival draw and latency is
  measured from the *scheduled* arrival (open loop: a slow server
  cannot slow the clients down and hide its own queueing). Reports
  p50/p95/p99 plus goodput (completions inside the SLO per second).
- **Admission static vs SLO-adaptive** — saturating closed-loop
  clients against a deliberately slow model: the static budget admits
  everything and lets queueing blow the SLO; the adaptive budget
  sheds early so admitted requests stay inside it.
- **Serialization tax** — per-request JSON encode/decode vs the
  zero-copy ``.npy`` codec (``npy_view`` / ``npy_header``).
- **Sharded residency** — dense vs ``mode="fsdp"`` per-chip resident
  parameter bytes on the virtual 8-device mesh, with the bitwise
  output check.
- **warm vs cold first request** — the shape-bucketed warmup payoff.
- **Serving observatory overhead** — the same HTTP predict loop with
  per-request tracing ON (default) vs OFF: ``trace_overhead_pct``
  must stay ≤ 1% at p50, the cost of leaving the observatory on in
  production.

Bench honesty: every latency figure here is device-side. On the axon
rig the client additionally pays the fixed ~100 ms tunnel RTT
(STATUS.md), so the line stamps ``meta.transport_rtt_ms`` and reports
``*_rtt_adj_ms`` next to each raw percentile — what a client of THIS
rig would see, kept separate so rig latency never masquerades as
serving latency. ``meta.proxy`` marks CPU-proxy rounds.

Prints ONE JSON line (``bench.py`` folds it into its ``serving``
block):

  {"metric": "serving_latency", "policies": {...}, "admission": {...},
   "serialization": {...}, "residency": {...},
   "first_request_ms": {...}, "meta": {...}}

Run: JAX_PLATFORMS=cpu python benchmarks/bench_serving.py
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("DL4J_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

BUCKETS = (8, 32)
#: open-loop offered load and sample size per policy
RATE_RPS = 250.0
N_REQS = 300
SLO_MS = 25.0
#: the fixed-window seed's knob (the PR-3 default)
WINDOW_MS = 2.0
#: the axon tunnel's fixed round trip (STATUS.md) — added to raw
#: percentiles as *_rtt_adj_ms when the round runs on that rig
AXON_RTT_MS = 100.0


def _net():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    return MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(7).updater(Adam(1e-2))
         .list()
         .layer(DenseLayer(n_out=16, activation=Activation.RELU))
         .layer(OutputLayer(n_out=4,
                            loss_function=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
         .set_input_type(InputType.feed_forward(8)).build())).init()


def _batcher(net, policy: str, mesh=None, mode: str = "dense"):
    from deeplearning4j_tpu.serving.batcher import ServingBatcher
    return ServingBatcher(net, BUCKETS, mesh, name="bench",
                          batch_window_ms=WINDOW_MS,
                          flush_policy=policy, mode=mode)


def _pcts(ms: np.ndarray, rtt_ms: float) -> dict:
    out = {}
    for q in (50, 95, 99):
        raw = float(np.percentile(ms, q))
        out[f"p{q}_ms"] = round(raw, 2)
        out[f"p{q}_rtt_adj_ms"] = round(raw + rtt_ms, 2)
    return out


def _open_loop(batcher, rate_rps: float, n: int, seed: int) -> dict:
    """Submit ``n`` requests on a pre-scheduled Poisson arrival clock;
    latency counts from the SCHEDULED arrival, so dispatcher or server
    lag shows up as latency instead of silently thinning the load."""
    rng = np.random.RandomState(seed)
    sched = np.cumsum(rng.exponential(1.0 / rate_rps, size=n))
    xs = [rng.randn(1, 8).astype(np.float32) for _ in range(n)]
    lats, lock = [], threading.Lock()
    pairs = []
    t0 = time.perf_counter()
    for i in range(n):
        target = t0 + sched[i]
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        fut = batcher.submit(xs[i])

        def done(f, t=target):
            with lock:
                lats.append(time.perf_counter() - t)
        fut.add_done_callback(done)
        pairs.append(fut)
    for f in pairs:
        f.result(timeout=120)
    wall = time.perf_counter() - t0
    ms = np.asarray(sorted(lats)) * 1e3
    good = int(np.sum(ms <= SLO_MS))
    return {"offered_rps": round(rate_rps, 1),
            "goodput_rps": round(good / wall, 1),
            "slo_ms": SLO_MS,
            "in_slo_pct": round(100.0 * good / n, 1),
            **_pcts(ms, _rtt_ms())}


def _rtt_ms() -> float:
    return AXON_RTT_MS if jax.default_backend() != "cpu" else 0.0


def _policy_leg(line: dict):
    """Continuous vs fixed-window at equal offered load — the
    tentpole A/B. Occupancy comes from the policy-labelled serving
    histogram the flushes feed."""
    from deeplearning4j_tpu.common import telemetry
    policies = {}
    for policy in ("window", "continuous"):
        net = _net()
        b = _batcher(net, policy)
        b.warmup((8,))
        policies[policy] = _open_loop(b, RATE_RPS, N_REQS,
                                      seed=17)
        b.shutdown()
        h = telemetry.histogram("dl4j_serving_batch_occupancy")
        cnt = h.count_of(model="bench", policy=policy)
        if cnt:
            policies[policy]["occupancy_mean"] = round(
                h.sum_of(model="bench", policy=policy) / cnt, 3)
    line["policies"] = policies


class _SlowModel:
    """A generic model whose forward costs ~1 ms/row — enough work
    that saturating clients actually queue on the CPU proxy."""

    def output(self, x):
        x = np.asarray(x)
        time.sleep(0.001 * x.shape[0])
        return x[:, :1]


def _admission_leg(line: dict):
    """Static budget vs SLO-adaptive budget under the same saturating
    closed loop: goodput counts only completions INSIDE the SLO, so
    admitting everything and queueing past the SLO loses."""
    from deeplearning4j_tpu.serving.admission import (
        AdmissionController, ShedError)
    slo_ms = 40.0
    out = {}
    for label, slo in (("static", None), ("adaptive", slo_ms)):
        adm = AdmissionController(max_queue=48, latency_slo_ms=slo,
                                  adapt_window=16)
        b = _batcher(_SlowModel(), "continuous")
        lats, shed = [], [0]
        lock = threading.Lock()

        def client(n_reqs, adm=adm, b=b, lats=lats, shed=shed):
            x = np.zeros((1, 8), np.float32)
            for _ in range(n_reqs):
                t0 = time.perf_counter()
                try:
                    with adm.track("bench"):
                        b.submit(x).result(timeout=30)
                except ShedError:
                    with lock:
                        shed[0] += 1
                    continue
                dt = time.perf_counter() - t0
                adm.observe_total("bench", dt)
                with lock:
                    lats.append(dt)

        threads = [threading.Thread(target=client, args=(12,))
                   for _ in range(24)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        b.shutdown()
        ms = np.asarray(lats) * 1e3
        good = int(np.sum(ms <= slo_ms))
        out[label] = {
            "slo_ms": slo_ms,
            "completed": len(lats),
            "shed": shed[0],
            "p95_ms": round(float(np.percentile(ms, 95)), 2),
            "goodput_rps": round(good / wall, 1),
            "final_budget": adm.budget("bench"),
        }
    line["admission"] = out


def _serialization_leg(line: dict):
    """The per-request tax the zero-copy ``.npy`` path removes: JSON
    encode+decode of a request-sized tensor vs npy_header + a
    frombuffer view."""
    from deeplearning4j_tpu.common.httputil import npy_header, npy_view
    x = np.random.RandomState(3).randn(32, 256).astype(np.float32)
    reps = 50

    t0 = time.perf_counter()
    for _ in range(reps):
        body = json.dumps({"inputs": x.tolist()}).encode()
        np.asarray(json.loads(body.decode())["inputs"],
                   dtype=np.float32)
    json_ms = (time.perf_counter() - t0) / reps * 1e3

    raw = npy_header(x) + memoryview(x).cast("B").tobytes()
    t0 = time.perf_counter()
    for _ in range(reps):
        parts = [npy_header(x), memoryview(x)]      # response side
        sum(memoryview(p).cast("B").nbytes for p in parts)
        npy_view(raw)                               # request side
    npy_ms = (time.perf_counter() - t0) / reps * 1e3

    line["serialization"] = {
        "tensor_bytes": int(x.nbytes),
        "json_roundtrip_ms": round(json_ms, 3),
        "npy_roundtrip_ms": round(npy_ms, 3),
        "speedup": round(json_ms / max(npy_ms, 1e-9), 1),
    }


def _observatory_leg(line: dict):
    """The serving-observatory overhead claim: the same HTTP predict
    loop with request tracing ON (the default — trace ids, phase
    spans, exemplars, flight-recorder records) vs forced OFF
    (``DL4J_TPU_REQUEST_TRACE=0`` equivalent, via the in-process
    override). Tracing is supposed to be default-on in production, so
    the p50 overhead must stay ≤ 1%."""
    import urllib.request

    from deeplearning4j_tpu.common import tracectx
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import InferenceServer

    registry = ModelRegistry(default_buckets=BUCKETS)
    registry.register("bench-obs", _net(), warmup_shape=(8,))
    srv = InferenceServer(registry).start(0)
    body = json.dumps(
        {"inputs": np.zeros((1, 8), np.float32).tolist()}).encode()
    url = f"{srv.url}/v1/models/bench-obs:predict"
    n = 150

    def loop() -> np.ndarray:
        lats = []
        for _ in range(n):
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req) as resp:
                resp.read()
            lats.append(time.perf_counter() - t0)
        return np.asarray(lats) * 1e3

    try:
        # warm both paths before timing (HTTP keep-alive, caches)
        tracectx.set_enabled(True)
        loop()
        p50_on = float(np.percentile(loop(), 50))
        tracectx.set_enabled(False)
        loop()
        p50_off = float(np.percentile(loop(), 50))
    finally:
        tracectx.set_enabled(None)
        srv.stop(drain=False)
        registry.shutdown()
    line["serving_observatory"] = {
        "n": n,
        "p50_on_ms": round(p50_on, 3),
        "p50_off_ms": round(p50_off, 3),
        "trace_overhead_pct": round(
            100.0 * (p50_on - p50_off) / max(p50_off, 1e-9), 2),
    }


def _residency_leg(line: dict):
    """Dense vs fsdp per-chip resident parameter bytes, plus the
    bitwise output check that makes the savings claim honest."""
    if len(jax.devices()) < 8:
        print("residency leg skipped: needs the 8-device mesh",
              file=sys.stderr)
        return
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.serving.residency import \
        resident_param_bytes
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    x = np.random.RandomState(5).randn(4, 8).astype(np.float32)

    net = _net()
    ref = np.asarray(net.output(x))
    dense_bytes = resident_param_bytes(net.params)

    b = _batcher(net, "continuous", mesh=mesh, mode="fsdp")
    b.warmup((8,))
    out = np.asarray(b.submit(x).result(timeout=120))
    fsdp_bytes = resident_param_bytes(b.params)
    b.shutdown()

    line["residency"] = {
        "dense_bytes_per_chip": int(dense_bytes),
        "fsdp_bytes_per_chip": int(fsdp_bytes),
        "savings_ratio": round(dense_bytes / max(fsdp_bytes, 1), 2),
        "bitwise_equal": bool(np.array_equal(out, ref)),
    }


def main():
    from deeplearning4j_tpu.common import telemetry

    on_proxy = jax.default_backend() == "cpu"
    line = {"metric": "serving_latency",
            "buckets": list(BUCKETS),
            "meta": {"proxy": on_proxy,
                     "transport_rtt_ms": _rtt_ms()}}

    # warm vs cold first request (the warmup payoff)
    net = _net()
    cold = _batcher(net, "continuous")
    t0 = time.perf_counter()
    cold.submit(np.zeros((1, 8), np.float32)).result(timeout=120)
    cold_ms = (time.perf_counter() - t0) * 1e3
    cold.shutdown()
    warm = _batcher(net, "continuous")
    warm.warmup((8,))
    t0 = time.perf_counter()
    warm.submit(np.zeros((1, 8), np.float32)).result(timeout=120)
    warm_ms = (time.perf_counter() - t0) * 1e3
    warm.shutdown()
    line["first_request_ms"] = {"cold": round(cold_ms, 2),
                                "warm": round(warm_ms, 2)}

    _policy_leg(line)
    _admission_leg(line)
    _serialization_leg(line)
    try:
        _observatory_leg(line)
    except Exception as e:
        print(f"observatory leg failed: {e!r}", file=sys.stderr)
    try:
        _residency_leg(line)
    except Exception as e:
        print(f"residency leg failed: {e!r}", file=sys.stderr)

    # the live registry's own quantile estimate (bucket-resolution)
    # for the aggregate queue stage — what /metrics scrapers see
    h = telemetry.histogram("dl4j_serving_latency_seconds")
    line["queue_p95_ms_registry"] = round(
        h.quantile(0.95, model="bench", stage="queue") * 1e3, 2)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
