#!/bin/bash
cd /root/repo
echo "=== inference serving v2 ($(date)) ==="
python benchmarks/bench_inference.py 2>/dev/null | grep "^{"
echo "=== real-decode ETL v2 (fast loader) ($(date)) ==="
python benchmarks/bench_pipeline.py --real-decode --threads 4 2>/dev/null | grep "^{"
echo "=== queue2 done ($(date)) ==="
