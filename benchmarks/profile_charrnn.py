"""char-RNN roofline probe (r4 verdict Weak #2: the 1.88M chars/s
headline had no ceiling statement). Prints ONE JSON line with the
XLA-measured per-step FLOPs/bytes of the 2x512 GravesLSTM train step,
the analytic decomposition, and the implied compute/HBM/launch bounds
to set the measured step time against.

The analysis (BENCH_notes_r05.md carries the prose): a small-batch
LSTM step is bound by re-reading the [512, 2048] recurrent weights
from HBM every timestep of the scan — the arithmetic intensity of the
[b, 512] @ [512, 2048] recurrent matmul at b=64 is far below the MXU
ridge, which is exactly why the reference grew a CudnnLSTMHelper
(SURVEY.md D9). The lever with headroom is batch (amortizes the
weight read); seq length only adds more serial steps.
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from benchmarks.cost_util import (V5E_BF16_PEAK_TFLOPS,  # noqa: E402
                                  V5E_HBM_GBPS)


def main(batch=64, seq_len=64, hidden=512, vocab=80):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.layers_recurrent import GravesLSTM

    conf = (NeuralNetConfiguration.Builder()
            .seed(12345).updater(Adam(5e-3))
            .compute_data_type("bfloat16")
            .list()
            .layer(GravesLSTM(n_out=hidden,
                              activation=Activation.TANH))
            .layer(GravesLSTM(n_out=hidden,
                              activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=vocab,
                                  loss_function=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(vocab, seq_len))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    eye = np.eye(vocab, dtype=np.float32)
    ids = rng.randint(0, vocab, (batch, seq_len + 1))
    x = jnp.asarray(eye[ids[:, :-1]])
    y = jnp.asarray(eye[ids[:, 1:]])
    net.fit(type("DS", (), {"features": x, "labels": y,
                            "features_mask": None,
                            "labels_mask": None})())

    ca = net._train_step.lower(
        net.params, net.states, net.updater_states, x, y, None, None,
        jnp.asarray(0), jax.random.PRNGKey(0)).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    bytes_accessed = float(ca.get("bytes accessed", 0.0))

    # analytic matmul decomposition (multiply-add = 2 FLOPs)
    H, V, B, T = hidden, vocab, batch, seq_len
    in_proj1 = 2 * B * T * V * 4 * H
    in_proj2 = 2 * B * T * H * 4 * H
    recur = 2 * B * H * 4 * H * T          # per layer, T serial steps
    head = 2 * B * T * H * V
    fwd = in_proj1 + in_proj2 + 2 * recur + head
    train_flops = 3 * fwd

    # the HBM floor: recurrent weights re-read per timestep (bf16)
    rw_bytes = 2 * (H * 4 * H) * 2          # two layers
    rw_traffic_fwd = rw_bytes * T
    rw_traffic_train = 3 * rw_traffic_fwd   # fwd + 2 bwd passes

    out = {
        "metric": "charrnn_step_roofline",
        "config": f"GravesLSTM 2x{H}, b{B}, seq {T}, vocab {V}, bf16",
        "xla_flops_per_step": flops,
        "xla_bytes_per_step": bytes_accessed,
        "analytic_matmul_flops_per_step": train_flops,
        "rw_weight_retraffic_bytes_per_step": rw_traffic_train,
        "compute_floor_us": round(
            train_flops / (V5E_BF16_PEAK_TFLOPS * 1e12) * 1e6, 1),
        "hbm_floor_us_xla_bytes": round(
            bytes_accessed / (V5E_HBM_GBPS * 1e9) * 1e6, 1),
        "serial_matmul_chain": 2 * T,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
