"""Shared steady-state measurement protocol (BASELINE.md step 2;
round-2 verdict Weak #1/#2: single-run numbers disagree with their
notes by more than tunnel variance).

``median_throughput`` runs a warm, self-syncing closure N times and
reports the MEDIAN rate plus min/max, so the committed artifact is
robust to run-to-run jitter through the shared tunnel and matches
what the notes claim."""
from __future__ import annotations

import statistics
import time
from typing import Callable, Dict


def median_throughput(run_once: Callable[[], None], units_per_run,
                      n_trials: int = 5) -> Dict[str, float]:
    """``run_once`` must execute the full measured work AND sync on a
    computed scalar (not just block_until_ready).  Returns
    {"value": median units/s, "min": ..., "max": ..., "n_trials": N}.
    """
    rates = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        run_once()
        dt = time.perf_counter() - t0
        rates.append(units_per_run / dt)
    rates.sort()
    return {"value": round(statistics.median(rates), 2),
            "min": round(rates[0], 2),
            "max": round(rates[-1], 2),
            "n_trials": n_trials}


def feed_stall_report(iterator, step_fn, *, pure_step_s: float,
                      n_batches: int) -> Dict[str, float]:
    """Input-pipeline stall accounting for one feeding strategy.

    Walks ``iterator`` for ``n_batches``, calling ``step_fn(ds)`` (which
    must sync on the step's result) per batch, and attributes everything
    that is not pure device compute to the input pipeline:

        host_wait = total_wall − n_batches × pure_step_s

    where ``pure_step_s`` is the same step measured on a device-resident
    batch. This charges the H2D copy to the pipeline even when it hides
    inside the jit dispatch (the host-async case), so sync /
    host-async / device-prefetch feeding are comparable on one scale.

    Returns ``{"total_s", "fetch_s", "host_wait_pct", "n_batches"}``;
    ``fetch_s`` is the explicit ``next()`` wait alone (the part a plain
    timer would see)."""
    if hasattr(iterator, "reset"):
        iterator.reset()
    fetch_s = 0.0
    t_start = time.perf_counter()
    for _ in range(n_batches):
        t0 = time.perf_counter()
        ds = iterator.next()
        fetch_s += time.perf_counter() - t0
        step_fn(ds)
    total_s = time.perf_counter() - t_start
    host_wait = max(0.0, total_s - n_batches * pure_step_s)
    return {"total_s": round(total_s, 4),
            "fetch_s": round(fetch_s, 4),
            "host_wait_pct": round(100.0 * host_wait / total_s, 2)
            if total_s > 0 else 0.0,
            "n_batches": n_batches}
