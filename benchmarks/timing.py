"""Shared steady-state measurement protocol (BASELINE.md step 2;
round-2 verdict Weak #1/#2: single-run numbers disagree with their
notes by more than tunnel variance).

``median_throughput`` runs a warm, self-syncing closure N times and
reports the MEDIAN rate plus min/max, so the committed artifact is
robust to run-to-run jitter through the shared tunnel and matches
what the notes claim."""
from __future__ import annotations

import statistics
import time
from typing import Callable, Dict


def median_throughput(run_once: Callable[[], None], units_per_run,
                      n_trials: int = 5) -> Dict[str, float]:
    """``run_once`` must execute the full measured work AND sync on a
    computed scalar (not just block_until_ready).  Returns
    {"value": median units/s, "min": ..., "max": ..., "n_trials": N}.
    """
    rates = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        run_once()
        dt = time.perf_counter() - t0
        rates.append(units_per_run / dt)
    rates.sort()
    return {"value": round(statistics.median(rates), 2),
            "min": round(rates[0], 2),
            "max": round(rates[-1], 2),
            "n_trials": n_trials}
