"""Bench leg: per-layer attribution observatory on ResNet-50 + BERT.

For each model: static per-layer flops/bytes from the compiled HLO
(``common.layerprof``), a measured train-step wall time split into
per-layer fwd/bwd ms (``share_step_time`` off-TPU — ``time_source``
marks the proxy), and the kernel-select decision joined per layer.
Reports the top-k layers by time with pct_of_roof so a throughput
regression in BENCH_r*.json comes pre-attributed to a layer.

Prints ONE JSON line:
  {"metric": "layer_attribution",
   "resnet50": {"layers": [...], "reconcile_err_pct": ..., ...},
   "bert": {...}, "meta": {"proxy": ...}}
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TOP_K = 8


def _top_layers(report: dict, k: int = TOP_K) -> list:
    """Top-k report entries by measured (or estimated) time, flattened
    to the bench-line schema."""
    rows = []
    for name, ent in report["layers"].items():
        if name == "_unattributed":
            continue
        rows.append({
            "layer": name,
            **({"type": ent["type"]} if "type" in ent else {}),
            "fwd_ms": ent.get("fwd_ms"),
            "bwd_ms": ent.get("bwd_ms"),
            "flops": ent["flops"],
            "bytes": ent["bytes"],
            "bound": ent["bound"],
            "pct_of_roof": ent.get("pct_of_roof"),
            "kernel_decision": ent.get("kernel"),
        })
    rows.sort(key=lambda r: (r["fwd_ms"] or 0.0) + (r["bwd_ms"] or 0.0),
              reverse=True)
    return rows[:k]


def _summarize(report: dict, step_ms: float) -> dict:
    from deeplearning4j_tpu.common import layerprof
    return {
        "step_ms": round(step_ms, 3),
        "time_source": report["time_source"],
        "reconcile_err_pct": round(
            layerprof.reconcile_error_pct(report), 4),
        "coverage": report["coverage"],
        "raw_model": report["raw_model"],
        "layers": _top_layers(report),
    }


def _step_ms(fit_once, steps: int, trials: int = 3) -> float:
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        fit_once()
        best = min(best, (time.perf_counter() - t0) / steps)
    return best * 1e3


def _resnet50(on_tpu: bool) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.common import layerprof
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    batch = 32 if on_tpu else 4
    hw = 224 if on_tpu else 64
    net = ResNet50(num_classes=1000, height=hw, width=hw,
                   compute_dtype="bfloat16").init()
    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    ds = DataSet(jax.device_put(jnp.asarray(x)),
                 jax.device_put(jnp.asarray(y)))
    report = net.layer_report(x, y)

    steps = 5 if on_tpu else 2

    def fit_once():
        net.fit_steps(ds, steps)
        jax.block_until_ready(net.params)

    fit_once()                        # compile outside the clock
    step_ms = _step_ms(fit_once, steps)
    layerprof.share_step_time(report, step_ms)
    return _summarize(report, step_ms)


def _bert(on_tpu: bool) -> dict:
    from deeplearning4j_tpu.common import layerprof
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.models.bert import Bert, BertConfig

    batch, seq = (16, 128) if on_tpu else (4, 64)
    conf = BertConfig.tiny(compute_dtype="bfloat16",
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    model = Bert(conf, Adam(1e-4)).init()
    rng = np.random.default_rng(0)
    bd = {"input_ids": rng.integers(0, conf.vocab_size, (batch, seq)),
          "mlm_labels": rng.integers(0, conf.vocab_size, (batch, seq))}
    report = model.layer_report(bd)

    steps = 5 if on_tpu else 2

    def fit_once():
        model.fit_steps(bd, steps)

    fit_once()                        # compile outside the clock
    step_ms = _step_ms(fit_once, steps)
    layerprof.share_step_time(report, step_ms)
    return _summarize(report, step_ms)


def main():
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    line = {"metric": "layer_attribution",
            "meta": {"proxy": not on_tpu}}
    try:
        line["resnet50"] = _resnet50(on_tpu)
    except Exception as e:            # noqa: BLE001
        print(f"resnet50 attribution failed: {e!r}", file=sys.stderr)
    try:
        line["bert"] = _bert(on_tpu)
    except Exception as e:            # noqa: BLE001
        print(f"bert attribution failed: {e!r}", file=sys.stderr)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
