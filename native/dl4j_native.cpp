// dl4j_native — host-side C++ runtime for deeplearning4j_tpu.
//
// The reference keeps its performance-critical host runtime native
// (SURVEY.md §2.1: libnd4j memory/workspaces N12, execution runtime
// N13, threshold encode/decode compression ops J11/P2, Aeron chunk
// CRC §5.8, DataVec parsing V1). On TPU the *device* math belongs to
// XLA, but the host-side runtime around it is still native here:
//
//  - threshold gradient codec (the reference's native encoder behind
//    EncodedGradientsAccumulator): sparse ±tau encoding + residual
//  - CRC32 for chunked tensor transfer integrity
//  - arena allocator (workspace-style host staging buffers)
//  - pthread bounded ring queue (async data-prefetch backbone)
//  - CSV float parser (DataVec record-reader fast path)
//  - Kahn toposort (graph-session scheduling)
//
// Flat C ABI (extern "C"), bound from Python via ctypes — the same
// seam style as the reference's NativeOps.h (SURVEY.md N14), minus JNI.
//
// Build: make -C native   (g++ -O3 -fPIC -shared -pthread)

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <cmath>
#include <condition_variable>
#include <mutex>
#include <new>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32 (IEEE 802.3, zlib-compatible) — chunk integrity for tensor
// transfer, parity with the reference's Aeron chunk CRC.
// ---------------------------------------------------------------------------
static uint32_t g_crc_table[256];
static std::atomic<int> g_crc_ready{0};

static void crc_init() {
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        g_crc_table[i] = c;
    }
    g_crc_ready.store(1);
}

uint32_t dl4j_crc32(const uint8_t* data, int64_t n) {
    if (!g_crc_ready.load()) crc_init();
    uint32_t c = 0xFFFFFFFFu;
    for (int64_t i = 0; i < n; ++i)
        c = g_crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// Threshold gradient codec (reference: native encodeThreshold /
// decodeThreshold ops feeding EncodedGradientsAccumulator, SURVEY.md
// P2). Encoding: for every |g[i]| >= tau emit sign(g[i]) * (i + 1)
// as int32. Decode adds ±tau into the target buffer. The residual
// update subtracts the transmitted part, keeping the untransmitted
// remainder for the next step.
// ---------------------------------------------------------------------------
int64_t dl4j_threshold_encode(const float* g, int64_t n, float tau,
                              int32_t* out, int64_t cap) {
    int64_t k = 0;
    for (int64_t i = 0; i < n; ++i) {
        float v = g[i];
        if (v >= tau) {
            if (k < cap) out[k] = (int32_t)(i + 1);
            ++k;
        } else if (v <= -tau) {
            if (k < cap) out[k] = -(int32_t)(i + 1);
            ++k;
        }
    }
    return k;  // caller re-runs with bigger cap if k > cap
}

void dl4j_threshold_decode(const int32_t* enc, int64_t k, float tau,
                           float* out, int64_t n) {
    for (int64_t j = 0; j < k; ++j) {
        int32_t e = enc[j];
        int64_t idx = (e > 0 ? e : -e) - 1;
        if (idx >= 0 && idx < n) out[idx] += (e > 0) ? tau : -tau;
    }
}

void dl4j_threshold_residual(float* residual, const int32_t* enc,
                             int64_t k, float tau, int64_t n) {
    for (int64_t j = 0; j < k; ++j) {
        int32_t e = enc[j];
        int64_t idx = (e > 0 ? e : -e) - 1;
        if (idx >= 0 && idx < n) residual[idx] -= (e > 0) ? tau : -tau;
    }
}

// ---------------------------------------------------------------------------
// Arena allocator — workspace-style bump allocator for host staging
// buffers (reference MemoryWorkspace / libnd4j memory N12: scoped
// arena reuse instead of per-step malloc/GC pressure).
// ---------------------------------------------------------------------------
struct Dl4jArena {
    uint8_t* base;
    int64_t cap;
    int64_t used;
    int64_t high_water;
};

void* dl4j_arena_create(int64_t cap) {
    auto* a = new (std::nothrow) Dl4jArena();
    if (!a) return nullptr;
    a->base = (uint8_t*)std::malloc((size_t)cap);
    if (!a->base) { delete a; return nullptr; }
    a->cap = cap;
    a->used = 0;
    a->high_water = 0;
    return a;
}

void* dl4j_arena_alloc(void* arena, int64_t size, int64_t align) {
    auto* a = (Dl4jArena*)arena;
    if (align <= 0) align = 64;
    int64_t off = (a->used + align - 1) & ~(align - 1);
    if (off + size > a->cap) return nullptr;  // spill: caller mallocs
    a->used = off + size;
    if (a->used > a->high_water) a->high_water = a->used;
    return a->base + off;
}

void dl4j_arena_reset(void* arena) { ((Dl4jArena*)arena)->used = 0; }
int64_t dl4j_arena_used(void* arena) { return ((Dl4jArena*)arena)->used; }
int64_t dl4j_arena_high_water(void* arena) {
    return ((Dl4jArena*)arena)->high_water;
}

void dl4j_arena_destroy(void* arena) {
    auto* a = (Dl4jArena*)arena;
    std::free(a->base);
    delete a;
}

// ---------------------------------------------------------------------------
// Bounded blocking ring queue — the async-prefetch backbone
// (reference: AsyncDataSetIterator's bounded queue between the ETL
// thread and fit(), SURVEY.md J9 / call stack 3.1 "async prefetch
// thread"). Items are opaque uintptr tokens.
// ---------------------------------------------------------------------------
struct Dl4jQueue {
    std::vector<uintptr_t> buf;
    size_t head = 0, tail = 0, count = 0;
    bool closed = false;
    std::mutex m;
    std::condition_variable not_full, not_empty;
};

void* dl4j_queue_create(int32_t cap) {
    auto* q = new (std::nothrow) Dl4jQueue();
    if (!q) return nullptr;
    q->buf.resize(cap > 0 ? cap : 1);
    return q;
}

// returns 1 on success, 0 on timeout, -1 if closed
int32_t dl4j_queue_push(void* qp, uintptr_t item, double timeout_s) {
    auto* q = (Dl4jQueue*)qp;
    std::unique_lock<std::mutex> lk(q->m);
    auto pred = [q] { return q->closed || q->count < q->buf.size(); };
    if (timeout_s < 0) {
        q->not_full.wait(lk, pred);
    } else if (!q->not_full.wait_for(
                   lk, std::chrono::duration<double>(timeout_s), pred)) {
        return 0;
    }
    if (q->closed) return -1;
    q->buf[q->tail] = item;
    q->tail = (q->tail + 1) % q->buf.size();
    ++q->count;
    q->not_empty.notify_one();
    return 1;
}

// returns 1 with *out set, 0 on timeout, -1 if closed AND drained
int32_t dl4j_queue_pop(void* qp, uintptr_t* out, double timeout_s) {
    auto* q = (Dl4jQueue*)qp;
    std::unique_lock<std::mutex> lk(q->m);
    auto pred = [q] { return q->count > 0 || q->closed; };
    if (timeout_s < 0) {
        q->not_empty.wait(lk, pred);
    } else if (!q->not_empty.wait_for(
                   lk, std::chrono::duration<double>(timeout_s), pred)) {
        return 0;
    }
    if (q->count == 0) return -1;  // closed and drained
    *out = q->buf[q->head];
    q->head = (q->head + 1) % q->buf.size();
    --q->count;
    q->not_full.notify_one();
    return 1;
}

int64_t dl4j_queue_size(void* qp) {
    auto* q = (Dl4jQueue*)qp;
    std::lock_guard<std::mutex> lk(q->m);
    return (int64_t)q->count;
}

void dl4j_queue_close(void* qp) {
    auto* q = (Dl4jQueue*)qp;
    std::lock_guard<std::mutex> lk(q->m);
    q->closed = true;
    q->not_empty.notify_all();
    q->not_full.notify_all();
}

void dl4j_queue_destroy(void* qp) { delete (Dl4jQueue*)qp; }

// ---------------------------------------------------------------------------
// CSV float parser — DataVec CSVRecordReader fast path (SURVEY.md
// V1). Parses delimiter-separated floats; rows separated by '\n'.
// Returns number of values written, or -1 if out of capacity,
// -2 on ragged rows. n_rows/n_cols report the parsed shape.
// ---------------------------------------------------------------------------
int64_t dl4j_parse_csv_floats(const char* buf, int64_t len, char delim,
                              float* out, int64_t cap,
                              int64_t* n_rows, int64_t* n_cols) {
    int64_t k = 0, rows = 0, cols = -1, cur_cols = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end) {
        // skip blank (incl. whitespace-only) lines anywhere — the
        // Python fallback filters them via str.strip(), so the two
        // paths must agree
        if (cur_cols == 0) {
            const char* q = p;
            while (q < end && (*q == ' ' || *q == '\t' || *q == '\r'))
                ++q;
            if (q >= end) break;
            if (*q == '\n') {
                p = q + 1;
                continue;
            }
        }
        // delimit THIS field first (strtof alone would eat the
        // newline as leading whitespace and merge rows when a field
        // is empty/whitespace)
        const char* fe = p;
        while (fe < end && *fe != delim && *fe != '\n') ++fe;
        bool has_content = false;
        for (const char* c = p; c < fe; ++c)
            if (*c != ' ' && *c != '\t' && *c != '\r') {
                has_content = true;
                break;
            }
        float v = NAN;
        if (has_content) {
            char* next = nullptr;
            v = strtof(p, &next);
            if (next == p || next > fe) v = NAN;
        }
        if (k >= cap) return -1;
        out[k++] = v;
        ++cur_cols;
        p = fe;
        if (p >= end || *p == '\n') {
            ++rows;
            if (cols < 0) cols = cur_cols;
            else if (cols != cur_cols) return -2;
            cur_cols = 0;
            if (p < end) ++p;
        } else {
            ++p;  // delim
        }
    }
    if (cur_cols > 0) {  // final row without trailing newline
        ++rows;
        if (cols < 0) cols = cur_cols;
        else if (cols != cur_cols) return -2;
    }
    *n_rows = rows;
    *n_cols = cols < 0 ? 0 : cols;
    return k;
}

// ---------------------------------------------------------------------------
// Kahn toposort — graph-session scheduling (reference: SameDiff
// AbstractSession topo traversal S3 / libnd4j GraphExecutioner N11).
// Returns number of nodes placed; < n_nodes means a cycle.
// ---------------------------------------------------------------------------
int32_t dl4j_toposort(const int32_t* src, const int32_t* dst,
                      int64_t n_edges, int32_t n_nodes,
                      int32_t* order) {
    std::vector<int32_t> indeg(n_nodes, 0);
    std::vector<int64_t> head(n_nodes, -1);
    std::vector<int64_t> nxt(n_edges, -1);
    for (int64_t e = 0; e < n_edges; ++e) {
        int32_t s = src[e], d = dst[e];
        if (s < 0 || s >= n_nodes || d < 0 || d >= n_nodes) return -1;
        ++indeg[d];
        nxt[e] = head[s];
        head[s] = e;
    }
    std::vector<int32_t> ready;
    ready.reserve(n_nodes);
    for (int32_t i = 0; i < n_nodes; ++i)
        if (indeg[i] == 0) ready.push_back(i);
    int32_t placed = 0;
    // FIFO over the ready set -> deterministic schedule for a given
    // edge list (validity, not byte-equality with the Python
    // fallback, is the contract).
    for (size_t qh = 0; qh < ready.size(); ++qh) {
        int32_t u = ready[qh];
        order[placed++] = u;
        for (int64_t e = head[u]; e != -1; e = nxt[e])
            if (--indeg[dst[e]] == 0) ready.push_back(dst[e]);
    }
    return placed;
}

}  // extern "C"
