"""Import a frozen TF graph — including legacy v1 control flow and a
TensorArray accumulator loop — and run + fine-tune it (reference
examples: the `tf-import` samples around `TFGraphMapper`).

Builds the frozen graph with the in-image TF at run time (zero
egress), freezes it through ``convert_variables_to_constants`` — the
classic deployment pipeline — then imports, checks parity, and
differentiates through the imported loop."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def build_frozen_graph():
    import tensorflow as tf
    g = tf.Graph()
    with g.as_default():
        tf.compat.v1.disable_control_flow_v2()
        try:
            x = tf.compat.v1.placeholder(tf.float32, [4], name="x")
            w = tf.compat.v1.get_variable(
                "w", initializer=np.float32([1.1, 0.9, 1.3, 0.7]))

            def cond(i, v):
                return tf.logical_and(i < 6,
                                      tf.reduce_sum(v) < 50.0)

            def body(i, v):
                return i + 1, v * 1.5 + w * 0.1

            _, vf = tf.compat.v1.while_loop(
                cond, body, (tf.constant(0), w * x), name="loop")
            tf.reduce_sum(vf, name="out")
            with tf.compat.v1.Session() as sess:
                sess.run(tf.compat.v1.global_variables_initializer())
                gd = tf.compat.v1.graph_util \
                    .convert_variables_to_constants(
                        sess, g.as_graph_def(), ["out"])
                xv = np.float32([1.0, 2.0, 0.5, 1.5])
                want = sess.run("out:0", {"x:0": xv})
        finally:
            tf.compat.v1.enable_control_flow_v2()
    return gd.SerializeToString(), xv, float(want)


def main():
    from deeplearning4j_tpu.modelimport.tensorflow import \
        TensorflowFrameworkImporter

    gd, xv, want = build_frozen_graph()
    # bounded import: the loop becomes reverse-differentiable
    sd = TensorflowFrameworkImporter.run_import(
        gd, {"x": (4,)}, while_max_iterations={"loop": 8})
    got = float(sd.output({"x": xv}, ["out"])["out"])
    print(f"TF says {want:.4f}, imported graph says {got:.4f}")
    assert abs(got - want) < 1e-3

    # fine-tune THROUGH the imported v1 loop: promote the frozen
    # weight constant... here the graph was frozen, so train the
    # input instead as a demonstration of gradient flow
    sd.convert_to_variables(["x"], {"x": xv})
    sd.set_loss_variables(["out"])
    grads = sd.calculate_gradients({}, ["x"])
    print("d out / d x through the imported loop:",
          np.asarray(grads["x"]).round(3))


if __name__ == "__main__":
    main()
