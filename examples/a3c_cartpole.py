"""Vectorized A3C on CartPole (reference example: the rl4j-examples
`A3CCartpole`). The reference races async JVM worker threads; here N
parallel environments advance in lockstep INSIDE the compiled update
program — rollout, returns, and the gradient step are one jitted XLA
program (see rl/vectorized.py)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    from deeplearning4j_tpu.rl import (A3CVectorized,
                                       A3CVectorizedConfiguration,
                                       VectorCartPole)

    env = VectorCartPole(n_envs=16, max_steps=200)
    agent = A3CVectorized(env, A3CVectorizedConfiguration(seed=7))
    for round_i in range(8):
        finished = agent.train(200)
        score = agent.evaluate(n_episodes=5)
        recent = np.mean(finished[-20:]) if finished else 0.0
        print(f"round {round_i + 1}: {len(finished)} episodes, "
              f"train mean(last 20) {recent:6.1f}, "
              f"greedy eval {score:6.1f}")
        if score >= 195.0:
            print("solved (>= 195/200)")
            break


if __name__ == "__main__":
    main()
