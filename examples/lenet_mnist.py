"""LeNet-5 on MNIST: the minimal end-to-end slice (BASELINE config #1;
reference example: LeNetMNIST). Uses the synthetic-MNIST fallback when
the real files are absent (zero-egress environments)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                               DenseLayer, OutputLayer,
                                               PoolingType,
                                               SubsamplingLayer)
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener
from deeplearning4j_tpu.utils import ModelSerializer


def build():
    return (NeuralNetConfiguration.Builder()
            .seed(123)
            .updater(Adam(1e-3))
            .list()
            .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=20,
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=50,
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation=Activation.RELU))
            .layer(OutputLayer(n_out=10,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())


def main(epochs=1):
    train = MnistDataSetIterator(batch_size=128, train=True)
    test = MnistDataSetIterator(batch_size=128, train=False)
    net = MultiLayerNetwork(build()).init()
    net.set_listeners(ScoreIterationListener(50))
    net.fit(train, n_epochs=epochs)

    e = Evaluation()
    for ds in test:
        e.eval(ds.labels, net.output(ds.features))
    print(f"accuracy: {e.accuracy():.4f}  f1: {e.f1():.4f}")

    ModelSerializer.write_model(net, "/tmp/lenet_mnist.zip",
                                save_updater=True)
    print("saved to /tmp/lenet_mnist.zip")
    return e.accuracy()


if __name__ == "__main__":
    main()
