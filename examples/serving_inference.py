"""Model serving with ParallelInference (reference example:
ParallelInference in dl4j-examples — SURVEY.md P6).

Three serving modes on one trained model:
  INPLACE  — direct forward per request, lowest latency
  BATCHED (sync)  — aggregate a request list into shard-wide batches
  BATCHED (async) — submit() -> Future; a background worker batches
                    concurrent requests within a time window

The production serving stack (``serving.ServingBatcher``) replaces
the fixed time window with *continuous* batching: the worker flushes
the instant the device frees, taking whatever is queued — a lone
request pays zero window latency, and under load queue depth alone
fills the warm buckets. The final leg below shows it on the same
model.
"""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                   ParallelInference)


def build():
    return (NeuralNetConfiguration.Builder()
            .seed(42).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())


def main():
    rng = np.random.RandomState(0)
    net = MultiLayerNetwork(build()).init()
    x = rng.randn(64, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 64)]
    for _ in range(20):
        net.fit(x, y)

    # INPLACE: each request runs directly (lowest latency)
    direct = (ParallelInference.Builder(net)
              .inference_mode(InferenceMode.INPLACE).build())
    one = rng.randn(1, 8).astype(np.float32)
    probs = direct.submit(one).result()
    print("INPLACE single request ->", np.round(probs, 3))

    # BATCHED, synchronous: a list of requests in one call
    batched = (ParallelInference.Builder(net)
               .inference_mode(InferenceMode.BATCHED)
               .batch_limit(16).build())
    reqs = [rng.randn(1, 8).astype(np.float32) for _ in range(40)]
    outs = batched.output_batched(reqs)
    print(f"BATCHED sync: {len(outs)} results, "
          f"first={np.round(outs[0], 3)}")

    # BATCHED, async observable: concurrent submits share batches —
    # the SAME instance serves both the sync and async APIs
    batched.batch_window_ms = 10.0
    futures = [batched.submit(r) for r in reqs]
    results = [f.result(timeout=60) for f in futures]
    batched.shutdown()
    ref = direct.output(np.concatenate(reqs))
    # tolerance, not equality: the chunked and whole-batch programs
    # are separate XLA compilations (bf16 matmuls on real TPU)
    np.testing.assert_allclose(np.concatenate(results), ref,
                               rtol=1e-3, atol=2e-3)
    print(f"BATCHED async: {len(results)} futures resolved; "
          f"results match the direct forward")

    # CONTINUOUS (the serving default): no batching window at all —
    # bucket-padded flushes fire the moment the worker is free
    from deeplearning4j_tpu.serving import ServingBatcher
    srv = ServingBatcher(net, buckets=(8, 16), name="example",
                         flush_policy="continuous")
    srv.warmup((8,))                    # pre-compile both buckets
    futures = [srv.submit(r) for r in reqs]
    cont = [f.result(timeout=60) for f in futures]
    srv.shutdown()
    np.testing.assert_allclose(np.concatenate(cont), ref,
                               rtol=1e-3, atol=2e-3)
    print(f"CONTINUOUS: {len(cont)} requests served on warm buckets "
          f"with no window latency; results match the direct forward")
    return results


if __name__ == "__main__":
    main()
