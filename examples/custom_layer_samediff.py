"""User-defined layer via the SameDiff graph builder (reference
example: CustomLayerExample / SameDiffLayer docs)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dataclasses import dataclass

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import OutputLayer
from deeplearning4j_tpu.nn.conf.layers_samediff import SameDiffLayer


@dataclass
class GatedDense(SameDiffLayer):
    """y = sigmoid(xG) * tanh(xW) — a custom gated layer in ~10 lines."""

    def define_parameters(self):
        return {"W": (self.n_in, self.n_out),
                "G": (self.n_in, self.n_out)}

    def define_layer(self, sd, x, p):
        return sd.nn.sigmoid(x.mmul(p["G"])).mul(
            sd.math.tanh(x.mmul(p["W"])))


def main():
    rng = np.random.RandomState(0)
    x = rng.randn(256, 6).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] * x[:, 1] > 0).astype(int)]
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-2))
            .list()
            .layer(GatedDense(n_out=24))
            .layer(OutputLayer(n_out=2,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    for _ in range(100):
        net.fit(x, y)
    acc = (np.asarray(net.output(x)).argmax(-1) == y.argmax(-1)).mean()
    print(f"accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
