"""Long-context training with ring-attention context parallelism.

The reference handles sequence scale only via truncated BPTT
(SURVEY.md §5.7); this framework makes long context first-class: the
sequence is time-sharded over a dedicated ``seq`` mesh axis and
attention runs as RING attention — K/V shards rotate around the axis
via ``ppermute`` while each device accumulates its queries' partial
softmax exactly (log-sum-exp merge). On TPU the per-shard work rides
the Pallas flash kernels (``use_flash=True``), measured 320x faster
than differentiated blockwise scan for a causal seq-8192 train step
(BENCH_notes_r04.md).

Here: the flagship ``DistributedTransformerLM`` on a
pipe=2 x seq=2 x model=2 mesh learning a tiny next-token task, every
strategy active in ONE jitted train step. Needs 8 devices — on a
single-chip or CPU host a virtual 8-device CPU mesh is provisioned
in-process.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# the CPU device count is an XLA flag read once at backend init, so it
# must be in place BEFORE anything touches jax.devices() (backend init
# is lazy — see tests/conftest.py); jax_num_cpu_devices exists only on
# newer jax, the flag works everywhere
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

import numpy as np


def ensure_devices(n):
    import jax
    if len(jax.devices()) >= n:
        return
    import jax.extend.backend as eb
    eb.clear_backends()
    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= n


def main():
    ensure_devices(8)
    import jax

    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.models.transformer import (
        DistributedTransformerLM, TransformerLMConfig)
    from deeplearning4j_tpu.parallel import make_mesh

    # ring-CP layout: time sharded over `seq`, K/V rotating via
    # ppermute; tensor parallel over `model`, GPipe over `pipe`
    mesh = make_mesh({"data": 1, "pipe": 2, "seq": 2, "model": 2},
                     jax.devices()[:8])
    conf = TransformerLMConfig(vocab_size=64, max_len=32, d_model=32,
                               n_heads=4, d_ff=64, layers_per_stage=2)
    model = DistributedTransformerLM(conf, mesh, Adam(3e-3), n_micro=2)
    params, opt = model.init(seed=0)

    # toy "long context" task: predict the next token of a fixed
    # periodic sequence (period 8, so attention must look back)
    rng = np.random.RandomState(0)
    base = rng.randint(0, 64, 8)
    seq = np.tile(base, 32 // 8 + 1)
    ids = np.stack([seq[:32]] * 4).astype(np.int32)
    labels = np.stack([seq[1:33]] * 4).astype(np.int32)

    for step in range(30):
        params, opt, loss = model.train_step(params, opt, ids,
                                             labels, step)
        if step % 10 == 0 or step == 29:
            print(f"step {step:3d}  loss {float(loss):.4f}")
    assert float(loss) < 2.0, "ring-CP training failed to learn"
    print("ring-attention CP training: ok")


if __name__ == "__main__":
    main()
