"""Serve an MNIST classifier over HTTP (reference pairing:
ParallelInference + a network-facing model server).

The full serving lifecycle on one page:

  1. train a small MNIST-shaped network,
  2. register it in a ``ModelRegistry`` with shape-bucketed warmup
     (every batch bucket's XLA program compiles BEFORE the first
     request) and a ``latency_slo_ms`` — the SLO the adaptive
     admission budget defends under overload,
  3. start the ``InferenceServer`` and drive it like a client would —
     a JSON predict request with a deadline, then the zero-copy raw
     ``.npy`` hot path (no JSON float round-trip in either
     direction),
  4. hot-swap a retrained version under the same name (no request
     dropped, live pointer flips atomically),
  5. read back the serving metrics from ``/metrics``.

Flushes are *continuous* by default: the batcher worker flushes the
moment the device frees, so the lone requests below pay no batching
window — under concurrent load, queue depth alone fills the warm
buckets (pass ``flush_policy="window"`` to ``ModelRegistry`` for the
classic fixed-window behavior).

Synthetic MNIST-shaped data keeps it offline-runnable; point
``_data()`` at ``datasets.mnist`` for the real thing.
"""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import io
import json
import urllib.request

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import (AdmissionController,
                                        InferenceServer, ModelRegistry)


def _net(seed):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-3))
         .list()
         .layer(DenseLayer(n_out=64, activation=Activation.RELU))
         .layer(OutputLayer(n_out=10,
                            loss_function=LossFunction.MCXENT,
                            activation=Activation.SOFTMAX))
         .set_input_type(InputType.feed_forward(784)).build())).init()


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)      # MNIST-shaped pixels
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    return x, y


def _predict(base, x, deadline_ms=250):
    req = urllib.request.Request(
        base + "/v1/models/mnist:predict",
        data=json.dumps({"inputs": x.tolist(),
                         "deadline_ms": deadline_ms}).encode(),
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req).read())


def main():
    x, y = _data()
    net = _net(seed=42)
    for _ in range(5):
        net.fit(x, y)

    # registry + warmup: buckets (8, 32) compile now, not on request 1;
    # the 250ms SLO arms the adaptive admission budget (shed early
    # under overload instead of queueing past the deadline)
    reg = ModelRegistry(default_buckets=(8, 32))
    ver = reg.register("mnist", net, warmup_shape=(784,),
                       latency_slo_ms=250.0)
    print(f"registered mnist v{ver.version}: "
          f"buckets={list(ver.batcher.buckets)}, "
          f"warm signatures={ver.warm_signatures}")

    srv = InferenceServer(reg, AdmissionController(max_queue=64))
    srv.start(port=0)                 # 0 picks a free port; see .url
    base = srv.url
    print("serving on", base)

    # a client request (single digit, 250ms deadline)
    resp = _predict(base, x[:1])
    probs = np.asarray(resp["outputs"][0])
    print(f"v{resp['version']} prediction: digit "
          f"{int(probs.argmax())} (p={probs.max():.3f})")
    # tolerance, not equality: the bucket-padded (batch 8) and direct
    # (batch 1) programs are separate XLA compilations whose 784-dim
    # matmuls may tile differently in the low bits
    np.testing.assert_allclose(
        np.asarray(resp["outputs"], np.float32),
        np.asarray(net.output(x[:1])), rtol=1e-5, atol=1e-6)

    # zero-copy raw path: a .npy body in, a .npy body out — the
    # request is parsed as a view over the received bytes and the
    # response streams the result array's own buffer
    buf = io.BytesIO()
    np.save(buf, x[:4])
    raw_req = urllib.request.Request(
        base + "/v1/models/mnist:predict", data=buf.getvalue(),
        headers={"Content-Type": "application/octet-stream"})
    raw_resp = urllib.request.urlopen(raw_req)
    raw_out = np.load(io.BytesIO(raw_resp.read()))
    print(f"raw .npy path: {raw_out.shape} {raw_out.dtype} from "
          f"v{raw_resp.headers['X-Model-Version']}")
    assert raw_out.shape == (4, 10)

    # hot-swap: retrain, re-register the SAME name — version bumps,
    # no request dropped, warmup happens before the pointer flips
    net2 = _net(seed=7)
    for _ in range(10):
        net2.fit(x, y)
    reg.register("mnist", net2, warmup_shape=(784,))
    resp = _predict(base, x[:1])
    print(f"after hot-swap: serving v{resp['version']}")
    assert resp["version"] == 2

    # zero post-warmup recompiles is the serving-latency guarantee
    print("retraces since warmup:",
          reg.retraces_since_warmup("mnist"))

    metrics = urllib.request.urlopen(base + "/metrics").read().decode()
    served = [ln for ln in metrics.splitlines()
              if ln.startswith("dl4j_serving_requests_total")]
    print("\n".join(served))

    srv.stop()
    reg.shutdown()
    return reg.retraces_since_warmup("mnist")


if __name__ == "__main__":
    main()
