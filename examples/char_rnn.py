"""GravesLSTM character RNN with tBPTT + temperature sampling
(BASELINE config #3; reference example: LSTMCharModellingExample)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.builders import BackpropType
from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
from deeplearning4j_tpu.nn.conf.layers_recurrent import GravesLSTM

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. ") * 40


def main(epochs=20, seq_len=32, hidden=64):
    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    n = len(chars)

    # [b, t] one-hot sequences: predict the next character
    ids = np.asarray([idx[c] for c in TEXT], np.int32)
    starts = np.arange(0, len(ids) - seq_len - 1, seq_len)
    x = np.stack([np.eye(n, dtype=np.float32)[ids[s:s + seq_len]]
                  for s in starts])
    y = np.stack([np.eye(n, dtype=np.float32)[ids[s + 1:s + seq_len + 1]]
                  for s in starts])

    conf = (NeuralNetConfiguration.Builder()
            .seed(12345)
            .updater(Adam(5e-3))
            .list()
            .layer(GravesLSTM(n_out=hidden,
                              activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=n,
                                  loss_function=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(n, seq_len))
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_length(16)
            .build())
    net = MultiLayerNetwork(conf).init()
    for ep in range(epochs):
        net.fit(x, y)
        if ep % 5 == 0:
            print(f"epoch {ep}: loss {float(net.score()):.4f}")

    # temperature sampling: stateful rnn_time_step, one char at a
    # time; temperature < 1 sharpens, > 1 flattens the distribution
    def sample(temperature=0.7, length=60):
        rng = np.random.RandomState(0)
        net.rnn_clear_previous_state()
        cur = np.eye(n, dtype=np.float32)[idx["t"]][None, None]
        out = ["t"]
        for _ in range(length):
            probs = np.asarray(net.rnn_time_step(cur))[0, 0]
            logits = np.log(np.maximum(probs, 1e-9)) / temperature
            p = np.exp(logits - logits.max())
            c = rng.choice(n, p=p / p.sum())
            out.append(chars[c])
            cur = np.eye(n, dtype=np.float32)[c][None, None]
        return "".join(out)

    print("sample (T=0.7):", sample(0.7))
    return float(net.score())


if __name__ == "__main__":
    main()
