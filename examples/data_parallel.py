"""Data-parallel training over a device mesh (reference example:
ParallelWrapper multi-GPU training; here pjit DP over jax devices —
run with XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu for a virtual 8-device mesh)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def main():
    import jax
    rng = np.random.RandomState(0)
    x = rng.randn(512, 16).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 512)]

    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=64, activation=Activation.RELU))
            .layer(OutputLayer(n_out=4,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(16))
            .build())
    net = MultiLayerNetwork(conf).init()
    pw = (ParallelWrapper.Builder(net)
          .workers(len(jax.devices()))
          .build())
    for _ in range(20):
        pw.fit_batch(DataSet(x, y))
    print(f"devices: {len(jax.devices())}, "
          f"loss: {float(net.score()):.4f}")
    return float(net.score())


if __name__ == "__main__":
    main()
