"""Transfer learning: freeze a trained feature stack, replace the head
(reference example: EditLastLayerOthersFrozen)."""
import os
import sys
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transferlearning import TransferLearning


def main():
    rng = np.random.RandomState(0)
    # pretrain a 3-class base model
    x = rng.randn(256, 8).astype(np.float32)
    y3 = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 256)]
    base_conf = (NeuralNetConfiguration.Builder()
                 .seed(0).updater(Adam(1e-2))
                 .list()
                 .layer(DenseLayer(n_out=32,
                                   activation=Activation.RELU))
                 .layer(DenseLayer(n_out=16,
                                   activation=Activation.RELU))
                 .layer(OutputLayer(n_out=3,
                                    loss_function=LossFunction.MCXENT,
                                    activation=Activation.SOFTMAX))
                 .set_input_type(InputType.feed_forward(8))
                 .build())
    base = MultiLayerNetwork(base_conf).init()
    for _ in range(30):
        base.fit(x, y3)

    # new 2-class task: freeze features, swap the head
    y2 = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    net = (TransferLearning.Builder(base)
           .set_feature_extractor(1)        # freeze layers 0..1
           .remove_output_layer()
           .add_layer(OutputLayer(n_out=2,
                                  loss_function=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
           .build())
    w_before = np.asarray(net.params["layer_0"]["W"]).copy()
    for _ in range(40):
        net.fit(x, y2)
    w_after = np.asarray(net.params["layer_0"]["W"])
    acc = (np.asarray(net.output(x)).argmax(-1) == y2.argmax(-1)).mean()
    print(f"fine-tuned accuracy: {acc:.3f}; "
          f"frozen weights moved: {np.abs(w_after - w_before).max():.2e}")
    return acc


if __name__ == "__main__":
    main()
