"""Decoder-only transformer LM with a paged-decode serving contract.

The generative serving engine needs a model that exposes the
prefill/decode split explicitly:

- :meth:`DecoderLM.prefill` — full causal attention over the (padded)
  prompt through ``sdpa_core`` (:func:`ops.attention.
  dot_product_attention`, so the flash kernel engages exactly where
  the classifier path's heuristics say), returning the last valid
  position's logits plus every layer's K/V for the KV pool.
- :meth:`DecoderLM.decode_step` — one token per live sequence: project
  q/k/v for the new token, scatter its K/V into the paged pool at the
  block-table slot, then paged attention over the pool (Pallas kernel
  or dense-gather fallback via the ``paged_attention`` kernel-select
  family). Everything is shape-stable in (batch, table-width), so one
  compiled step serves the whole continuous batch forever.

Parameters are a **two-level dict** ``{entry: {leaf: array}}`` with
per-layer entries (``layer_0`` … ``layer_{n-1}``), the exact layout
``parallel.zero.params_to_fsdp`` / ``serving.residency`` shard — so a
generative model composes with ``mode="sharded"``/``"fsdp"`` residency
out of the box (the forward walks ``params[entry]``, which an
``FsdpParamView`` serves with a point-of-use all-gather).

Causality makes the two paths agree: token *t*'s activations depend
only on tokens ``<= t``, so a decode step over cached K/V computes the
same logits (up to float associativity) as a full forward's last
position — the property the conformance gate
(``scripts/check_generative.py``) asserts as greedy token equality.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class DecoderConfig:
    """Hyperparameters; ``tiny()`` is the test/bench size."""

    vocab_size: int = 64
    n_layers: int = 2
    n_heads: int = 2
    d_model: int = 32
    d_ff: int = 64
    max_len: int = 256
    eos_id: int = 1
    seed: int = 0

    @staticmethod
    def tiny(**kw) -> "DecoderConfig":
        return DecoderConfig(**kw)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def _ln(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


class DecoderLM:
    """Pre-LN decoder-only transformer over token ids."""

    def __init__(self, conf: Optional[DecoderConfig] = None, **kw):
        self.conf = conf if conf is not None else DecoderConfig(**kw)
        self.params = None

    # -- init -----------------------------------------------------------
    def init(self, key=None) -> dict:
        c = self.conf
        if key is None:
            key = jax.random.PRNGKey(c.seed)
        d, f, v = c.d_model, c.d_ff, c.vocab_size

        def dense(k, shape, scale=0.02):
            return (jax.random.normal(k, shape, jnp.float32)
                    * jnp.float32(scale))

        keys = iter(jax.random.split(key, 4 + 6 * c.n_layers))
        params = {"embed": {"tok": dense(next(keys), (v, d)),
                            "pos": dense(next(keys), (c.max_len, d))}}
        for i in range(c.n_layers):
            params[f"layer_{i}"] = {
                "ln1_g": jnp.ones((d,)), "ln1_b": jnp.zeros((d,)),
                "wq": dense(next(keys), (d, d)),
                "wk": dense(next(keys), (d, d)),
                "wv": dense(next(keys), (d, d)),
                "wo": dense(next(keys), (d, d)),
                "ln2_g": jnp.ones((d,)), "ln2_b": jnp.zeros((d,)),
                "w1": dense(next(keys), (d, f)),
                "b1": jnp.zeros((f,)),
                "w2": dense(next(keys), (f, d)),
                "b2": jnp.zeros((d,)),
            }
        params["head"] = {"ln_g": jnp.ones((d,)),
                          "ln_b": jnp.zeros((d,)),
                          "w": dense(next(keys), (d, v))}
        self.params = params
        return params

    # -- shared blocks --------------------------------------------------
    def _attn_qkv(self, p, h, heads_first: bool):
        c = self.conf
        shp = h.shape[:-1] + (c.n_heads, c.head_dim)
        q = jnp.reshape(h @ p["wq"], shp)
        k = jnp.reshape(h @ p["wk"], shp)
        v = jnp.reshape(h @ p["wv"], shp)
        if heads_first:                  # [b, t, h, dh] -> [b, h, t, dh]
            q, k, v = (jnp.swapaxes(a, -3, -2) for a in (q, k, v))
        return q, k, v

    def _mlp(self, p, x):
        h = _ln(x, p["ln2_g"], p["ln2_b"])
        return x + jax.nn.gelu(h @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

    # -- full causal forward (prefill / reference decode) ---------------
    def forward_with_kv(self, params, tokens, length=None):
        """Logits ``[b, t, vocab]`` plus stacked per-layer K/V
        ``[n_layers, b, t, heads, head_dim]`` (token-major — the KV
        pool's block layout). ``length`` ``[b]`` masks right-padding;
        padded positions still produce K/V (callers route them to the
        scratch block)."""
        from deeplearning4j_tpu.ops.attention import \
            dot_product_attention
        c = self.conf
        b, t = tokens.shape
        pos = jnp.arange(t, dtype=jnp.int32)
        x = params["embed"]["tok"][tokens] + params["embed"]["pos"][pos]
        causal = jnp.tril(jnp.ones((t, t), jnp.float32))
        mask = causal[None, None]                   # [1, 1, t, t]
        if length is not None:
            valid = (pos[None, :]
                     < jnp.asarray(length)[:, None]).astype(jnp.float32)
            mask = mask * valid[:, None, None, :]
        ks, vs = [], []
        for i in range(c.n_layers):
            p = params[f"layer_{i}"]
            h = _ln(x, p["ln1_g"], p["ln1_b"])
            q, k, v = self._attn_qkv(p, h, heads_first=True)
            a = dot_product_attention(q, k, v, mask=mask)
            x = x + jnp.reshape(jnp.swapaxes(a, 1, 2),
                                (b, t, c.d_model)) @ p["wo"]
            x = self._mlp(p, x)
            ks.append(jnp.swapaxes(k, 1, 2))        # [b, t, h, dh]
            vs.append(jnp.swapaxes(v, 1, 2))
        hp = params["head"]
        logits = _ln(x, hp["ln_g"], hp["ln_b"]) @ hp["w"]
        return logits, jnp.stack(ks), jnp.stack(vs)

    def output(self, tokens):
        """Full-sequence logits (the generic serving surface; also the
        dense-attention reference the conformance gate decodes with).
        """
        if self.params is None:
            self.init()
        logits, _, _ = self.forward_with_kv(self.params,
                                            jnp.asarray(tokens))
        return logits

    def prefill(self, params, tokens, length):
        """Prompt pass: ``(last_logits [b, vocab], k, v)`` where
        ``last_logits`` is position ``length-1``'s row and k/v are the
        stacked caches from :meth:`forward_with_kv`."""
        logits, k, v = self.forward_with_kv(params, tokens, length)
        b = tokens.shape[0]
        last = logits[jnp.arange(b), jnp.asarray(length) - 1]
        return last, k, v

    # -- one fused decode step over the paged pool ----------------------
    def decode_step(self, params, tokens, positions, k_pool, v_pool,
                    block_tables, *, paged: bool = False,
                    interpret=None):
        """One token for every sequence in the decode batch.

        ``tokens``/``positions`` ``[b]`` int32 (position = index of
        this token; the KV valid length becomes ``positions + 1``);
        ``k_pool``/``v_pool`` ``[n_layers, num_blocks, block, heads,
        head_dim]``; ``block_tables`` ``[b, max_blocks]`` int32 padded
        with the scratch block 0 (dead batch slots pass position 0 and
        an all-zero table — their writes land in scratch). ``paged``
        picks the Pallas kernel over the dense-gather fallback.
        Returns ``(logits [b, vocab], k_pool, v_pool)`` — a functional
        pool update."""
        from deeplearning4j_tpu.ops.attention_pallas import (
            paged_attention_reference, paged_decode_attention)
        c = self.conf
        b = tokens.shape[0]
        nl, nb, bs = (k_pool.shape[0], k_pool.shape[1],
                      k_pool.shape[2])
        x = (params["embed"]["tok"][tokens]
             + params["embed"]["pos"][positions])        # [b, d]
        slot = (block_tables[jnp.arange(b), positions // bs] * bs
                + positions % bs)                        # [b]
        lengths = positions + 1
        kf = jnp.reshape(k_pool, (nl, nb * bs) + k_pool.shape[3:])
        vf = jnp.reshape(v_pool, (nl, nb * bs) + v_pool.shape[3:])
        for i in range(c.n_layers):
            p = params[f"layer_{i}"]
            h = _ln(x, p["ln1_g"], p["ln1_b"])
            q, k_new, v_new = self._attn_qkv(p, h, heads_first=False)
            # low-precision pools (kv_dtype=bf16) take writes in the
            # pool's own dtype; attention math re-promotes via q
            kf = kf.at[i, slot].set(k_new.astype(kf.dtype))
            vf = vf.at[i, slot].set(v_new.astype(vf.dtype))
            kp = jnp.reshape(kf[i], (nb, bs, c.n_heads, c.head_dim))
            vp = jnp.reshape(vf[i], (nb, bs, c.n_heads, c.head_dim))
            if paged:
                a = paged_decode_attention(q, kp, vp, block_tables,
                                           lengths,
                                           interpret=interpret)
            else:
                a = paged_attention_reference(q, kp, vp, block_tables,
                                              lengths)
            x = x + jnp.reshape(a, (b, c.d_model)) @ p["wo"]
            x = self._mlp(p, x)
        hp = params["head"]
        logits = _ln(x, hp["ln_g"], hp["ln_b"]) @ hp["w"]
        shape = (nl, nb, bs, c.n_heads, c.head_dim)
        return logits, jnp.reshape(kf, shape), jnp.reshape(vf, shape)

    # -- reference decode (conformance gate) ----------------------------
    def reference_decode(self, params, prompt, max_tokens: int,
                         eos_id: Optional[int] = None):
        """Greedy decode by full re-forward each step — the
        dense-attention reference paged decode must match token for
        token. ``prompt`` is a 1-D id list; returns generated ids."""
        eos = self.conf.eos_id if eos_id is None else eos_id
        ids = list(np.asarray(prompt, np.int32))
        out = []
        for _ in range(max_tokens):
            tok = jnp.asarray([ids], jnp.int32)
            logits, _, _ = self.forward_with_kv(params, tok)
            nxt = int(jnp.argmax(logits[0, -1]))
            out.append(nxt)
            ids.append(nxt)
            if nxt == eos:
                break
        return out
