"""Model zoo.

Reference parity: ``org.deeplearning4j.zoo.**`` (SURVEY.md D15): ``ZooModel``
base with ``init()`` building the network; ``LeNet``, ``SimpleCNN``,
``VGG16/19``, ``ResNet50``, ``AlexNet`` first (the BASELINE configs need
LeNet + ResNet50). ``init_pretrained()`` loads the checkpoints BUNDLED
under ``models/pretrained/`` (trained + gated by
``scripts/train_pretrained.py`` — this container has no egress, so the
weights ship with the package instead of downloading); pass a path for
your own checkpoints.

Architectures follow the reference zoo's configurations; layouts are NHWC
(TPU-first). ResNet50 is the BASELINE.json north-star model: a
ComputationGraph of bottleneck residual blocks whose conv+BN+add lower to
fused XLA ops on the MXU.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning.updaters import Adam, IUpdater, Nesterovs
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, ConvolutionMode, DenseLayer,
    DropoutLayer, GlobalPoolingLayer, OutputLayer, PoolingType,
    SubsamplingLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit


#: bundled checkpoints (scripts/train_pretrained.py trains and gates
#: them; meta.json records accuracy/dataset). The reference downloads
#: from its model repository; zero egress here, so the weights SHIP
#: with the package instead.
def pretrained_dir():
    import pathlib
    return pathlib.Path(__file__).parent / "pretrained"


def pretrained_meta() -> dict:
    import json
    with open(pretrained_dir() / "meta.json") as fh:
        return json.load(fh)


class ZooModel:
    """Base (reference: org.deeplearning4j.zoo.ZooModel)."""

    #: key into the bundled pretrained/ dir; None = no shipped weights
    pretrained_name: Optional[str] = None

    def init(self):
        """Build and initialize the network."""
        raise NotImplementedError

    def init_pretrained(self, path=None):
        """Load pretrained weights (reference: initPretrained — it
        downloads+caches; here the default resolves to the checkpoint
        bundled with the package, or pass an explicit zip path). The
        checkpoint zip carries its own full configuration; customized
        architecture fields on this instance therefore cannot apply,
        and customizing them while loading bundled weights raises."""
        from deeplearning4j_tpu.utils import ModelSerializer
        if path is None:
            name = self.pretrained_name
            if name is None:
                raise ValueError(
                    f"{type(self).__name__} has no bundled pretrained "
                    f"weights; pass an explicit checkpoint path")
            changed = self._non_default_fields()
            if changed:
                raise ValueError(
                    f"{type(self).__name__}({', '.join(changed)}) "
                    f"customizes the architecture, but the bundled "
                    f"'{name}' checkpoint carries its own "
                    f"configuration — the customization would be "
                    f"silently ignored. Drop the kwargs, or pass an "
                    f"explicit checkpoint path trained with them.")
            path = str(pretrained_dir() / f"{name}.zip")
        return ModelSerializer.restore_model(str(path))

    def _non_default_fields(self):
        import dataclasses
        if not dataclasses.is_dataclass(self):
            return []
        return [f.name for f in dataclasses.fields(self)
                if f.default is not dataclasses.MISSING
                and getattr(self, f.name) != f.default]

    initPretrained = init_pretrained

    def meta_data(self) -> dict:
        return {"name": type(self).__name__}


@dataclass
class LeNet(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.LeNet (MNIST-class)."""
    pretrained_name = "lenet"
    num_classes: int = 10
    height: int = 28
    width: int = 28
    channels: int = 1
    seed: int = 123
    updater: Optional[IUpdater] = None

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater or Adam(1e-3))
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=20,
                                        stride=(1, 1),
                                        activation=Activation.IDENTITY))
                .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                        kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=50,
                                        stride=(1, 1),
                                        activation=Activation.IDENTITY))
                .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                        kernel_size=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=500,
                                  activation=Activation.RELU))
                .layer(OutputLayer(
                    n_out=self.num_classes,
                    loss_function=LossFunction.NEGATIVELOGLIKELIHOOD,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional_flat(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


@dataclass
class SimpleCNN(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.SimpleCNN."""
    num_classes: int = 10
    height: int = 48
    width: int = 48
    channels: int = 3
    seed: int = 123

    def init(self) -> MultiLayerNetwork:
        def conv(n, k=(3, 3)):
            return ConvolutionLayer(kernel_size=k, n_out=n,
                                    convolution_mode=ConvolutionMode.SAME,
                                    activation=Activation.IDENTITY)
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed).updater(Adam(1e-3))
             .weight_init(WeightInit.RELU).list())
        for n in (16, 16):
            b = b.layer(conv(n)).layer(BatchNormalization(
                activation=Activation.RELU))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        for n in (32, 32):
            b = b.layer(conv(n)).layer(BatchNormalization(
                activation=Activation.RELU))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        conf = (b.layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(DropoutLayer(dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


@dataclass
class AlexNet(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.AlexNet (single-stream)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    def init(self) -> MultiLayerNetwork:
        conf = (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, 0.9))
                .weight_init(WeightInit.RELU)
                .l2(5e-4)
                .list()
                .layer(ConvolutionLayer(kernel_size=(11, 11), n_out=96,
                                        stride=(4, 4),
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=256,
                                        convolution_mode=ConvolutionMode
                                        .SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=384,
                                        convolution_mode=ConvolutionMode
                                        .SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=384,
                                        convolution_mode=ConvolutionMode
                                        .SAME,
                                        activation=Activation.RELU))
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=256,
                                        convolution_mode=ConvolutionMode
                                        .SAME,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(3, 3),
                                        stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, dropout=0.5,
                                  activation=Activation.RELU))
                .layer(DenseLayer(n_out=4096, dropout=0.5,
                                  activation=Activation.RELU))
                .layer(OutputLayer(n_out=self.num_classes))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


def _vgg(blocks, num_classes, height, width, channels, seed):
    b = (NeuralNetConfiguration.Builder()
         .seed(seed).updater(Nesterovs(1e-2, 0.9))
         .weight_init(WeightInit.RELU).list())
    for n_convs, n_out in blocks:
        for _ in range(n_convs):
            b = b.layer(ConvolutionLayer(
                kernel_size=(3, 3), n_out=n_out,
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.RELU))
        b = b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
    conf = (b.layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                               dropout=0.5))
            .layer(DenseLayer(n_out=4096, activation=Activation.RELU,
                              dropout=0.5))
            .layer(OutputLayer(n_out=num_classes))
            .set_input_type(InputType.convolutional(height, width,
                                                    channels))
            .build())
    return MultiLayerNetwork(conf).init()


@dataclass
class VGG16(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.VGG16."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    def init(self) -> MultiLayerNetwork:
        return _vgg([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)],
                    self.num_classes, self.height, self.width,
                    self.channels, self.seed)


@dataclass
class VGG19(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.VGG19."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    def init(self) -> MultiLayerNetwork:
        return _vgg([(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)],
                    self.num_classes, self.height, self.width,
                    self.channels, self.seed)


@dataclass
class ResNet50(ZooModel):
    """Reference: org.deeplearning4j.zoo.model.ResNet50 — the
    BASELINE.json north-star model (ComputationGraph, conv/BN/pool
    lowerings). Standard [3, 4, 6, 3] bottleneck architecture, NHWC.

    The bundled checkpoint ('resnet_cifar') is the CIFAR-scale
    variant trained by scripts/train_pretrained.py — restoring it
    returns THAT graph (32x32, STAGES ((2,16),(2,32))), not the
    ImageNet-sized default, since a checkpoint zip carries its full
    configuration (no ImageNet data exists in this container).
    """
    pretrained_name = "resnet_cifar"
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    updater: Optional[IUpdater] = None
    #: mixed precision: 'bfloat16' runs the conv/BN math on the MXU's
    #: native dtype with float32 master params (roughly doubles
    #: throughput; the reference's cuDNN TensorCore analog)
    compute_dtype: Optional[str] = None
    #: MLPerf-style TPU stem: space-to-depth(2) + 4x4/s1 conv replaces
    #: the 7x7/s2 conv on 3 channels — mathematically the same function
    #: class (the 4x4x12 kernel is the scattered zero-padded 8x8x3
    #: kernel) with an MXU-friendly 192-deep contraction instead of a
    #: 3-channel one. Off by default: parameter layout differs from the
    #: reference checkpoint format.
    stem_space_to_depth: bool = False
    #: sqrt(N)-checkpoint the training forward in this many segments
    #: (0 = store all activations); see ComputationGraphConfiguration
    remat_segments: int = 0

    # stage definitions: (n_blocks, bottleneck_width)
    STAGES: Tuple[Tuple[int, int], ...] = ((3, 64), (4, 128), (6, 256),
                                           (3, 512))

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(1e-1, 0.9))
             .weight_init(WeightInit.RELU)
             .l2(1e-4)
             .compute_data_type(self.compute_dtype)
             .remat_segments(self.remat_segments)
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_bn(name, inp, n_out, kernel, stride, act=True):
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(
                            kernel_size=kernel, n_out=n_out,
                            stride=stride,
                            convolution_mode=ConvolutionMode.SAME,
                            has_bias=False,
                            activation=Activation.IDENTITY), inp)
            g.add_layer(f"{name}_bn",
                        BatchNormalization(
                            activation=Activation.RELU if act
                            else Activation.IDENTITY), f"{name}_conv")
            return f"{name}_bn"

        # stem
        if self.stem_space_to_depth:
            from deeplearning4j_tpu.nn.conf.layers_shape import \
                SpaceToDepthLayer
            g.add_layer("stem_s2d", SpaceToDepthLayer(block_size=2),
                        "input")
            # SAME on k=4/s=1 pads (1, 2) == the 7x7/s2 conv's (2, 3)
            # in input coordinates: exact output-shape equivalence
            last = conv_bn("stem", "stem_s2d", 64, (4, 4), (1, 1))
        else:
            last = conv_bn("stem", "input", 64, (7, 7), (2, 2))
        g.add_layer("stem_pool",
                    SubsamplingLayer(pooling_type=PoolingType.MAX,
                                     kernel_size=(3, 3), stride=(2, 2),
                                     convolution_mode=ConvolutionMode.SAME),
                    last)
        last = "stem_pool"

        for si, (n_blocks, width) in enumerate(self.STAGES):
            for bi in range(n_blocks):
                name = f"s{si}b{bi}"
                stride = (2, 2) if (bi == 0 and si > 0) else (1, 1)
                a = conv_bn(f"{name}_a", last, width, (1, 1), stride)
                b = conv_bn(f"{name}_b", a, width, (3, 3), (1, 1))
                c = conv_bn(f"{name}_c", b, width * 4, (1, 1), (1, 1),
                            act=False)
                if bi == 0:
                    sc = conv_bn(f"{name}_sc", last, width * 4, (1, 1),
                                 stride, act=False)
                else:
                    sc = last
                g.add_vertex(f"{name}_add",
                             ElementWiseVertex(ElementWiseVertex.Op.Add),
                             c, sc)
                g.add_layer(f"{name}_relu", _relu_layer(), f"{name}_add")
                last = f"{name}_relu"

        g.add_layer("avgpool",
                    GlobalPoolingLayer(pooling_type=PoolingType.AVG), last)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes,
                                loss_function=LossFunction.MCXENT,
                                activation=Activation.SOFTMAX), "avgpool")
        conf = g.set_outputs("output").build()
        return ComputationGraph(conf).init()


def _relu_layer():
    from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
    return ActivationLayer(activation=Activation.RELU)


# -- convenience constructors over the bundled checkpoints ------------------
def lenet(pretrained: bool = False, **kw):
    """LeNet network; ``pretrained=True`` loads the bundled
    synthetic-MNIST checkpoint (>=99% on its test split — meta.json)."""
    if pretrained:
        if kw:
            raise ValueError(
                f"lenet(pretrained=True) loads the bundled checkpoint "
                f"with its own architecture; architecture kwargs "
                f"{sorted(kw)} would be silently ignored — drop them "
                f"or build fresh with pretrained=False")
        return LeNet().init_pretrained()
    return LeNet(**kw).init()


def resnet_cifar(pretrained: bool = True):
    """The bundled CIFAR-scale ResNet checkpoint (see ResNet50 note)."""
    if pretrained:
        return ResNet50().init_pretrained()
    return ResNet50(num_classes=10, height=32, width=32,
                    STAGES=((2, 16), (2, 32))).init()


def char_rnn(pretrained: bool = True):
    """Bundled GravesLSTM character model. Returns (net, chars) — the
    vocabulary (index -> char) ships in pretrained/meta.json."""
    if not pretrained:
        raise ValueError("char_rnn is only offered as the bundled "
                         "checkpoint; build your own via examples/"
                         "char_rnn.py otherwise")
    from deeplearning4j_tpu.utils import ModelSerializer
    net = ModelSerializer.restore_model(
        str(pretrained_dir() / "charrnn.zip"))
    chars = pretrained_meta()["charrnn"]["chars"]
    return net, chars
