"""Model zoo, part 2 (SURVEY.md D15 long tail).

Reference parity: `org.deeplearning4j.zoo.model.{Darknet19, TinyYOLO,
YOLO2, Xception, SqueezeNet, UNet, InceptionResNetV1, NASNet,
TextGenerationLSTM}`. Architectures follow the reference zoo configs;
all NHWC, built on the same config/graph builders as the rest of the
framework (so they serialize, transfer-learn, and shard like any
user model).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning.updaters import Adam, IUpdater
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (ElementWiseVertex,
                                                       MergeVertex)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer,
    ConvolutionMode, DenseLayer, DropoutLayer, GlobalPoolingLayer,
    OutputLayer, PoolingType, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.layers_conv_extra import (
    Deconvolution2D, SeparableConvolution2D, Upsampling2D)
from deeplearning4j_tpu.nn.conf.layers_objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.nn.conf.layers_recurrent import LSTM
from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.models.zoo import ZooModel


def _conv(n, k=(3, 3), s=(1, 1), act=Activation.IDENTITY, bias=False):
    return ConvolutionLayer(kernel_size=k, n_out=n, stride=s,
                            convolution_mode=ConvolutionMode.SAME,
                            has_bias=bias, activation=act)


def _lrelu():
    return ActivationLayer(activation=Activation.LEAKYRELU)


@dataclass
class Darknet19(ZooModel):
    """reference: zoo.model.Darknet19 — conv/BN/leaky-relu backbone,
    1x1 bottlenecks between 3x3 blocks, 5 maxpools."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    updater: Optional[IUpdater] = None

    #: (filters, kernel) per conv; 'M' = maxpool
    PLAN = (32, "M", 64, "M", 128, (64, 1), 128, "M", 256, (128, 1),
            256, "M", 512, (256, 1), 512, (256, 1), 512, "M", 1024,
            (512, 1), 1024, (512, 1), 1024)

    def _backbone(self, b):
        for item in self.PLAN:
            if item == "M":
                b = b.layer(SubsamplingLayer(
                    kernel_size=(2, 2), stride=(2, 2),
                    convolution_mode=ConvolutionMode.SAME))
                continue
            if isinstance(item, tuple):
                n, k = item
                b = b.layer(_conv(n, (k, k)))
            else:
                b = b.layer(_conv(item))
            b = b.layer(BatchNormalization(
                activation=Activation.LEAKYRELU))
        return b

    def init(self) -> MultiLayerNetwork:
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weight_init(WeightInit.RELU).list())
        b = self._backbone(b)
        conf = (b.layer(ConvolutionLayer(
                    kernel_size=(1, 1), n_out=self.num_classes,
                    convolution_mode=ConvolutionMode.SAME,
                    activation=Activation.IDENTITY))
                .layer(GlobalPoolingLayer(
                    pooling_type=PoolingType.AVG))
                .layer(OutputLayer(
                    n_out=self.num_classes,
                    loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


@dataclass
class TinyYOLO(ZooModel):
    """reference: zoo.model.TinyYOLO — 9-conv darknet-tiny backbone +
    Yolo2OutputLayer; 416x416/32 -> 13x13 grid."""
    num_classes: int = 20
    height: int = 416
    width: int = 416
    channels: int = 3
    seed: int = 123
    anchors: Tuple[Tuple[float, float], ...] = (
        (1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11),
        (16.62, 10.52))

    def init(self) -> MultiLayerNetwork:
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU).list())
        for i, n in enumerate((16, 32, 64, 128, 256, 512)):
            b = b.layer(_conv(n)).layer(BatchNormalization(
                activation=Activation.LEAKYRELU))
            if i < 5:
                b = b.layer(SubsamplingLayer(
                    kernel_size=(2, 2), stride=(2, 2),
                    convolution_mode=ConvolutionMode.SAME))
        b = b.layer(_conv(1024)).layer(BatchNormalization(
            activation=Activation.LEAKYRELU))
        a = len(self.anchors)
        conf = (b.layer(ConvolutionLayer(
                    kernel_size=(1, 1),
                    n_out=a * (5 + self.num_classes),
                    convolution_mode=ConvolutionMode.SAME,
                    has_bias=True, activation=Activation.IDENTITY))
                .layer(Yolo2OutputLayer(anchors=self.anchors))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


@dataclass
class YOLO2(ZooModel):
    """reference: zoo.model.YOLO2 — Darknet19 backbone +
    Yolo2OutputLayer detection head."""
    num_classes: int = 80
    height: int = 416
    width: int = 416
    channels: int = 3
    seed: int = 123
    anchors: Tuple[Tuple[float, float], ...] = (
        (0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
        (7.88282, 3.52778), (9.77052, 9.16828))

    def init(self) -> MultiLayerNetwork:
        d = Darknet19(seed=self.seed)
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU).list())
        b = d._backbone(b)
        for _ in range(2):
            b = b.layer(_conv(1024)).layer(BatchNormalization(
                activation=Activation.LEAKYRELU))
        a = len(self.anchors)
        conf = (b.layer(ConvolutionLayer(
                    kernel_size=(1, 1),
                    n_out=a * (5 + self.num_classes),
                    convolution_mode=ConvolutionMode.SAME,
                    has_bias=True, activation=Activation.IDENTITY))
                .layer(Yolo2OutputLayer(anchors=self.anchors))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())
        return MultiLayerNetwork(conf).init()


@dataclass
class SqueezeNet(ZooModel):
    """reference: zoo.model.SqueezeNet — fire modules
    (squeeze 1x1 -> expand 1x1 | 3x3 concat)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123

    FIRES = ((16, 64), (16, 64), (32, 128), "M", (32, 128),
             (48, 192), (48, 192), (64, 256), "M", (64, 256))

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("stem", _conv(64, (3, 3), (2, 2),
                                  Activation.RELU, bias=True), "input")
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), "stem")
        last = "stem_pool"
        fi = 0
        for item in self.FIRES:
            if item == "M":
                g.add_layer(f"pool{fi}", SubsamplingLayer(
                    kernel_size=(3, 3), stride=(2, 2),
                    convolution_mode=ConvolutionMode.SAME), last)
                last = f"pool{fi}"
                continue
            sq, ex = item
            n = f"fire{fi}"
            g.add_layer(f"{n}_sq", _conv(sq, (1, 1),
                                         act=Activation.RELU,
                                         bias=True), last)
            g.add_layer(f"{n}_e1", _conv(ex, (1, 1),
                                         act=Activation.RELU,
                                         bias=True), f"{n}_sq")
            g.add_layer(f"{n}_e3", _conv(ex, (3, 3),
                                         act=Activation.RELU,
                                         bias=True), f"{n}_sq")
            g.add_vertex(f"{n}_cat", MergeVertex(), f"{n}_e1",
                         f"{n}_e3")
            last = f"{n}_cat"
            fi += 1
        g.add_layer("drop", DropoutLayer(dropout=0.5), last)
        g.add_layer("head_conv", _conv(self.num_classes, (1, 1),
                                       act=Activation.RELU,
                                       bias=True), "drop")
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), "head_conv")
        g.add_layer("output", OutputLayer(
            n_out=self.num_classes,
            loss_function=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gap")
        return ComputationGraph(g.set_outputs("output").build()).init()


@dataclass
class Xception(ZooModel):
    """reference: zoo.model.Xception — separable-conv towers with
    residual shortcuts (entry/middle/exit flows; middle-flow depth
    configurable, 8 in the paper)."""
    num_classes: int = 1000
    height: int = 299
    width: int = 299
    channels: int = 3
    seed: int = 123
    middle_blocks: int = 8

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_bn(name, inp, n, k, s, act=True):
            g.add_layer(f"{name}_c", _conv(n, k, s), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(
                activation=Activation.RELU if act
                else Activation.IDENTITY), f"{name}_c")
            return f"{name}_bn"

        def sep_bn(name, inp, n, act_first=True, act_last=False):
            src = inp
            if act_first:
                g.add_layer(f"{name}_pre", ActivationLayer(
                    activation=Activation.RELU), inp)
                src = f"{name}_pre"
            g.add_layer(f"{name}_s", SeparableConvolution2D(
                kernel_size=(3, 3), n_out=n,
                convolution_mode=ConvolutionMode.SAME,
                has_bias=False,
                activation=Activation.IDENTITY), src)
            g.add_layer(f"{name}_bn", BatchNormalization(
                activation=Activation.RELU if act_last
                else Activation.IDENTITY), f"{name}_s")
            return f"{name}_bn"

        last = conv_bn("stem1", "input", 32, (3, 3), (2, 2))
        last = conv_bn("stem2", last, 64, (3, 3), (1, 1))

        # entry flow: 3 residual down blocks
        for i, n in enumerate((128, 256, 728)):
            name = f"entry{i}"
            a = sep_bn(f"{name}_a", last, n, act_first=i > 0)
            bse = sep_bn(f"{name}_b", a, n)
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                kernel_size=(3, 3), stride=(2, 2),
                convolution_mode=ConvolutionMode.SAME), bse)
            sc = conv_bn(f"{name}_sc", last, n, (1, 1), (2, 2),
                         act=False)
            g.add_vertex(f"{name}_add",
                         ElementWiseVertex(ElementWiseVertex.Op.Add),
                         f"{name}_pool", sc)
            last = f"{name}_add"

        # middle flow: residual triple-separable blocks
        for i in range(self.middle_blocks):
            name = f"mid{i}"
            a = sep_bn(f"{name}_a", last, 728)
            b2 = sep_bn(f"{name}_b", a, 728)
            c = sep_bn(f"{name}_c", b2, 728)
            g.add_vertex(f"{name}_add",
                         ElementWiseVertex(ElementWiseVertex.Op.Add),
                         c, last)
            last = f"{name}_add"

        # exit flow
        a = sep_bn("exit_a", last, 728)
        b2 = sep_bn("exit_b", a, 1024)
        g.add_layer("exit_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), b2)
        sc = conv_bn("exit_sc", last, 1024, (1, 1), (2, 2), act=False)
        g.add_vertex("exit_add",
                     ElementWiseVertex(ElementWiseVertex.Op.Add),
                     "exit_pool", sc)
        last = sep_bn("exit_c", "exit_add", 1536, act_first=False,
                      act_last=True)
        last = sep_bn("exit_d", last, 2048, act_first=False,
                      act_last=True)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), last)
        g.add_layer("output", OutputLayer(
            n_out=self.num_classes,
            loss_function=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gap")
        return ComputationGraph(g.set_outputs("output").build()).init()


@dataclass
class UNet(ZooModel):
    """reference: zoo.model.UNet — encoder/decoder with skip
    concats; sigmoid 1-channel segmentation head."""
    height: int = 128
    width: int = 128
    channels: int = 3
    seed: int = 123
    base_filters: int = 64
    depth: int = 4

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def double_conv(name, inp, n):
            g.add_layer(f"{name}_c1", _conv(n, act=Activation.RELU,
                                            bias=True), inp)
            g.add_layer(f"{name}_c2", _conv(n, act=Activation.RELU,
                                            bias=True), f"{name}_c1")
            return f"{name}_c2"

        skips = []
        last = "input"
        for d in range(self.depth):
            n = self.base_filters * (2 ** d)
            last = double_conv(f"enc{d}", last, n)
            skips.append(last)
            g.add_layer(f"down{d}", SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2)), last)
            last = f"down{d}"

        last = double_conv("bottom", last,
                           self.base_filters * (2 ** self.depth))

        for d in reversed(range(self.depth)):
            n = self.base_filters * (2 ** d)
            g.add_layer(f"up{d}", Deconvolution2D(
                kernel_size=(2, 2), n_out=n, stride=(2, 2),
                convolution_mode=ConvolutionMode.SAME,
                activation=Activation.IDENTITY), last)
            g.add_vertex(f"cat{d}", MergeVertex(), f"up{d}", skips[d])
            last = double_conv(f"dec{d}", f"cat{d}", n)

        g.add_layer("head", ConvolutionLayer(
            kernel_size=(1, 1), n_out=1,
            convolution_mode=ConvolutionMode.SAME,
            activation=Activation.SIGMOID), last)
        from deeplearning4j_tpu.nn.conf.layers import CnnLossLayer
        g.add_layer("output", CnnLossLayer(
            loss_function=LossFunction.XENT,
            activation=Activation.IDENTITY), "head")
        return ComputationGraph(g.set_outputs("output").build()).init()


@dataclass
class InceptionResNetV1(ZooModel):
    """reference: zoo.model.InceptionResNetV1 (FaceNet backbone):
    stem + scaled-residual inception blocks (A/B/C) with reduction
    blocks between. Block counts configurable (5/10/5 in the
    reference)."""
    num_classes: int = 1000
    height: int = 160
    width: int = 160
    channels: int = 3
    seed: int = 123
    blocks: Tuple[int, int, int] = (2, 3, 2)   # A, B, C counts

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_bn(name, inp, n, k=(3, 3), s=(1, 1), act=True):
            g.add_layer(f"{name}_c", _conv(n, k, s), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(
                activation=Activation.RELU if act
                else Activation.IDENTITY), f"{name}_c")
            return f"{name}_bn"

        def scaled_residual(name, inp, branches, n_out, scale=0.17):
            """concat branches -> 1x1 up -> scale -> add -> relu."""
            g.add_vertex(f"{name}_cat", MergeVertex(), *branches)
            g.add_layer(f"{name}_up", ConvolutionLayer(
                kernel_size=(1, 1), n_out=n_out,
                convolution_mode=ConvolutionMode.SAME, has_bias=True,
                activation=Activation.IDENTITY), f"{name}_cat")
            from deeplearning4j_tpu.nn.conf.graph_vertices import \
                ScaleVertex
            g.add_vertex(f"{name}_scale", ScaleVertex(scale),
                         f"{name}_up")
            g.add_vertex(f"{name}_add",
                         ElementWiseVertex(ElementWiseVertex.Op.Add),
                         inp, f"{name}_scale")
            g.add_layer(f"{name}_relu", ActivationLayer(
                activation=Activation.RELU), f"{name}_add")
            return f"{name}_relu"

        # stem (slightly reduced vs paper; same topology family)
        last = conv_bn("stem1", "input", 32, (3, 3), (2, 2))
        last = conv_bn("stem2", last, 64, (3, 3), (1, 1))
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), last)
        last = conv_bn("stem3", "stem_pool", 128, (1, 1), (1, 1))
        last = conv_bn("stem4", last, 256, (3, 3), (2, 2))

        # Inception-A blocks (35x35 family)
        for i in range(self.blocks[0]):
            n = f"A{i}"
            b0 = conv_bn(f"{n}_b0", last, 32, (1, 1))
            b1 = conv_bn(f"{n}_b1a", last, 32, (1, 1))
            b1 = conv_bn(f"{n}_b1b", b1, 32, (3, 3))
            b2 = conv_bn(f"{n}_b2a", last, 32, (1, 1))
            b2 = conv_bn(f"{n}_b2b", b2, 32, (3, 3))
            b2 = conv_bn(f"{n}_b2c", b2, 32, (3, 3))
            last = scaled_residual(n, last, [b0, b1, b2], 256)

        # reduction-A
        ra = conv_bn("redA_c", last, 384, (3, 3), (2, 2))
        g.add_layer("redA_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), last)
        g.add_vertex("redA_cat", MergeVertex(), ra, "redA_pool")
        last = "redA_cat"
        ch = 384 + 256

        # Inception-B blocks
        for i in range(self.blocks[1]):
            n = f"B{i}"
            b0 = conv_bn(f"{n}_b0", last, 128, (1, 1))
            b1 = conv_bn(f"{n}_b1a", last, 128, (1, 1))
            b1 = conv_bn(f"{n}_b1b", b1, 128, (1, 7))
            b1 = conv_bn(f"{n}_b1c", b1, 128, (7, 1))
            last = scaled_residual(n, last, [b0, b1], ch, scale=0.1)

        # reduction-B
        rb = conv_bn("redB_c", last, 256, (3, 3), (2, 2))
        g.add_layer("redB_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2),
            convolution_mode=ConvolutionMode.SAME), last)
        g.add_vertex("redB_cat", MergeVertex(), rb, "redB_pool")
        last = "redB_cat"
        ch = ch + 256

        # Inception-C blocks
        for i in range(self.blocks[2]):
            n = f"C{i}"
            b0 = conv_bn(f"{n}_b0", last, 192, (1, 1))
            b1 = conv_bn(f"{n}_b1a", last, 192, (1, 1))
            b1 = conv_bn(f"{n}_b1b", b1, 192, (1, 3))
            b1 = conv_bn(f"{n}_b1c", b1, 192, (3, 1))
            last = scaled_residual(n, last, [b0, b1], ch, scale=0.2)

        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), last)
        g.add_layer("drop", DropoutLayer(dropout=0.2), "gap")
        g.add_layer("output", OutputLayer(
            n_out=self.num_classes,
            loss_function=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "drop")
        return ComputationGraph(g.set_outputs("output").build()).init()


@dataclass
class NASNet(ZooModel):
    """reference: zoo.model.NASNet (NASNet-A mobile). Normal cells:
    separable-conv + pooling branch pairs summed then concatenated;
    reduction cells stride-2. Cell counts configurable (4@ penultimate
    in mobile)."""
    num_classes: int = 1000
    height: int = 224
    width: int = 224
    channels: int = 3
    seed: int = 123
    penultimate_filters: int = 1056
    cells_per_stack: int = 2

    def init(self) -> ComputationGraph:
        g = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(1e-3)).weight_init(WeightInit.RELU)
             .graph_builder().add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        f0 = self.penultimate_filters // 24

        def sep_bn(name, inp, n, k=(3, 3), s=(1, 1)):
            g.add_layer(f"{name}_s", SeparableConvolution2D(
                kernel_size=k, n_out=n, stride=s,
                convolution_mode=ConvolutionMode.SAME, has_bias=False,
                activation=Activation.RELU), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(
                activation=Activation.IDENTITY), f"{name}_s")
            return f"{name}_bn"

        def normal_cell(name, inp, n):
            """two sep-conv branches + avgpool branch, concat."""
            a = sep_bn(f"{name}_a", inp, n, (5, 5))
            b = sep_bn(f"{name}_b", inp, n, (3, 3))
            g.add_vertex(f"{name}_ab",
                         ElementWiseVertex(ElementWiseVertex.Op.Add),
                         a, b)
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                pooling_type=PoolingType.AVG, kernel_size=(3, 3),
                stride=(1, 1),
                convolution_mode=ConvolutionMode.SAME), inp)
            p = conv_bn(f"{name}_pw", f"{name}_pool", n)
            g.add_vertex(f"{name}_cat", MergeVertex(),
                         f"{name}_ab", p)
            return f"{name}_cat"

        def conv_bn(name, inp, n, k=(1, 1), s=(1, 1)):
            g.add_layer(f"{name}_c", _conv(n, k, s), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(
                activation=Activation.RELU), f"{name}_c")
            return f"{name}_bn"

        def reduction_cell(name, inp, n):
            a = sep_bn(f"{name}_a", inp, n, (5, 5), (2, 2))
            g.add_layer(f"{name}_mp", SubsamplingLayer(
                kernel_size=(3, 3), stride=(2, 2),
                convolution_mode=ConvolutionMode.SAME), inp)
            b = conv_bn(f"{name}_mpw", f"{name}_mp", n)
            g.add_vertex(f"{name}_cat", MergeVertex(), a, b)
            return f"{name}_cat"

        last = conv_bn("stem", "input", f0, (3, 3), (2, 2))
        n = f0
        for stack in range(3):
            for c in range(self.cells_per_stack):
                last = normal_cell(f"s{stack}n{c}", last, n)
            if stack < 2:
                n *= 2
                last = reduction_cell(f"s{stack}r", last, n)
        g.add_layer("relu_out", ActivationLayer(
            activation=Activation.RELU), last)
        g.add_layer("gap", GlobalPoolingLayer(
            pooling_type=PoolingType.AVG), "relu_out")
        g.add_layer("output", OutputLayer(
            n_out=self.num_classes,
            loss_function=LossFunction.MCXENT,
            activation=Activation.SOFTMAX), "gap")
        return ComputationGraph(g.set_outputs("output").build()).init()


@dataclass
class TextGenerationLSTM(ZooModel):
    """reference: zoo.model.TextGenerationLSTM — stacked LSTM
    character model with per-timestep softmax."""
    total_unique_characters: int = 47
    max_length: int = 60
    units: int = 256
    layers: int = 2
    seed: int = 123

    def init(self) -> MultiLayerNetwork:
        b = (NeuralNetConfiguration.Builder().seed(self.seed)
             .updater(Adam(2e-3)).weight_init(WeightInit.XAVIER)
             .list())
        for _ in range(self.layers):
            b = b.layer(LSTM(n_out=self.units,
                             activation=Activation.TANH))
        conf = (b.layer(RnnOutputLayer(
                    n_out=self.total_unique_characters,
                    loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(
                    self.total_unique_characters, self.max_length))
                .build())
        return MultiLayerNetwork(conf).init()
