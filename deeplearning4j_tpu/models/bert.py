"""BERT family — the framework's native transformer encoder.

Reference parity: the reference has no native BERT *model*; BERT-base
arrives via SameDiff TF import (`TensorflowFrameworkImporter`,
SURVEY.md S6, BASELINE config #4) and is fed by
``org.deeplearning4j.iterator.BertIterator`` (D16). Here the encoder is
a first-class model built TPU-first:

- **stacked-layer scan**: all L encoder layers live in ONE stacked
  params pytree and run under ``lax.scan`` — compile time is O(1) in
  depth and XLA sees a single fused layer body.
- **remat**: optional ``jax.checkpoint`` over the layer body trades
  FLOPs for HBM (activation memory O(sqrt) trick is XLA's choice).
- **attention**: dense fused attention by default
  (`ops.attention.dot_product_attention` over split heads), or the
  Pallas flash kernel (`parallel.sequence.flash_attention`, key-mask
  aware) for long sequences.
- **bf16-ready**: ``compute_dtype=bfloat16`` keeps params fp32 and
  casts activations, the standard TPU mixed-precision recipe (MXU
  native bf16).

Weight layout follows the TF/HF BERT conventions (q/k/v/output dense
per layer, gelu intermediate, post-LN) so TF-checkpoint import can map
1:1 onto these pytrees.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deeplearning4j_tpu.common import layerprof
from deeplearning4j_tpu.learning.updaters import Adam, IUpdater
from deeplearning4j_tpu.ops.attention import (dot_product_attention,
                                              merge_heads, split_heads)


def _raw_step(loss_fn, updater):
    """Functional train step shared by every model head:
    (params, opt_state, iteration, batch, rng) -> (params', state',
    loss)."""

    def step(params, opt_state, iteration, batch, rng):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, rng))(params)
        # attribution scope (common.layerprof): the updater sweep is
        # real step work that belongs to no functional block
        with layerprof.scope("optimizer"):
            updates, new_state = updater.apply(grads, opt_state,
                                               iteration)
            # apply the (possibly f32) updater math at full precision
            # but keep each param's own dtype — bf16 params would
            # otherwise silently promote to f32 after one step
            new_params = jax.tree_util.tree_map(
                lambda p, u: (p - u).astype(p.dtype), params, updates)
        return new_params, new_state, loss

    return step


def _make_train_step(loss_fn, updater):
    """Jitted train step; params/opt-state buffers are donated (XLA
    reuses them)."""
    return jax.jit(_raw_step(loss_fn, updater), donate_argnums=(0, 1))


class _Trainable:
    """fit_batch/score plumbing over a jitted `_make_train_step`."""

    updater: IUpdater
    params: dict

    def _loss_fn(self, params, batch, rng):
        raise NotImplementedError

    def _ensure_step(self):
        if getattr(self, "_compiled_updater", None) is not None and \
                self._compiled_updater is not self.updater:
            # updater reassigned after compile: the cached programs
            # bake the OLD update rule (and the opt state's moments
            # belong to it) — evict everything, like SameDiff's
            # set_training_config eviction of train_multi
            self._step = None
            self._multi_step = None
            self._opt_state = None
        if getattr(self, "_step", None) is None:
            self._compiled_updater = self.updater
            self._step = _make_train_step(self._loss_fn, self.updater)
            self._opt_state = self.updater.init_state(self.params)
            self._iteration = 0

    def fit_batch(self, batch) -> float:
        self._ensure_step()
        batch = {k: jnp.asarray(v) for k, v in batch.items()
                 if v is not None}
        rng = jax.random.PRNGKey(np.random.randint(0, 2 ** 31))
        self.params, self._opt_state, loss = self._step(
            self.params, self._opt_state, self._iteration, batch, rng)
        self._iteration += 1
        self.score_value = float(loss)
        return self.score_value

    def fit_steps(self, batch, n_steps: int) -> float:
        """``n_steps`` updates on ONE device-resident batch inside a
        single ``lax.fori_loop`` dispatch, syncing on the final loss
        once — the benchmark-grade loop (same recipe as
        ``MultiLayerNetwork.fit_steps``: per-step dispatch + loss
        sync through a TPU tunnel is a fixed tax that a fori-loop
        amortizes). Per-step RNG is ``fold_in(rng, i)``."""
        self._ensure_step()
        if getattr(self, "_multi_step", None) is None:
            raw = _raw_step(self._loss_fn, self.updater)

            def multi(params, opt_state, it0, batch, rng, n):
                def body(i, carry):
                    p, s, _ = carry
                    p, s, l = raw(p, s, it0 + i, batch,
                                  jax.random.fold_in(rng, i))
                    return p, s, jnp.float32(l)

                return lax.fori_loop(
                    0, n, body,
                    (params, opt_state, jnp.float32(0)))

            self._multi_step = jax.jit(multi, static_argnums=(5,),
                                       donate_argnums=(0, 1))
        batch = {k: jnp.asarray(v) for k, v in batch.items()
                 if v is not None}
        rng = jax.random.PRNGKey(np.random.randint(0, 2 ** 31))
        self.params, self._opt_state, loss = self._multi_step(
            self.params, self._opt_state, self._iteration, batch,
            rng, n_steps)
        self._iteration += n_steps
        self.score_value = float(loss)
        return self.score_value

    def score(self) -> float:
        return self.score_value

    def layer_report(self, batch, **roofline_kw):
        """Per-functional-block flops/bytes/roofline attribution of
        the compiled train step (common.layerprof): lowers the jitted
        step at ``batch``, partitions ``cost_analysis()`` by the
        ``dl4j.*`` scopes (embeddings / encoder.attention /
        encoder.ffn / pooler / mlm_head / nsp_head for BERT), and
        joins the kernel-select decisions recorded at trace time.
        Lowering only — nothing executes, buffers are not donated."""
        self._ensure_step()
        batch = {k: jnp.asarray(v) for k, v in batch.items()
                 if v is not None}
        lowered = self._step.lower(
            self.params, self._opt_state, self._iteration, batch,
            jax.random.PRNGKey(0))
        return layerprof.attribute_compiled(
            lowered.compile(), model_name=type(self).__name__,
            **roofline_kw)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    # TPU-first knobs
    compute_dtype: str = "float32"        # "bfloat16" for MXU-native
    # remat: False = store all activations; True/"full" = per-layer
    # jax.checkpoint saving nothing (max recompute, min HBM);
    # "dots" = jax.checkpoint(policy=dots_saveable) — matmul outputs
    # are SAVED, only elementwise/softmax recompute (the r4 MFU-sweep
    # winner candidate: recompute cost drops from ~1 fwd to ~0)
    remat: object = False
    # Pallas kernel (t % 128 == 0). Key masks are supported in-kernel;
    # attention-prob dropout is not (needs materialized weights), so
    # training with attention_probs_dropout_prob > 0 uses the dense
    # path — set it to 0.0 to train through the flash kernel.
    use_flash_attention: bool = False
    #: compute q/k/v with ONE [H, 3H] GEMM instead of three [H, H]
    #: GEMMs. Param layout is unchanged (Wq/Wk/Wv stay separate for
    #: the TF-checkpoint 1:1 mapping). Measured NULL on v5e: the
    #: concat sits inside the stacked-layer scan body, is rebuilt on
    #: every remat pass, and cost 8% at the headline config
    #: (BENCH_notes_r04.md) — kept for the record, default off
    fused_qkv: bool = False
    # MLM head on at most this many gathered positions per sequence
    # (the reference TF BERT pretraining knob of the same name);
    # 0 = decode every position. Rows with more masked positions than
    # this train on the first max_predictions_per_seq of them.
    max_predictions_per_seq: int = 0

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny(**kw):
        """Test-scale config (layers=2, hidden=128)."""
        d = dict(vocab_size=1000, hidden_size=128, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=256,
                 max_position_embeddings=128)
        d.update(kw)
        return BertConfig(**d)


def _norm(x, g, b, eps):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def _dropout(x, rate, rng, training):
    if not training or rng is None or rate <= 0.0:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


class Bert(_Trainable):
    """BERT encoder with MLM/NSP pretraining heads.

    params pytree:
      embeddings: word/position/token_type [.,hidden], ln {g,b}
      encoder:    STACKED over layers: each leaf [L, ...]
      pooler:     {W, b}
      mlm:        transform {W, b, ln_g, ln_b}, output bias (decoder
                  weights tied to word embeddings)
      nsp:        {W, b}
    """

    def __init__(self, config: BertConfig,
                 updater: Optional[IUpdater] = None, seed: int = 0):
        self.conf = config
        self.updater = updater or Adam(1e-4)
        self.seed = seed
        self.params = None
        self._opt_state = None
        self._step = None
        self._encode_jit = None
        self.score_value = float("nan")

    # -- init ------------------------------------------------------------
    def init(self) -> "Bert":
        c = self.conf
        key = jax.random.PRNGKey(self.seed)
        ks = iter(jax.random.split(key, 32))
        sd = c.initializer_range
        H, L = c.hidden_size, c.num_hidden_layers

        def tn(k, shape):
            return sd * jax.random.truncated_normal(k, -2, 2, shape,
                                                    jnp.float32)

        def stacked(shape):
            return tn(next(ks), (L,) + shape)

        self.params = {
            "embeddings": {
                "word": tn(next(ks), (c.vocab_size, H)),
                "position": tn(next(ks),
                               (c.max_position_embeddings, H)),
                "token_type": tn(next(ks), (c.type_vocab_size, H)),
                "ln_g": jnp.ones((H,)), "ln_b": jnp.zeros((H,)),
            },
            "encoder": {
                "Wq": stacked((H, H)), "bq": jnp.zeros((L, H)),
                "Wk": stacked((H, H)), "bk": jnp.zeros((L, H)),
                "Wv": stacked((H, H)), "bv": jnp.zeros((L, H)),
                "Wo": stacked((H, H)), "bo": jnp.zeros((L, H)),
                "attn_ln_g": jnp.ones((L, H)),
                "attn_ln_b": jnp.zeros((L, H)),
                "Wi": stacked((H, c.intermediate_size)),
                "bi": jnp.zeros((L, c.intermediate_size)),
                "Wout": stacked((c.intermediate_size, H)),
                "bout": jnp.zeros((L, H)),
                "out_ln_g": jnp.ones((L, H)),
                "out_ln_b": jnp.zeros((L, H)),
            },
            "pooler": {"W": tn(next(ks), (H, H)), "b": jnp.zeros((H,))},
            "mlm": {"W": tn(next(ks), (H, H)), "b": jnp.zeros((H,)),
                    "ln_g": jnp.ones((H,)), "ln_b": jnp.zeros((H,)),
                    "out_b": jnp.zeros((c.vocab_size,))},
            "nsp": {"W": tn(next(ks), (H, 2)), "b": jnp.zeros((2,))},
        }
        return self

    # -- encoder ---------------------------------------------------------
    def _attention(self, lp, x, key_mask, rng, training):
        c = self.conf
        h = c.num_attention_heads
        b, t, H = x.shape
        r_attn = r_out = None
        if rng is not None:
            r_attn, r_out = jax.random.split(rng)

        if c.fused_qkv:
            w = jnp.concatenate([lp["Wq"], lp["Wk"], lp["Wv"]], 1)
            bias = jnp.concatenate([lp["bq"], lp["bk"], lp["bv"]])
            qkv = x @ w + bias
            q, k, v = (split_heads(t, h)
                       for t in jnp.split(qkv, 3, axis=-1))
        else:
            q = split_heads(x @ lp["Wq"] + lp["bq"], h)
            k = split_heads(x @ lp["Wk"] + lp["bk"], h)
            v = split_heads(x @ lp["Wv"] + lp["bv"], h)
        attn_drop = (c.attention_probs_dropout_prob
                     if training and r_attn is not None else 0.0)
        if c.use_flash_attention and attn_drop == 0.0:
            from deeplearning4j_tpu.parallel.sequence import \
                flash_attention
            o = flash_attention(q, k, v, False, 128, 128, None,
                                key_mask)
        else:
            m = None
            if key_mask is not None:
                m = key_mask[:, None, None, :]
            o = dot_product_attention(q, k, v, m,
                                      dropout_rng=r_attn,
                                      dropout_rate=attn_drop)
        o = merge_heads(o)
        o = o @ lp["Wo"] + lp["bo"]
        return _dropout(o, c.hidden_dropout_prob, r_out, training)

    def _layer(self, lp, x, key_mask, rng, training):
        c = self.conf
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        # functional-block attribution scopes (common.layerprof): the
        # encoder is a lax.scan over stacked layer params — one traced
        # body for all L layers — so per-layer-index scopes cannot
        # exist; attention vs FFN is the finest static split
        with layerprof.scope("encoder.attention"):
            a = self._attention(lp, x, key_mask, r1, training)
            x = _norm(x + a, lp["attn_ln_g"], lp["attn_ln_b"],
                      c.layer_norm_eps)
        with layerprof.scope("encoder.ffn"):
            i = jax.nn.gelu(x @ lp["Wi"] + lp["bi"])
            o = _dropout(i @ lp["Wout"] + lp["bout"],
                         c.hidden_dropout_prob, r2, training)
            return _norm(x + o, lp["out_ln_g"], lp["out_ln_b"],
                         c.layer_norm_eps)

    def encode(self, params, input_ids, token_type_ids=None,
               attention_mask=None, *, training=False, rng=None):
        """input_ids [b, t] int32 -> (sequence_output [b, t, H],
        pooled_output [b, H])."""
        c = self.conf
        dt = jnp.dtype(c.compute_dtype)
        b, t = input_ids.shape
        if t > c.max_position_embeddings:
            raise ValueError(
                f"sequence length {t} exceeds max_position_embeddings "
                f"{c.max_position_embeddings} (JAX gather would "
                "silently clamp to the last position)")
        r_emb = None
        if rng is not None:
            rng, r_emb = jax.random.split(rng)
        with layerprof.scope("embeddings"):
            e = params["embeddings"]
            x = e["word"][input_ids]
            x = x + e["position"][jnp.arange(t)][None]
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + e["token_type"][token_type_ids]
            x = _norm(x, e["ln_g"], e["ln_b"], c.layer_norm_eps)
            x = _dropout(x, c.hidden_dropout_prob, r_emb, training)
            x = x.astype(dt)

        key_mask = None
        if attention_mask is not None:
            key_mask = attention_mask.astype(dt)

        L = c.num_hidden_layers
        enc = jax.tree_util.tree_map(lambda a: a.astype(dt),
                                     params["encoder"])

        def body(carry, layer_in):
            x, rng = carry
            lp, i = layer_in
            r = None
            if rng is not None:
                r = jax.random.fold_in(rng, i)
            y = self._layer(lp, x, key_mask, r, training)
            return (y, rng), None

        if not c.remat:
            layer_fn = body
        elif c.remat in (True, "full"):
            layer_fn = jax.checkpoint(body)
        elif c.remat == "dots":
            layer_fn = jax.checkpoint(
                body, policy=jax.checkpoint_policies.dots_saveable)
        else:
            raise ValueError(f"remat={c.remat!r}: use False, True, "
                             f"'full', or 'dots'")
        (x, _), _ = lax.scan(layer_fn, (x, rng),
                             (enc, jnp.arange(L)))

        x = x.astype(jnp.float32)
        with layerprof.scope("pooler"):
            p = params["pooler"]
            pooled = jnp.tanh(x[:, 0] @ p["W"] + p["b"])
        return x, pooled

    # -- heads -----------------------------------------------------------
    def mlm_logits(self, params, sequence_output):
        with layerprof.scope("mlm_head"):
            m = params["mlm"]
            h = jax.nn.gelu(sequence_output @ m["W"] + m["b"])
            h = _norm(h, m["ln_g"], m["ln_b"],
                      self.conf.layer_norm_eps)
            # decoder tied to word embeddings (TF/HF convention)
            return h @ params["embeddings"]["word"].T + m["out_b"]

    def nsp_logits(self, params, pooled_output):
        with layerprof.scope("nsp_head"):
            n = params["nsp"]
            return pooled_output @ n["W"] + n["b"]

    def pretrain_loss(self, params, batch, rng=None, training=True):
        """Masked-LM + next-sentence loss.

        batch keys: input_ids, token_type_ids, attention_mask,
        mlm_labels ([b, t], -1 = unmasked/ignore), nsp_labels ([b]
        int, optional).
        """
        seq, pooled = self.encode(
            params, batch["input_ids"],
            batch.get("token_type_ids"), batch.get("attention_mask"),
            training=training, rng=rng)
        labels = batch["mlm_labels"]
        k = self.conf.max_predictions_per_seq
        if k and k < labels.shape[1]:
            # Gather the (at most k) masked positions per sequence and
            # run the vocab-sized decoder on [b, k, H] instead of
            # [b, t, H] — the reference TF BERT's
            # max_predictions_per_seq design. With ~15% masking the
            # decoder matmul is the single largest head cost; rows
            # with more than k masked positions train on the first k
            # (identical to the reference's truncation).
            masked = labels >= 0
            # stable argsort of "not masked": masked positions first,
            # original order preserved within each group
            pos = jnp.argsort(~masked, axis=1, stable=True)[:, :k]
            labels = jnp.take_along_axis(labels, pos, axis=1)
            seq_sel = jnp.take_along_axis(seq, pos[..., None], axis=1)
        else:
            seq_sel = seq
        logits = self.mlm_logits(params, seq_sel)
        with layerprof.scope("loss"):
            w = (labels >= 0).astype(jnp.float32)
            safe = jnp.maximum(labels, 0)
            logp = jax.nn.log_softmax(logits, -1)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       -1)[..., 0]
            mlm = jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)
            loss = mlm
        if "nsp_labels" in batch and batch["nsp_labels"] is not None:
            nlogits = self.nsp_logits(params, pooled)
            with layerprof.scope("loss"):
                nlogp = jax.nn.log_softmax(nlogits, -1)
                nsp = -jnp.mean(jnp.take_along_axis(
                    nlogp, batch["nsp_labels"][:, None], -1)[:, 0])
                loss = loss + nsp
        return loss

    # -- training (fit_batch from _Trainable) ----------------------------
    def _loss_fn(self, params, batch, rng):
        return self.pretrain_loss(params, batch, rng)

    def output(self, input_ids, token_type_ids=None,
               attention_mask=None):
        """Inference forward: (sequence_output, pooled_output)."""
        if self._encode_jit is None:
            self._encode_jit = jax.jit(functools.partial(
                self.encode, training=False, rng=None))
        return self._encode_jit(
            self.params, jnp.asarray(input_ids),
            None if token_type_ids is None
            else jnp.asarray(token_type_ids),
            None if attention_mask is None
            else jnp.asarray(attention_mask))


class BertForSequenceClassification(_Trainable):
    """Fine-tuning head over a (pretrained) encoder — the reference's
    BERT fine-tune flow (BertIterator supervised mode, D16)."""

    def __init__(self, bert: Bert, num_labels: int,
                 updater: Optional[IUpdater] = None, seed: int = 1):
        self.bert = bert
        self.num_labels = num_labels
        self.updater = updater or Adam(2e-5)
        key = jax.random.PRNGKey(seed)
        H = bert.conf.hidden_size
        # COPY the encoder params: the jitted train step donates its
        # param buffers, so sharing them with `bert` would invalidate
        # the encoder's arrays on the first fine-tune step. fit_batch
        # re-syncs bert.params to the fine-tuned weights.
        self.params = {
            "bert": jax.tree_util.tree_map(jnp.array, bert.params),
            "cls": {"W": bert.conf.initializer_range *
                    jax.random.truncated_normal(key, -2, 2,
                                                (H, num_labels)),
                    "b": jnp.zeros((num_labels,))},
        }
        self._step = None
        self._opt_state = None
        self._logits_jit = None
        self.score_value = float("nan")

    def logits(self, params, input_ids, token_type_ids=None,
               attention_mask=None, training=False, rng=None):
        _, pooled = self.bert.encode(params["bert"], input_ids,
                                     token_type_ids, attention_mask,
                                     training=training, rng=rng)
        return pooled @ params["cls"]["W"] + params["cls"]["b"]

    def _loss_fn(self, params, batch, rng):
        lg = self.logits(params, batch["input_ids"],
                         batch.get("token_type_ids"),
                         batch.get("attention_mask"),
                         training=True, rng=rng)
        logp = jax.nn.log_softmax(lg, -1)
        return -jnp.mean(jnp.take_along_axis(
            logp, batch["labels"][:, None], -1)[:, 0])

    def fit_batch(self, batch) -> float:
        loss = super().fit_batch(batch)
        # keep the encoder object consistent with the fine-tuned weights
        self.bert.params = self.params["bert"]
        return loss

    def predict(self, input_ids, token_type_ids=None,
                attention_mask=None):
        if self._logits_jit is None:
            self._logits_jit = jax.jit(functools.partial(
                self.logits, training=False, rng=None))
        lg = self._logits_jit(
            self.params, jnp.asarray(input_ids),
            None if token_type_ids is None
            else jnp.asarray(token_type_ids),
            None if attention_mask is None
            else jnp.asarray(attention_mask))
        return np.asarray(jnp.argmax(lg, -1))
