"""Flagship distributed Transformer LM — every parallelism strategy in
one jitted train step.

The reference's only distribution story is whole-model data parallelism
(SURVEY.md §2.6 P1–P4: `ParallelWrapper` replicas + gradient sharing
over Aeron; P7–P10 ABSENT). This model is the TPU-native superset: one
``jax.sharding.Mesh`` with axes

- ``data``  — DP: batch sharded; non-expert gradients psum over ICI.
              Also hosts **EP**: MoE expert weights are sharded over
              ``data`` (DeepSpeed-style — expert params replace DP
              replication) and tokens reach their experts via two
              ``all_to_all``s.
- ``pipe``  — PP: contiguous stages of transformer blocks; GPipe
              microbatch schedule via ``lax.ppermute`` + ``lax.scan``
              (:mod:`..parallel.pipeline`), differentiable end-to-end.
- ``model`` — TP: megatron column/row sharding of QKV/out-proj and
              MLP up/down (:mod:`..parallel.tensor`), vocab-parallel
              embedding + cross-entropy. Also hosts **SP** in megatron
              form: norm/residual regions keep activations
              time-sharded over ``model`` (all_gather in,
              reduce_scatter out of each TP region).
- ``seq``   — optional dedicated CP axis: activations time-sharded,
              attention via ring attention (:mod:`..parallel.sequence`,
              K/V blocks rotating over ICI). When present it replaces
              the megatron-SP layout.

The whole step — fwd, bwd, gradient reduction, updater — is ONE
``shard_map`` over the mesh inside ONE ``jax.jit``, so XLA compiles a
single SPMD program with all collectives visible to its scheduler
(overlap with compute), exactly the design SURVEY.md §7 prescribes.

Gradient reduction rule: a parameter leaf's gradient is psum'd over
every mesh axis that does NOT appear in its PartitionSpec, except
``model`` (TP weight grads are complete locally via collective
transposes, and model-replicated leaves compute identical grads on
every TP rank). Expert weights (sharded over ``data``) are complete
via the all_to_all transpose; stage-stacked leaves (sharded over
``pipe``) are local to their stage.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..learning.updaters import IUpdater, Sgd
from ..parallel.expert import init_moe_params, moe_ffn
from ..parallel.pipeline import (from_microbatches, pipeline_apply,
                                 to_microbatches)
from ..parallel.mesh import shard_map as _shard_map
from ..parallel.sequence import ring_attention
from ..parallel.tensor import (init_tp_block_params, layer_norm,
                               row_parallel_dense, sp_all_gather,
                               tp_mlp, tp_self_attention)


@dataclass
class TransformerLMConfig:
    vocab_size: int = 256
    max_len: int = 128
    d_model: int = 64
    n_heads: int = 4
    d_ff: int = 128
    layers_per_stage: int = 2
    n_experts: int = 0          # 0 = dense MLP everywhere (no MoE)
    moe_top_k: int = 2
    moe_capacity: Optional[int] = None   # None = capacity_factor rule
    moe_capacity_factor: float = 1.25
    aux_coef: float = 0.01
    dtype: object = jnp.float32


class DistributedTransformerLM:
    """dp/pp/tp/sp/ep-sharded causal LM with a single-jit train step.

    ``mesh`` must have axes ``data``, ``pipe``, ``model``; an optional
    ``seq`` axis (size>1) switches sequence handling from megatron-SP
    (time sharded over ``model``) to ring-attention CP (time sharded
    over ``seq``).
    """

    def __init__(self, conf: TransformerLMConfig, mesh,
                 updater: Optional[IUpdater] = None, n_micro: int = 4):
        self.conf = conf
        self.mesh = mesh
        self.updater = updater if updater is not None else Sgd(0.1)
        self.n_micro = n_micro
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        for need in ("data", "pipe", "model"):
            if need not in ax:
                raise ValueError(f"mesh needs axis '{need}', has {ax}")
        self.dp = ax["data"]
        self.pp = ax["pipe"]
        self.tp = ax["model"]
        self.sp = ax.get("seq", 1)
        self.ring = self.sp > 1
        if conf.n_heads % self.tp:
            raise ValueError("n_heads must divide by tp")
        if conf.n_experts and conf.n_experts % self.dp:
            raise ValueError("n_experts must divide by dp (EP axis)")
        self._step = None

    # -- parameter structure ------------------------------------------
    def _moe_layer(self, l: int) -> bool:
        """Static MoE placement: last block of every stage is MoE."""
        return (self.conf.n_experts > 0
                and l == self.conf.layers_per_stage - 1)

    def init_global_params(self, seed: int = 0):
        """Full (unsharded) parameter pytree; stage-stacked leaves get
        a leading [n_stages] dim. Same math as the sharded runtime —
        shards are slices of these arrays."""
        c = self.conf
        key = jax.random.PRNGKey(seed)
        k_emb, k_pos, k_head, k_blk = jax.random.split(key, 4)
        stages = []
        for l in range(c.layers_per_stage):
            per_stage = []
            for s in range(self.pp):
                bk = jax.random.fold_in(k_blk,
                                        s * c.layers_per_stage + l)
                p = init_tp_block_params(bk, c.d_model, c.n_heads,
                                         c.d_ff, tp=1, tp_rank=0,
                                         dtype=c.dtype)
                if self._moe_layer(l):
                    del p["mlp"]
                    p["moe"] = init_moe_params(
                        jax.random.fold_in(bk, 7), c.d_model, c.d_ff,
                        c.n_experts, ep=1, ep_rank=0, dtype=c.dtype)
                per_stage.append(p)
            stages.append(jax.tree_util.tree_map(
                lambda *a: jnp.stack(a), *per_stage))
        return {
            "embed": jax.random.normal(
                k_emb, (c.vocab_size, c.d_model), c.dtype) * 0.02,
            "pos": jax.random.normal(
                k_pos, (c.max_len, c.d_model), c.dtype) * 0.02,
            "stages": stages,
            "ln_f_g": jnp.ones((c.d_model,), c.dtype),
            "ln_f_b": jnp.zeros((c.d_model,), c.dtype),
            "head": jax.random.normal(
                k_head, (c.d_model, c.vocab_size), c.dtype)
            * (c.d_model ** -0.5),
        }

    def param_specs(self):
        # The 5D flagship STACKS per-stage blocks on a leading stage
        # dimension and shards that dimension over `pipe` — the one
        # deliberate exception to the "pipe never appears in a
        # PartitionSpec" invariant (the 1F1B fit path keeps stages as
        # stage-local arrays instead; see parallel/speclayout.py).
        # dl4j-lint: disable-file=spec-invariants
        col = P("pipe", None, "model")
        row = P("pipe", "model", None)
        rep = P("pipe", None)
        blk = {
            "ln1_g": rep, "ln1_b": rep, "ln2_g": rep, "ln2_b": rep,
            "attn": {"Wq": col, "Wk": col, "Wv": col, "Wo": row,
                     "bo": rep},
        }
        dense = dict(blk)
        dense["mlp"] = {"Wi": col, "bi": P("pipe", "model"),
                        "Wo": row, "bo": rep}
        moe = dict(blk)
        moe["moe"] = {"Wg": P("pipe", None, None),
                      "Wi": P("pipe", "data", None, None),
                      "Wo": P("pipe", "data", None, None)}
        stages = [moe if self._moe_layer(l) else dense
                  for l in range(self.conf.layers_per_stage)]
        return {
            "embed": P("model", None),     # vocab-parallel rows
            "pos": P(),
            "stages": stages,
            "ln_f_g": P(), "ln_f_b": P(),
            "head": P(None, "model"),      # column-parallel
        }

    def init(self, seed: int = 0):
        """(params, opt_state) placed on the mesh with their specs."""
        params = self.init_global_params(seed)
        opt_state = self.updater.init_state(params)
        specs = self.param_specs()
        ospecs = _state_specs(opt_state, specs)
        put = lambda tree, sp: _zip_map(
            lambda a, s: jax.device_put(a, NamedSharding(self.mesh, s)),
            tree, sp)
        return put(params, specs), put(opt_state, ospecs)

    # -- sharded math (inside shard_map) ------------------------------
    def _embed(self, p, ids):
        """Vocab-parallel embedding + positions. Returns the
        time-LOCAL activation [b, t_local, d]."""
        table = p["embed"]                  # [V/tp, d] local
        vl = table.shape[0]
        rank = lax.axis_index("model")
        loc = ids - rank * vl
        ok = (loc >= 0) & (loc < vl)
        emb = jnp.take(table, jnp.clip(loc, 0, vl - 1), axis=0)
        emb = jnp.where(ok[..., None], emb, 0.0)   # partial per rank
        t = ids.shape[1]
        if self.ring:
            emb = lax.psum(emb, "model")
            off = lax.axis_index("seq") * t
            return emb + lax.dynamic_slice_in_dim(p["pos"], off, t, 0)
        # megatron-SP: reduce the vocab-partial sums AND scatter time
        # over the model axis in one collective. (The transpose is an
        # all_gather of the cotangent, which keeps the vocab-sharded
        # table's gradients local-complete.)
        emb = lax.psum_scatter(emb, "model", scatter_dimension=1,
                               tiled=True)         # [b, t/tp, d]
        tl = t // self.tp
        off = lax.axis_index("model") * tl
        return emb + lax.dynamic_slice_in_dim(p["pos"], off, tl, 0)

    def _attention(self, h, ap, n_heads_local):
        if not self.ring:
            t = h.shape[1] * self.tp        # global length
            mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
            return tp_self_attention(h, ap, n_heads_local,
                                     mask=mask, sequence_parallel=True)
        b, tl, _ = h.shape
        dh = ap["Wq"].shape[-1] // n_heads_local
        hd = lambda a: a.reshape(b, tl, n_heads_local, dh) \
            .transpose(0, 2, 1, 3)
        # use_flash: per-shard Pallas kernels + exact lse merge —
        # measured 320x over the differentiated blockwise ring for a
        # causal seq-8192 train step on v5e (BENCH_notes_r04.md); on
        # CPU backends it runs the exact dense-with-lse reference
        o = ring_attention(hd(h @ ap["Wq"]), hd(h @ ap["Wk"]),
                           hd(h @ ap["Wv"]), "seq", causal=True,
                           use_flash=True)
        o = o.transpose(0, 2, 1, 3).reshape(b, tl, n_heads_local * dh)
        return row_parallel_dense(o, ap["Wo"], ap["bo"], "model")

    def _block(self, p, x, n_heads_local):
        """One transformer block on the local activation layout.
        Returns (x, aux)."""
        c = self.conf
        h = layer_norm(x, p["ln1_g"], p["ln1_b"])
        x = x + self._attention(h, p["attn"], n_heads_local)
        h = layer_norm(x, p["ln2_g"], p["ln2_b"])
        if "moe" in p:
            # each rank routes its LOCAL tokens (time-sharded under
            # megatron-SP, seq-sharded under ring); EP all_to_all over
            # `data`. Expert grads: complete over data (a2a
            # transpose), partial over the time-sharding axis — the
            # reduction rule psums them there.
            y, aux = moe_ffn(h, p["moe"], axis="data",
                             k=c.moe_top_k, capacity=c.moe_capacity,
                             capacity_factor=c.moe_capacity_factor)
            if not self.ring:
                # make the loss (hence every rank's cotangent scale)
                # identical across model ranks
                aux = lax.pmean(aux, "model")
            return x + y, aux
        return (x + tp_mlp(h, p["mlp"], "model",
                           sequence_parallel=not self.ring),
                jnp.zeros((), x.dtype))

    def _loss_local(self, params, ids, labels):
        """Scalar loss (replicated across the mesh) from local shards.
        ids/labels: [b_local, t_local] int32."""
        c = self.conf
        hl = c.n_heads // self.tp
        x = self._embed(params, ids)
        xm = to_microbatches(x, self.n_micro)

        def stage_fn(stage_params, xx):
            aux_t = jnp.zeros((), xx.dtype)
            for l in range(c.layers_per_stage):
                bp = jax.tree_util.tree_map(lambda a: a[0],
                                            stage_params[l])
                xx, aux = self._block(bp, xx, hl)
                aux_t = aux_t + aux
            return xx, aux_t

        outs, aux_sum = pipeline_apply(
            stage_fn, params["stages"], xm, with_aux=True,
            varying_axes=tuple(self.mesh.axis_names))
        h = from_microbatches(outs)            # [b_local, t_local, d]
        h = layer_norm(h, params["ln_f_g"], params["ln_f_b"])
        if not self.ring:
            h = sp_all_gather(h, "model")      # [b_local, t, d]
        logits = h @ params["head"]            # [.., t, V/tp] local
        ce = _vocab_parallel_xent(logits, labels)
        ce_mean = jnp.mean(ce)

        stage = lax.axis_index("pipe")
        last = (stage == self.pp - 1).astype(ce_mean.dtype)
        local = ce_mean * last + c.aux_coef * aux_sum / self.n_micro
        loss = lax.psum(local, "pipe")
        loss = lax.pmean(loss, "data")
        if self.ring:
            loss = lax.pmean(loss, "seq")
        return loss

    # -- gradient reduction -------------------------------------------
    def _reduce_grads(self, grads, specs):
        """Cross-rank gradient reduction.

        Under jax's VMA-typed shard_map (jax >= 0.8, ``lax.pcast``
        exists) this is a NO-OP: every implicit unvarying→varying cast
        in the forward (a replicated param meeting a data/seq/time-
        sharded activation) transposes to a psum over exactly the
        right axes, so the grads arriving here are already complete —
        verified leaf-for-leaf against a single-device reference in
        test_transformer_5d. On older jax the manual rule applies:
        psum each leaf over EVERY mesh axis absent from its
        PartitionSpec. Size-1 axes are psummed too — numerically a
        no-op, but it is what marks the leaf replicated over that
        axis for the shard_map replication checker (skipping them is
        why the ring-CP step used to be rejected by check_rep: a
        size-1 ``data`` axis never entered the grads' inferred
        replication set, so the params' out_specs failed)."""
        if hasattr(lax, "pcast"):
            return grads
        present = set(self.mesh.axis_names)

        def red(g, spec):
            named = set()
            for entry in tuple(spec):
                if entry is None:
                    continue
                if isinstance(entry, (tuple, list)):
                    named.update(entry)
                else:
                    named.add(entry)
            todo = tuple(ax for ax in present if ax not in named)
            return lax.psum(g, todo) if todo else g

        return _zip_map(red, grads, specs)

    # -- public API ----------------------------------------------------
    def data_specs(self):
        if self.ring:
            return P("data", "seq")
        return P("data", None)

    def build_train_step(self):
        specs = self.param_specs()
        # opt-state specs mirror param specs leaf-for-leaf
        ospecs = _state_specs(
            jax.eval_shape(self.updater.init_state,
                           jax.eval_shape(self.init_global_params)),
            specs)
        dsp = self.data_specs()

        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))

        def objective(params, ids, labels):
            loss = self._loss_local(params, ids, labels)
            # the scalar is numerically replicated over its remaining
            # varying axes (e.g. `model`: every TP rank stitches the
            # same CE). Autodiff sums all rank-copies through the
            # collective transposes, so each rank must contribute
            # loss/n_copies for the grads to come out exactly dL/dθ
            # (verified leaf-for-leaf in test_transformer_5d). Under
            # VMA-typed jax (>= 0.8) the copy count is the product of
            # the loss's varying axes; on older jax there is no vma
            # type and every rank of the whole mesh seeds cotangent 1
            # through the rep-checked transpose, so the count is the
            # full mesh size.
            if hasattr(lax, "pcast"):
                vma = tuple(getattr(getattr(loss, "aval", None),
                                    "vma", ()))
                scale = int(np.prod([sizes.get(a, 1)
                                     for a in vma])) or 1
            else:
                scale = int(np.prod(list(sizes.values()))) or 1
            return loss / scale, loss

        def body(params, opt_state, ids, labels, it):
            grads, loss = jax.grad(objective, has_aux=True)(
                params, ids, labels)
            grads = self._reduce_grads(grads, specs)
            upd, new_state = self.updater.apply(grads, opt_state, it)
            new_params = jax.tree_util.tree_map(
                lambda p_, u: p_ - u, params, upd)
            return new_params, new_state, _unvary(loss, self.mesh)

        fn = _shard_map(body, self.mesh,
                        in_specs=(specs, ospecs, dsp, dsp, P()),
                        out_specs=(specs, ospecs, P()))
        self._step = jax.jit(fn, donate_argnums=(0, 1))
        return self._step

    def train_step(self, params, opt_state, ids, labels, it=0):
        if self._step is None:
            self.build_train_step()
        it = jnp.asarray(it, jnp.int32)
        return self._step(params, opt_state, ids, labels, it)


def _axsize(mesh, ax):
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(ax, 1)


def _zip_map(f, tree, specs):
    """tree_map over (array-tree, spec-tree) that treats PartitionSpec
    entries as leaves regardless of their pytree registration."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    s_flat = jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    assert len(flat) == len(s_flat), (len(flat), len(s_flat))
    return jax.tree_util.tree_unflatten(
        treedef, [f(a, s) for a, s in zip(flat, s_flat)])


def _state_specs(state, specs):
    """Opt-state spec tree: every state leaf mirrors its param leaf
    (updater states are {name: param-shaped tree} maps, or ())."""
    if isinstance(state, tuple) and state == ():
        return ()
    return {k: specs for k in state}


def _vocab_parallel_xent(logits_local, labels, axis: str = "model"):
    """Per-token cross-entropy with the vocab dim sharded over
    ``axis`` (megatron): max/sum/target-logit stitched by pmax/psum."""
    vl = logits_local.shape[-1]
    rank = lax.axis_index(axis)
    # the stabilizer is mathematically a constant — stop_gradient both
    # dodges pmax's missing diff rule and skips a useless backward op
    m = lax.pmax(jnp.max(lax.stop_gradient(logits_local), -1), axis)
    e = jnp.sum(jnp.exp(logits_local - m[..., None]), -1)
    lse = jnp.log(lax.psum(e, axis)) + m
    loc = labels - rank * vl
    ok = (loc >= 0) & (loc < vl)
    tgt = jnp.take_along_axis(
        logits_local, jnp.clip(loc, 0, vl - 1)[..., None], -1)[..., 0]
    tgt = lax.psum(jnp.where(ok, tgt, 0.0), axis)
    return lse - tgt


def _unvary(x, mesh):
    """Type a numerically-replicated scalar as unvarying on every mesh
    axis (needed for out_specs=P() under shard_map VMA checking).
    psum over the still-varying axes multiplies the value by their
    total size, so divide it back out — numerically a no-op that gives
    the checker the collective it wants."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    axes = tuple(getattr(getattr(x, "aval", None), "vma", ())
                 ) or tuple(mesh.axis_names)
    n = int(np.prod([sizes.get(a, 1) for a in axes]))
    return lax.psum(x, axes) / n
