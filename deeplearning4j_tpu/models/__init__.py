from deeplearning4j_tpu.models.zoo import (  # noqa: F401
    ZooModel, LeNet, SimpleCNN, VGG16, VGG19, ResNet50, AlexNet)
from deeplearning4j_tpu.models.bert import (  # noqa: F401
    Bert, BertConfig, BertForSequenceClassification)
