from deeplearning4j_tpu.models.zoo import (  # noqa: F401
    ZooModel, LeNet, SimpleCNN, VGG16, VGG19, ResNet50, AlexNet)
from deeplearning4j_tpu.models.zoo_extra import (  # noqa: F401
    Darknet19, InceptionResNetV1, NASNet, SqueezeNet,
    TextGenerationLSTM, TinyYOLO, UNet, Xception, YOLO2)
from deeplearning4j_tpu.models.bert import (  # noqa: F401
    Bert, BertConfig, BertForSequenceClassification)
from deeplearning4j_tpu.models.transformer import (  # noqa: F401
    DistributedTransformerLM, TransformerLMConfig)
from deeplearning4j_tpu.models.decoder import (  # noqa: F401
    DecoderConfig, DecoderLM)
