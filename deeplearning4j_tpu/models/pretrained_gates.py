"""Quality gates for the bundled pretrained checkpoints (SURVEY.md
D15; round-2 verdict Weak #4: a gate that cannot fail is a plumbing
test).  Single source of truth for the hard-split configuration —
imported by scripts/train_pretrained.py (gate at train time) and
tests/test_pretrained_zoo.py (gate on the committed artifact)."""
from __future__ import annotations

import numpy as np

#: signal fraction of the HARD held-out split (training mixes at 0.6;
#: 0.45 measures ~0.7 accuracy on the shipped checkpoint — below
#: saturation, so regressions are observable)
HARD_TEMPLATE_WEIGHT = 0.45
#: (min, max) accuracy bounds the hard split must land in
HARD_GATE = (0.60, 0.999)


def eval_resnet_cifar_hard(net, n: int = 2000) -> float:
    """Accuracy of ``net`` on the hard held-out CIFAR surrogate."""
    from deeplearning4j_tpu.datasets.vision import synthetic_images
    xs, ys = synthetic_images(
        n, 32, 32, 3, 10, train=False, seed=123,
        template_weight=HARD_TEMPLATE_WEIGHT)
    probs = np.asarray(net.output(xs))
    return float((probs.argmax(-1) == ys).mean())
