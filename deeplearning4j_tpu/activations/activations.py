"""Activation functions.

Reference parity: ``org.nd4j.linalg.activations.Activation`` enum + the
``IActivation`` impls (SURVEY.md J8). Each member maps to a jax callable;
backprop comes from jax autodiff rather than the reference's hand-written
``backprop(in, epsilon)`` pairs. All lower to fused XLA elementwise HLO.
"""
from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

_SELU_ALPHA = 1.6732632423543772
_SELU_SCALE = 1.0507009873554805


def _cube(x):
    return x * x * x


def _rational_tanh(x):
    # reference RationalTanh: 1.7159 * tanh_approx(2x/3)
    a = 0.6666667 * x
    approx = jnp.sign(a) * (1.0 - 1.0 / (1.0 + jnp.abs(a) + a * a +
                                         1.41645 * a * a * a * a))
    return 1.7159 * approx


def _rectified_tanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def _threshold_relu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


class Activation(enum.Enum):
    CUBE = "cube"
    ELU = "elu"
    GELU = "gelu"
    HARDSIGMOID = "hardsigmoid"
    HARDTANH = "hardtanh"
    IDENTITY = "identity"
    LEAKYRELU = "leakyrelu"
    MISH = "mish"
    RATIONALTANH = "rationaltanh"
    RECTIFIEDTANH = "rectifiedtanh"
    RELU = "relu"
    RELU6 = "relu6"
    SELU = "selu"
    SIGMOID = "sigmoid"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    SWISH = "swish"
    TANH = "tanh"
    THRESHOLDEDRELU = "thresholdedrelu"

    def fn(self):
        return _FNS[self]

    def __call__(self, x):
        return _FNS[self](x)

    @staticmethod
    def from_name(name: str) -> "Activation":
        return Activation[name.upper()]


_FNS = {
    Activation.CUBE: _cube,
    Activation.ELU: jax.nn.elu,
    Activation.GELU: jax.nn.gelu,
    Activation.HARDSIGMOID: jax.nn.hard_sigmoid,
    Activation.HARDTANH: lambda x: jnp.clip(x, -1.0, 1.0),
    Activation.IDENTITY: lambda x: x,
    Activation.LEAKYRELU: lambda x: jax.nn.leaky_relu(x, 0.01),
    Activation.MISH: jax.nn.mish,
    Activation.RATIONALTANH: _rational_tanh,
    Activation.RECTIFIEDTANH: _rectified_tanh,
    Activation.RELU: jax.nn.relu,
    Activation.RELU6: jax.nn.relu6,
    Activation.SELU: jax.nn.selu,
    Activation.SIGMOID: jax.nn.sigmoid,
    Activation.SOFTMAX: lambda x: jax.nn.softmax(x, axis=-1),
    Activation.SOFTPLUS: jax.nn.softplus,
    Activation.SOFTSIGN: jax.nn.soft_sign,
    Activation.SWISH: jax.nn.swish,
    Activation.TANH: jnp.tanh,
    Activation.THRESHOLDEDRELU: _threshold_relu,
}
