from deeplearning4j_tpu.activations.activations import Activation  # noqa: F401
