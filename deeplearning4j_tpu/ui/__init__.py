"""Training UI / stats subsystem (SURVEY.md D17).

Reference: `deeplearning4j-ui` — `StatsListener` collects per-iteration
model statistics into a `StatsStorage` (in-memory / file), and the
Vert.x `VertxUIServer` renders them. Here the storage formats are
in-memory and JSONL-on-disk (machine-readable; any dashboard can tail
it), a static-HTML report renderer, and a live stdlib-HTTP `UIServer`
(VertxUIServer parity: attach a storage, watch during training).

The Chrome-trace `ProfilingListener` (SURVEY.md S8/§5.1) writes
chrome://tracing-compatible JSON for per-iteration timing.
"""
from .stats import (FileStatsStorage, InMemoryStatsStorage,
                    StatsListener, render_html_report)
from .profiling import ProfilingListener
from .server import UIServer
from ..common.telemetry import MetricsRegistry, MetricsReporterListener

__all__ = ["StatsListener", "InMemoryStatsStorage",
           "FileStatsStorage", "render_html_report",
           "ProfilingListener", "UIServer",
           "MetricsRegistry", "MetricsReporterListener"]
