"""Training UI / stats subsystem (SURVEY.md D17).

Reference: `deeplearning4j-ui` — `StatsListener` collects per-iteration
model statistics into a `StatsStorage` (in-memory / file), and the
Vert.x `VertxUIServer` renders them. Here the storage formats are
in-memory and JSONL-on-disk (machine-readable; any dashboard can tail
it), plus a static-HTML report renderer in place of the live web
server (zero-dependency, works over a shared filesystem).

The Chrome-trace `ProfilingListener` (SURVEY.md S8/§5.1) writes
chrome://tracing-compatible JSON for per-iteration timing.
"""
from .stats import (FileStatsStorage, InMemoryStatsStorage,
                    StatsListener, render_html_report)
from .profiling import ProfilingListener

__all__ = ["StatsListener", "InMemoryStatsStorage",
           "FileStatsStorage", "render_html_report",
           "ProfilingListener"]
