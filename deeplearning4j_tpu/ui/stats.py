"""StatsListener + StatsStorage (reference:
`org.deeplearning4j.ui.model.stats.StatsListener`,
`org.deeplearning4j.ui.model.storage.{InMemoryStatsStorage,
FileStatsStorage}` — SURVEY.md D17/§5.5).

Collected per report (every ``frequency`` iterations): score,
per-layer parameter/update/activation summary stats (mean absolute
value + histograms), update:parameter ratios (the reference UI's
headline training-health chart), iteration timing, and memory info.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

import numpy as np

from ..optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")


def _summary(arr, bins: int = 20) -> dict:
    a = np.asarray(arr, np.float32).ravel()
    if a.size == 0:
        return {"mean_abs": 0.0, "mean": 0.0, "std": 0.0,
                "hist": [], "edges": []}
    hist, edges = np.histogram(a, bins=bins)
    return {"mean_abs": float(np.abs(a).mean()),
            "mean": float(a.mean()), "std": float(a.std()),
            "hist": hist.tolist(),
            "edges": [float(e) for e in edges]}


class InMemoryStatsStorage:
    """reference: InMemoryStatsStorage."""

    def __init__(self):
        self.reports: List[dict] = []

    def put_report(self, report: dict):
        self.reports.append(report)

    def get_reports(self) -> List[dict]:
        return list(self.reports)

    def latest(self) -> Optional[dict]:
        return self.reports[-1] if self.reports else None


class FileStatsStorage(InMemoryStatsStorage):
    """JSONL-on-disk storage (reference: FileStatsStorage's mapdb
    file, re-designed as line-delimited JSON so anything can tail it)."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        #: a crash mid-append can leave a newline-less tail — the next
        #: append must not glue onto it
        self._tail_open = False
        try:                       # load existing reports (resume)
            with open(path) as f:
                l = ""
                for lineno, l in enumerate(f, 1):
                    if not l.strip():
                        continue
                    try:
                        self.reports.append(json.loads(l))
                    except ValueError:
                        # a crash mid-append leaves a truncated tail
                        # line; resuming must not die on it
                        log.warning(
                            "skipping corrupt report on line %d of "
                            "%s", lineno, path)
                self._tail_open = bool(l) and not l.endswith("\n")
        except FileNotFoundError:
            pass

    def put_report(self, report: dict):
        super().put_report(report)
        # one write + flush-to-disk per report: a reader tailing the
        # file (or a resume after a crash) sees whole lines only
        line = json.dumps(report) + "\n"
        if self._tail_open:
            line = "\n" + line
        with open(self.path, "a") as f:
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self._tail_open = False


class StatsListener(TrainingListener):
    """Collects model stats into a StatsStorage every N iterations
    (reference: StatsListener(statsStorage, frequency))."""

    def __init__(self, storage=None, frequency: int = 1,
                 histograms: bool = True):
        self.storage = storage if storage is not None \
            else InMemoryStatsStorage()
        self.frequency = max(1, int(frequency))
        self.histograms = histograms
        self._last_params: Optional[Dict[str, np.ndarray]] = None
        self._last_time = None

    def _param_table(self, model) -> Dict[str, np.ndarray]:
        if hasattr(model, "param_table"):
            return {k: np.asarray(v) for k, v in
                    model.param_table().items()}
        return {}

    def iteration_done(self, model, iteration: int, epoch: int):
        if iteration % self.frequency:
            self._last_params = None
            return
        now = time.time()
        params = self._param_table(model)
        report = {
            "iteration": iteration,
            "epoch": epoch,
            "time": now,
            "score": float(model.score()),
            "iter_seconds": (now - self._last_time
                             if self._last_time else None),
        }
        layers: Dict[str, dict] = {}
        for k, p in params.items():
            entry = {"param": _summary(p) if self.histograms else
                     {"mean_abs": float(np.abs(p).mean())}}
            if self._last_params is not None and \
                    k in self._last_params:
                upd = p - self._last_params[k]
                entry["update"] = (_summary(upd) if self.histograms
                                   else {"mean_abs":
                                         float(np.abs(upd).mean())})
                pm = float(np.abs(p).mean())
                um = float(np.abs(upd).mean())
                # update:param mean-magnitude ratio — the canonical
                # learning-health signal (~1e-3 is healthy)
                entry["update_param_ratio"] = (um / pm if pm > 0
                                               else 0.0)
            layers[k] = entry
        report["layers"] = layers
        self.storage.put_report(report)
        self._last_params = params
        self._last_time = now


def render_html_report(storage, path: str, title: str = "Training"):
    """Static single-file HTML dashboard from a StatsStorage —
    score curve, update:param ratios, iteration timings (the
    reference's Vert.x overview page, server-free)."""
    reports = storage.get_reports()
    iters = [r["iteration"] for r in reports]
    scores = [r["score"] for r in reports]
    ratio_keys = sorted({k for r in reports
                         for k, v in r.get("layers", {}).items()
                         if "update_param_ratio" in v})
    ratios = {k: [r["layers"].get(k, {}).get("update_param_ratio")
                  for r in reports] for k in ratio_keys}
    data = json.dumps({"iters": iters, "scores": scores,
                       "ratios": ratios})
    html = f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>body{{font-family:sans-serif;margin:2em}}
.chart{{margin-bottom:2em}}</style></head>
<body><h1>{title}</h1>
<div class="chart"><h3>Score vs iteration</h3>
<canvas id="score" width="800" height="240"></canvas></div>
<div class="chart"><h3>log10 update:param ratio</h3>
<canvas id="ratio" width="800" height="240"></canvas></div>
<script>
const D = {data};
function plot(id, series) {{
  const c = document.getElementById(id), g = c.getContext('2d');
  const all = series.flatMap(s => s.ys).filter(v => v != null &&
      isFinite(v));
  if (!all.length) return;
  const ymin = Math.min(...all), ymax = Math.max(...all) || 1;
  const xs = D.iters, xmin = Math.min(...xs),
        xmax = Math.max(...xs) || 1;
  series.forEach((s, si) => {{
    g.strokeStyle = `hsl(${{si * 57 % 360}},70%,45%)`;
    g.beginPath();
    s.ys.forEach((y, i) => {{
      if (y == null || !isFinite(y)) return;
      const px = 40 + (xs[i] - xmin) / (xmax - xmin || 1) * 740;
      const py = 220 - (y - ymin) / (ymax - ymin || 1) * 200;
      i ? g.lineTo(px, py) : g.moveTo(px, py);
    }});
    g.stroke();
  }});
}}
plot('score', [{{ys: D.scores}}]);
plot('ratio', Object.values(D.ratios).map(r => ({{
  ys: r.map(v => v > 0 ? Math.log10(v) : null)}})));
</script></body></html>"""
    with open(path, "w") as f:
        f.write(html)
    return path
