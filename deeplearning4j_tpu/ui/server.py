"""Live training dashboard server.

Reference parity: ``org.deeplearning4j.ui.VertxUIServer`` (SURVEY.md
D17): ``UIServer.getInstance().attach(statsStorage)`` then watch the
dashboard during training. Vert.x + WebSocket push is re-designed as a
stdlib ``ThreadingHTTPServer`` + polling fetch: zero dependencies, same
charts (score curve, update:param ratio), and the storage contract is
identical — any InMemoryStatsStorage/FileStatsStorage can be attached,
during or after training.

Endpoints:
- ``/``             live dashboard (auto-refreshes every 2s)
- ``/api/reports``  all reports of every attached storage (JSON)
- ``/api/latest``   most recent report (JSON)
- ``/api/memory``   per-buffer HBM attribution report (JSON —
  ``common.diagnostics.memory_report``)
- ``/metrics``      process-wide telemetry registry in Prometheus
  text exposition format (``common.telemetry.MetricsRegistry``) —
  point a Prometheus scrape job (or ``curl``) at it
- ``/api/profile``  scaling-observatory on-demand profiling:
  ``POST /api/profile?steps=N`` starts a bounded capture (409 while
  one is active); ``GET`` returns capture status + last result
  (``common.stepstats.ProfileCapture``; ``scripts/dl4j_profile.py``
  is the CLI wrapper)
- ``/api/layers``   last per-layer attribution report (JSON — flops /
  bytes / roofline / kernel decision per layer,
  ``common.layerprof``; 404 until a ``model.layer_report()`` ran;
  ``scripts/dl4j_layers.py`` is the CLI table)
"""
from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer
from typing import List, Optional

from deeplearning4j_tpu.common.httputil import (QuietHandler,
                                                start_http_server)


_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>deeplearning4j_tpu UI</title>
<style>body{font-family:sans-serif;margin:2em}
.chart{margin-bottom:2em}</style></head>
<body><h1>Training dashboard</h1>
<div>iteration: <b id="it">-</b> &nbsp; score: <b id="sc">-</b></div>
<div class="chart"><h3>Score vs iteration</h3>
<canvas id="score" width="800" height="240"></canvas></div>
<div class="chart"><h3>log10 update:param ratio</h3>
<canvas id="ratio" width="800" height="240"></canvas></div>
<script>
function plot(id, xs, series) {
  const c = document.getElementById(id), g = c.getContext('2d');
  g.clearRect(0, 0, c.width, c.height);
  const all = series.flatMap(s => s).filter(v => v != null &&
      isFinite(v));
  if (!all.length) return;
  const ymin = Math.min(...all), ymax = Math.max(...all);
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  series.forEach((ys, si) => {
    g.strokeStyle = `hsl(${si * 57 % 360},70%,45%)`;
    g.beginPath();
    let started = false;
    ys.forEach((y, i) => {
      if (y == null || !isFinite(y)) return;
      const px = 40 + (xs[i] - xmin) / (xmax - xmin || 1) * 740;
      const py = 220 - (y - ymin) / (ymax - ymin || 1) * 200;
      started ? g.lineTo(px, py) : g.moveTo(px, py);
      started = true;
    });
    g.stroke();
  });
}
async function tick() {
  try {
    const rs = await (await fetch('/api/reports')).json();
    if (rs.length) {
      const last = rs[rs.length - 1];
      document.getElementById('it').textContent = last.iteration;
      document.getElementById('sc').textContent =
          last.score.toFixed(5);
      const iters = rs.map(r => r.iteration);
      plot('score', iters, [rs.map(r => r.score)]);
      const keys = [...new Set(rs.flatMap(r =>
          Object.entries(r.layers || {})
              .filter(([k, v]) => 'update_param_ratio' in v)
              .map(([k]) => k)))];
      plot('ratio', iters, keys.map(k => rs.map(r => {
        const v = (r.layers || {})[k];
        return v && v.update_param_ratio > 0 ?
            Math.log10(v.update_param_ratio) : null;
      })));
    }
  } catch (e) {}
  setTimeout(tick, 2000);
}
tick();
</script></body></html>"""


class UIServer:
    """Singleton live dashboard (reference: UIServer.getInstance())."""

    _instance: Optional["UIServer"] = None

    def __init__(self):
        self._storages: List = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    @classmethod
    def get_instance(cls) -> "UIServer":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def attach(self, storage) -> "UIServer":
        if storage not in self._storages:
            self._storages.append(storage)
        return self

    def detach(self, storage) -> "UIServer":
        if storage in self._storages:
            self._storages.remove(storage)
        return self

    # ------------------------------------------------------------------
    def start(self, port: int = 9000) -> "UIServer":
        """Serve on ``DL4J_TPU_HTTP_HOST``:port (0 picks a free port;
        see ``self.port``). Idempotent."""
        if self._httpd is not None:
            return self
        server = self

        class Handler(QuietHandler):
            def do_GET(self):               # noqa: N802
                if self.path == "/" or self.path.startswith("/train"):
                    self.send_html(_PAGE)
                elif self.path == "/api/reports":
                    reports = []
                    for s in server._storages:
                        reports.extend(s.get_reports())
                    self.send_json(reports)
                elif self.path == "/api/latest":
                    latest = None
                    for s in server._storages:
                        r = s.latest()
                        if r and (latest is None or
                                  r["time"] > latest["time"]):
                            latest = r
                    self.send_json(latest)
                elif self.path == "/api/memory":
                    from deeplearning4j_tpu.common import diagnostics
                    try:
                        self.send_json(diagnostics.memory_report())
                    except Exception as e:   # noqa: BLE001
                        self.send_json({"error": repr(e)}, 500)
                elif self.path == "/api/layers":
                    from deeplearning4j_tpu.common import layerprof
                    try:
                        rep = layerprof.last_report()
                        if rep is None:
                            self.send_json(
                                {"error": "no layer report computed "
                                 "yet (run model.layer_report())"},
                                404)
                        else:
                            self.send_json(rep)
                    except Exception as e:   # noqa: BLE001
                        self.send_json({"error": repr(e)}, 500)
                elif self.path == "/metrics":
                    self.send_metrics()
                elif self.path.split("?")[0] == "/api/profile":
                    from deeplearning4j_tpu.common.stepstats import \
                        ProfileCapture
                    self.send_json(ProfileCapture.current_status())
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):              # noqa: N802
                path, _, query = self.path.partition("?")
                if path != "/api/profile":
                    self.send_json({"error": "not found"}, 404)
                    return
                from urllib.parse import parse_qs

                from deeplearning4j_tpu.common.stepstats import (
                    CaptureActiveError, ProfileCapture)
                q = parse_qs(query)
                try:
                    steps = int(q.get("steps", ["20"])[0])
                    expire = q.get("expire_seconds", [None])[0]
                    status = ProfileCapture.start(
                        steps,
                        out_dir=(q.get("out_dir", [None])[0]),
                        use_jax=q.get("jax", ["1"])[0] not in ("0",
                                                               "false"),
                        expire_seconds=(float(expire)
                                        if expire is not None
                                        else None))
                    self.send_json({"started": True, **status})
                except CaptureActiveError as e:
                    # one capture at a time: concurrent POSTs conflict
                    self.send_json({"started": False,
                                    "error": str(e)}, 409)
                except (ValueError, OSError) as e:
                    self.send_json({"started": False,
                                    "error": repr(e)}, 400)

        self._httpd, self._thread = start_http_server(Handler, port)
        self.port = self._httpd.server_address[1]
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
            self.port = None

    @property
    def url(self) -> Optional[str]:
        if not self.port:
            return None
        host = self._httpd.server_address[0] if self._httpd else \
            "127.0.0.1"
        if host in ("0.0.0.0", "::"):   # wildcard bind: loopback works
            host = "127.0.0.1"
        return f"http://{host}:{self.port}"
