"""Chrome-trace profiling listener (reference:
`org.nd4j.autodiff.listeners.profiler.ProfilingListener` — SURVEY.md
S8/§5.1: writes chrome://tracing JSON).

On TPU, per-op timing inside a jitted step is invisible from Python
(XLA fuses the whole step) — use ``jax.profiler`` for op-level TPU
traces. This listener records what the host CAN see — iteration and
epoch spans, scores — in the same chrome://tracing format so both
traces load into one timeline.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import List, Optional

from ..optimize.listeners import TrainingListener

log = logging.getLogger("deeplearning4j_tpu")


class ProfilingListener(TrainingListener):
    def __init__(self, output_path: str, max_events: int = 100_000):
        self.output_path = output_path
        self.max_events = max_events
        self.events: List[dict] = []
        #: events silently discarded past ``max_events`` — surfaced in
        #: the trace metadata and warned once at flush, so a truncated
        #: trace is never mistaken for a complete one
        self.dropped = 0
        self._warned_drop = False
        self._iter_start: Optional[float] = None
        self._epoch_start: Optional[float] = None
        self._pid = os.getpid()

    def _us(self, t: float) -> int:
        return int(t * 1e6)

    def _emit(self, name: str, start: float, end: float, args=None):
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return
        self.events.append({
            "name": name, "ph": "X", "pid": self._pid, "tid": 1,
            "ts": self._us(start), "dur": self._us(end - start),
            "args": args or {}})

    def on_epoch_start(self, model):
        self._epoch_start = time.time()

    def on_epoch_end(self, model):
        if self._epoch_start is not None:
            self._emit("epoch", self._epoch_start, time.time())
            self._epoch_start = None
        self.flush()

    def iteration_done(self, model, iteration: int, epoch: int):
        now = time.time()
        if self._iter_start is None:
            self._iter_start = now
            return
        self._emit(f"iteration {iteration}", self._iter_start, now,
                   {"iteration": iteration, "epoch": epoch,
                    "score": float(model.score())})
        self._iter_start = now

    def flush(self) -> str:
        if self.dropped and not self._warned_drop:
            self._warned_drop = True
            log.warning(
                "ProfilingListener dropped %d events past "
                "max_events=%d — the trace is truncated; raise "
                "max_events or profile a shorter window",
                self.dropped, self.max_events)
        with open(self.output_path, "w") as f:
            json.dump({"traceEvents": self.events,
                       "displayTimeUnit": "ms",
                       "metadata": {"dropped_events": self.dropped}},
                      f)
        return self.output_path
