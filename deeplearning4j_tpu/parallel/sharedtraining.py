"""SharedTrainingMaster: cluster (multi-host) data-parallel training.

Reference parity (SURVEY.md P3–P5, call stack 3.5):
``org.deeplearning4j.spark.parameterserver.training.SharedTrainingMaster``
— Spark driver broadcasts config+params, per-executor
`SharedTrainingWrapper`s train on local GPUs, threshold-encoded updates
traverse an Aeron UDP mesh (`MeshOrganizer` tree), driver collects.

TPU-first design (BASELINE.json north star): Spark, Aeron, the mesh
organizer and the parameter server all disappear. Their roles map to:

- Spark driver / cluster membership -> ``jax.distributed`` gRPC
  coordinator (`initialize(coordinator_address, num_processes,
  process_id)`);
- per-executor workers + Aeron update exchange -> ONE global
  ``jax.sharding.Mesh`` over every chip of every host; the gradient
  all-reduce is compiled into the train step and rides ICI within a
  slice and DCN across slices;
- driver's canonical params -> replicated params, identical on all
  hosts by construction (exact synchronous SGD — stronger than the
  reference's async encoded updates);
- `RDD<DataSet>` partitions -> each process feeds its LOCAL batch
  shard; `jax.make_array_from_process_local_data` assembles the global
  sharded batch.

Threshold compression (the reference's wire format) is preserved as an
optional gradient transform in `parallel.encoding`, not as a wire
protocol — dense XLA AllReduce is bandwidth-optimal on ICI.
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.encoding import (AdaptiveThresholdAlgorithm,
                                                  ThresholdAlgorithm)
from deeplearning4j_tpu.parallel.mesh import DEFAULT_DATA_AXIS, make_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

log = logging.getLogger("deeplearning4j_tpu")


@dataclass
class SharedTrainingConfiguration:
    """Reference: SharedTrainingMaster.Builder knobs. Aeron/unicast/port
    knobs have no equivalent; threshold/residual knobs are accepted for
    API parity but the exchange is a dense in-step AllReduce (logged at
    fit time) — `parallel.encoding` holds the compression transform."""
    batch_size_per_worker: int = 32
    workers_per_node: int = -1          # -1 = all local devices
    threshold_algorithm: Optional[ThresholdAlgorithm] = None
    residual_post_processor: object = None
    # how replicas exchange the weight update: 'dense' (AllReduce +
    # replicated update), 'sharded' (ZeRO-1 ReduceScatter/AllGather —
    # parallel.zero), 'fsdp' (ZeRO-3: params resident 1/N with
    # just-in-time per-layer gathers), 'encoded' (ZeRO-1 with the flat
    # gradient compressed before the collective — the reference's
    # threshold/residual knobs above become LIVE and shape the codec),
    # 'auto' (sharded whenever legal)
    update_exchange: str = "auto"
    # EncodingSpec or scheme string for update_exchange='encoded'
    # (None -> threshold scheme with the knobs above, or env default)
    encoding: object = None
    # updater applies every N micro-batches on the mean gradient
    # (reference: GradientsAccumulator)
    accumulation_steps: int = 1
    # shard model weights N-ways over a second `model` mesh axis
    # (megatron column/row splits, parallel.speclayout); composes with
    # every update_exchange mode — the global mesh becomes 2D
    # (data, model) and the dp world size becomes devices // N
    tensor_parallel: int = 1
    # split the layer stack into N contiguous pipeline stages over a
    # third `pipe` mesh axis (parallel.pipeline — the 1F1B/GPipe
    # microbatch engine); the global mesh becomes 3D
    # (data, model, pipe) and the dp world = devices // (tp * pp)
    pipeline_stages: int = 1
    # control plane (jax.distributed); None = single-process
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None


class SharedTrainingMaster:
    """Multi-host DP trainer. Single-process it degenerates to
    :class:`ParallelWrapper` over all local devices; multi-process it
    initializes `jax.distributed` and builds the global mesh."""

    def __init__(self, config: Optional[SharedTrainingConfiguration] = None):
        self.config = config or SharedTrainingConfiguration()
        self._mesh = None
        self._initialized_dist = False
        self._observatory = None        # leader-side aggregator
        self._obs_client = None         # this process's shipper
        self._last_observatory = None   # report kept after teardown

    class Builder:
        def __init__(self, batch_size_per_worker: int = 32):
            self._c = SharedTrainingConfiguration(
                batch_size_per_worker=batch_size_per_worker)

        def workers_per_node(self, n: int):
            self._c.workers_per_node = n
            return self

        def threshold_algorithm(self, algo: ThresholdAlgorithm):
            self._c.threshold_algorithm = algo
            return self

        def residual_post_processor(self, rp):
            self._c.residual_post_processor = rp
            return self

        def update_exchange(self, mode):
            """'dense' | 'sharded' | 'fsdp' | 'encoded' | 'auto' —
            validated eagerly so a typo fails at build time, not first
            fit. Under 'encoded' the reference threshold/residual
            knobs (:meth:`threshold_algorithm`,
            :meth:`residual_post_processor`) configure the codec."""
            from deeplearning4j_tpu.parallel.zero import UpdateExchange
            self._c.update_exchange = UpdateExchange(
                mode.lower() if isinstance(mode, str) else mode).value
            return self

        def encoding(self, spec):
            """Codec for ``update_exchange('encoded')``: an
            ``EncodingSpec`` or scheme string ('threshold' | 'int8' |
            '1bit' — parallel.encoding). Overrides the
            threshold_algorithm/residual_post_processor knobs."""
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            self._c.encoding = resolve_encoding(spec)
            return self

        def accumulation_steps(self, n: int):
            """Apply the updater every ``n`` micro-batches on the mean
            gradient (reference: GradientsAccumulator)."""
            self._c.accumulation_steps = max(int(n), 1)
            return self

        def tensor_parallel(self, n: int):
            """Shard model weights ``n``-ways over a second ``model``
            mesh axis (parallel.speclayout); the global mesh becomes
            2D ``(data, model)``. Composes with every update_exchange
            mode: dense×tp, sharded×tp, fsdp×tp."""
            n = int(n)
            if n < 1:
                raise ValueError(
                    f"tensor_parallel must be >= 1, got {n}")
            self._c.tensor_parallel = n
            return self

        def pipeline_stages(self, n: int):
            """Split the layer stack into ``n`` contiguous pipeline
            stages over a third ``pipe`` mesh axis
            (parallel.pipeline); the global mesh becomes 3D
            ``(data, model, pipe)``. Composes with workers_per_node
            (dp) and tensor_parallel — total devices must divide by
            ``tp * pp``."""
            n = int(n)
            if n < 1:
                raise ValueError(
                    f"pipeline_stages must be >= 1, got {n}")
            self._c.pipeline_stages = n
            return self

        def coordinator(self, address: str, num_processes: int,
                        process_id: int):
            self._c.coordinator_address = address
            self._c.num_processes = num_processes
            self._c.process_id = process_id
            return self

        def build(self) -> "SharedTrainingMaster":
            return SharedTrainingMaster(self._c)

    # ------------------------------------------------------------------
    def _ensure_distributed(self):
        c = self.config
        if c.coordinator_address and not self._initialized_dist:
            # idempotent: the host program may have initialized the
            # world already (it must happen before ANY jax computation,
            # e.g. before building the model)
            if not jax.distributed.is_initialized():
                jax.distributed.initialize(
                    coordinator_address=c.coordinator_address,
                    num_processes=c.num_processes,
                    process_id=c.process_id)
            elif c.num_processes is not None and \
                    jax.process_count() != c.num_processes:
                raise ValueError(
                    f"jax.distributed world has {jax.process_count()} "
                    f"processes but this master was configured for "
                    f"{c.num_processes}")
            self._initialized_dist = True
            log.info("jax.distributed up: process %d/%d, %d global devices",
                     jax.process_index(), jax.process_count(),
                     len(jax.devices()))

    def _global_mesh(self):
        if self._mesh is None:
            devs = jax.devices()     # global across all processes
            tp = max(int(self.config.tensor_parallel), 1)
            pp = max(int(self.config.pipeline_stages), 1)
            group = tp * pp
            if self.config.workers_per_node > 0 and jax.process_count() == 1:
                devs = devs[:self.config.workers_per_node * group]
            if group > 1:
                if len(devs) % group or len(devs) < group:
                    raise ValueError(
                        f"tensor_parallel={tp} x pipeline_stages={pp} "
                        f"does not divide {len(devs)} devices")
                from deeplearning4j_tpu.parallel.mesh import \
                    DEFAULT_MODEL_AXIS
                axes = {DEFAULT_DATA_AXIS: -1}
                if tp > 1:
                    axes[DEFAULT_MODEL_AXIS] = tp
                if pp > 1:
                    from deeplearning4j_tpu.parallel.pipeline import \
                        PIPE_AXIS
                    axes[PIPE_AXIS] = pp
                self._mesh = make_mesh(axes, devs)
            else:
                self._mesh = make_mesh({DEFAULT_DATA_AXIS: len(devs)},
                                       devs)
        return self._mesh

    # ------------------------------------------------------------------
    def fit(self, model, iterator, *, n_epochs: int = 1,
            checkpoint_dir=None, save_every_n_epochs: int = 1,
            keep_last: int = 3):
        """fit(model, DataSetIterator). Each process iterates its LOCAL
        data partition (the analogue of an executor's RDD partition);
        arrays are assembled into globally-sharded batches.

        Global-batch assembly (``make_array_from_process_local_data`` —
        metadata + local device_puts, no collective) runs on the
        DevicePrefetcher feeder thread via ``ParallelWrapper.
        run_epochs``, one batch ahead of the step loop: every process
        stages its local shard while its chips step, and the processes
        stay aligned because only the jitted step itself rendezvouses.

        With ``checkpoint_dir`` the multi-host save/resume discipline
        (SURVEY.md §5.4) is active: if checkpoints exist there the
        model is RESUMED on every process (same bytes, shared fs) and
        only the remaining epochs run; process 0 writes asynchronous
        atomic checkpoints every ``save_every_n_epochs`` behind a
        world barrier, so a killed job re-run with the same arguments
        converges to the same state as an uncrashed one."""
        self._ensure_distributed()
        mesh = self._global_mesh()
        from deeplearning4j_tpu.parallel.zero import (
            UpdateExchange, resolve_update_exchange)
        mode = resolve_update_exchange(mesh, DEFAULT_DATA_AXIS,
                                       self.config.update_exchange,
                                       model)
        encoding = None
        if mode is UpdateExchange.ENCODED:
            # the reference threshold/residual knobs are LIVE here:
            # they shape the codec of the compressed collective
            from deeplearning4j_tpu.parallel.encoding import (
                EncodingSpec, resolve_encoding)
            encoding = resolve_encoding(self.config.encoding)
            if self.config.encoding is None and (
                    self.config.threshold_algorithm is not None
                    or self.config.residual_post_processor is not None):
                encoding = EncodingSpec(
                    scheme=encoding.scheme,
                    algorithm=(self.config.threshold_algorithm
                               or encoding.algorithm),
                    residual_post=(self.config.residual_post_processor
                                   or encoding.residual_post))
            log.info("encoded update exchange: scheme=%s algorithm=%s",
                     encoding.scheme,
                     type(encoding.algorithm).__name__)
        elif self.config.threshold_algorithm is not None:
            log.info("threshold_algorithm configures the encoded "
                     "update exchange; update_exchange=%r keeps it "
                     "inert (dense AllReduce | ZeRO-1 sharded "
                     "ReduceScatter/AllGather | ZeRO-3 fsdp) — pass "
                     "update_exchange='encoded' to compress the "
                     "gradient collective", self.config.update_exchange)
        telemetry.gauge(
            "dl4j_dp_workers",
            "devices participating in the data-parallel mesh").set(
                mesh.size, master=type(self).__name__,
                update_exchange=mode.value)
        from deeplearning4j_tpu.common import faults
        mgr = None
        if checkpoint_dir is not None:
            from deeplearning4j_tpu.utils.checkpoint import (
                MultiHostCheckpointListener, MultiHostCheckpointManager)
            mgr = MultiHostCheckpointManager(checkpoint_dir,
                                             keep_last=keep_last)
            if mgr.restore_into(model):
                log.info("resumed from %s at epoch %d",
                         checkpoint_dir, model.epoch_count)
                faults.note_resume("restart")
                # n_epochs is the TOTAL target for a RESUMED job only:
                # a warm-started model (epoch_count from elsewhere,
                # nothing restored here) still trains n_epochs
                n_epochs = n_epochs - model.epoch_count
            lis = MultiHostCheckpointListener(mgr, save_every_n_epochs)
            model.add_listeners(lis)
            if n_epochs <= 0:
                log.info("fit: %d epochs already done",
                         model.epoch_count)
                model.listeners.remove(lis)
                return model
            # with a checkpoint dir a SIGTERM/preemption notice becomes
            # a coordinated final snapshot + clean resumable exit (75)
            faults.install_preemption_capture()
        if jax.process_count() > 1:
            self._setup_observatory()
        target_total = model.epoch_count + n_epochs
        attempt = 0
        try:
            while True:
                remaining = target_total - model.epoch_count
                if remaining <= 0:
                    break
                try:
                    # a FRESH wrapper per attempt: an elastic resume can
                    # land on a different world size, so the exchange
                    # mode and the dense/sharded/fsdp layouts must
                    # re-resolve against the current mesh
                    pw = ParallelWrapper(
                        model, mesh, update_exchange=mode,
                        encoding=encoding,
                        accumulation_steps=self.config.accumulation_steps)
                    if jax.process_count() == 1:
                        pw.fit(iterator, n_epochs=remaining)
                    else:
                        # multi-host: same epoch loop, batches assembled
                        # globally from each process's local shard
                        pw.run_epochs(
                            iterator, remaining,
                            lambda ds: self._make_global(mesh, ds))
                except faults.TrainingPreempted:
                    # final coordinated snapshot, then unwind so the
                    # supervisor sees the resumable exit code
                    if mgr is not None:
                        mgr.save(model)
                        mgr.flush()
                    raise
                except Exception:
                    attempt += 1
                    if mgr is None or attempt > faults.resume_retries():
                        raise
                    log.warning(
                        "fit attempt %d failed; auto-resuming from %s",
                        attempt, checkpoint_dir, exc_info=True)
                    time.sleep(faults.resume_backoff(attempt))
                    it_before = model.iteration_count
                    if mgr.restore_into(model):
                        faults.note_resume(
                            "inprocess",
                            lost_steps=max(
                                it_before - model.iteration_count, 0))
        finally:
            self._teardown_observatory()
            if mgr is not None:
                model.listeners.remove(lis)
                mgr.flush()
        return model

    # -- scaling observatory sidecar -----------------------------------
    def _setup_observatory(self):
        """Ship every worker's per-step breakdown to the leader over a
        sidecar socket (NOT inside the gradient exchange — that is a
        compiled collective): the leader merges per step, gauges
        per-worker skew, and trips straggler detection.  The connect
        handshake gives each worker its clock offset vs the leader for
        the cross-host trace merge.  Any failure here disables the
        sidecar — observability must never take training down."""
        import os

        from deeplearning4j_tpu.common import stepstats
        port = int(os.environ.get("DL4J_TPU_OBSERVATORY_PORT", "9470"))
        leader = (self.config.coordinator_address or "").split(":")[0] \
            or "127.0.0.1"
        try:
            if jax.process_index() == 0:
                self._observatory = stepstats.StepStatsAggregator(
                    expected_workers=jax.process_count(), port=port,
                    host="")
                port = self._observatory.port
            stepstats.collector().set_worker(jax.process_index(),
                                             jax.process_count())
            self._obs_client = stepstats.StepStatsClient(
                leader, port, worker=jax.process_index())
            stepstats.collector().add_sink(self._obs_client.ship)
        except OSError as e:
            log.warning("scaling observatory sidecar disabled: %r", e)

    def _teardown_observatory(self):
        from deeplearning4j_tpu.common import stepstats
        if self._obs_client is not None:
            stepstats.collector().remove_sink(self._obs_client.ship)
            self._obs_client.close()
            self._obs_client = None
        if self._observatory is not None:
            self._last_observatory = self._observatory.report()
            self._observatory.close()
            self._observatory = None

    def observatory_report(self) -> Optional[dict]:
        """The leader's merged cross-host view (skew, trips, clock
        offsets) — live during fit, the final report afterwards; None
        on non-leader processes and single-process runs."""
        if self._observatory is not None:
            return self._observatory.report()
        return self._last_observatory

    def _make_global(self, mesh, ds):
        from deeplearning4j_tpu.common.diagnostics import collective_span
        from deeplearning4j_tpu.datasets.prefetch import _ds_nbytes
        with collective_span("global_assembly", DEFAULT_DATA_AXIS,
                             _ds_nbytes(ds),
                             processes=jax.process_count()):
            return self._make_global_inner(mesh, ds)

    def _make_global_inner(self, mesh, ds):
        from deeplearning4j_tpu.parallel.mesh import (data_sharding,
                                                      map_dataset_arrays)
        n_local = max(len(jax.local_devices()), 1)

        def glob(a):
            a = jnp.asarray(a)
            # trim the LOCAL shard to a local-device multiple (mirrors
            # wrapper._shard_dataset; every process must trim identically)
            b = (a.shape[0] // n_local) * n_local
            if b == 0:
                raise ValueError(
                    f"local minibatch of {a.shape[0]} < {n_local} local "
                    f"devices; increase batch size")
            if b != a.shape[0]:
                log.warning("trimming local minibatch %d -> %d for "
                            "%d local devices", a.shape[0], b, n_local)
                a = a[:b]
            return jax.make_array_from_process_local_data(
                data_sharding(mesh, a.ndim, DEFAULT_DATA_AXIS), a)

        return map_dataset_arrays(ds, glob)


class ParameterAveragingTrainingMaster(SharedTrainingMaster):
    """Reference: ``org.deeplearning4j.spark.impl.paramavg.
    ParameterAveragingTrainingMaster`` — Spark's broadcast-params /
    average-every-N-rounds scheme (SURVEY.md P3).

    TPU-native, synchronous in-step AllReduce makes every iteration an
    exact average, which is the averaging scheme's N=1 fixed point with
    none of its staleness — so this class is the same trainer with the
    reference's builder surface (``averaging_frequency``/
    ``rdd_data_set_num_examples``-style knobs accepted and logged)."""

    class Builder(SharedTrainingMaster.Builder):
        def __init__(self, rdd_data_set_num_examples: int = 32):
            super().__init__(
                batch_size_per_worker=rdd_data_set_num_examples)

        def averaging_frequency(self, n: int):
            log.info("averagingFrequency=%d accepted for API parity; "
                     "in-step AllReduce averages exactly every "
                     "iteration", n)
            return self

        def batch_size_per_worker(self, n: int):
            self._c.batch_size_per_worker = n
            return self

        def build(self) -> "ParameterAveragingTrainingMaster":
            return ParameterAveragingTrainingMaster(self._c)
