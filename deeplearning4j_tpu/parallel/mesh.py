"""Device-mesh construction and sharding helpers.

The reference binds parallelism to explicit device lists and per-device
model replicas (``ParallelWrapper`` workers, `org.deeplearning4j.
parallelism.factory.TrainerContext`). On TPU the analogue is a
``jax.sharding.Mesh`` with named axes; replication/sharding is expressed
as `NamedSharding` partition specs and the GSPMD partitioner inserts the
collectives (psum over ICI for the gradient all-reduce).

Axis convention (scaling-book style):
- ``data``  — batch dimension (DP); always present.
- ``model`` — tensor-parallel dimension (TP, megatron-style splits).
- ``seq``   — sequence/context-parallel dimension (SP/CP, ring attention).
- ``stage`` — pipeline stages (PP).
Axes of size 1 are free, so a single mesh shape covers every strategy.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DEFAULT_DATA_AXIS = "data"
DEFAULT_MODEL_AXIS = "model"
DEFAULT_SEQ_AXIS = "seq"
DEFAULT_STAGE_AXIS = "stage"


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. ``axes`` maps axis name -> size; a single ``-1``
    entry absorbs the remaining devices (like a reshape). Default:
    all devices on the ``data`` axis (pure DP — the reference's only
    in-node strategy, SURVEY.md P1)."""
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DEFAULT_DATA_AXIS: len(devices)}
    names = list(axes.keys())
    sizes = list(axes.values())
    n = len(devices)
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {n}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


class MeshFactory:
    """Named mesh presets (the counterpart of the reference's
    `TrainerContext` strategy selection)."""

    @staticmethod
    def data_parallel(n: Optional[int] = None) -> Mesh:
        devs = jax.devices()[:n] if n else jax.devices()
        return make_mesh({DEFAULT_DATA_AXIS: len(devs)}, devs)

    @staticmethod
    def data_model(data: int = -1, model: int = 1) -> Mesh:
        return make_mesh({DEFAULT_DATA_AXIS: data,
                          DEFAULT_MODEL_AXIS: model})

    @staticmethod
    def full(data: int = -1, model: int = 1, seq: int = 1,
             stage: int = 1) -> Mesh:
        return make_mesh({DEFAULT_DATA_AXIS: data,
                          DEFAULT_MODEL_AXIS: model,
                          DEFAULT_SEQ_AXIS: seq,
                          DEFAULT_STAGE_AXIS: stage})


def data_sharding(mesh: Mesh, ndim: int,
                  axis: str = DEFAULT_DATA_AXIS) -> NamedSharding:
    """Leading-axis (batch) sharding: P(data, None, ...)."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def flat_sharding(mesh: Mesh,
                  axis: str = DEFAULT_DATA_AXIS) -> NamedSharding:
    """1-D sharding along ``axis`` — the ZeRO-1 flat param/optimizer
    state layout (``parallel.zero``): each replica holds 1/N of the
    padded flat vector."""
    return NamedSharding(mesh, P(axis))


def replicate_tree(mesh: Mesh, tree):
    """Place every leaf fully replicated on the mesh (params/opt state
    for DP — the analogue of ParallelWrapper's per-device model copies,
    except there is ONE logical copy and XLA keeps replicas in sync)."""
    sh = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sh) if hasattr(a, "shape") else a,
        tree)


def shard_batch(mesh: Mesh, tree, axis: str = DEFAULT_DATA_AXIS):
    """Shard every array leaf along its leading (batch) dimension."""
    def put(a):
        if not hasattr(a, "ndim") or a.ndim == 0:
            return a
        return jax.device_put(a, data_sharding(mesh, a.ndim, axis))
    return jax.tree_util.tree_map(put, tree)


#: every batch-dim array attribute a DataSet/MultiDataSet can carry
#: (singular = DataSet, plural = MultiDataSet)
DATASET_ARRAY_ATTRS = ("features", "labels", "features_mask",
                       "labels_mask", "features_masks", "labels_masks")


def map_dataset_arrays(ds, fn):
    """Shallow-copy ``ds`` with ``fn`` applied to every array attribute
    (lists mapped elementwise, None passed through). The single place
    that knows the DataSet/MultiDataSet array surface — used by both the
    single-host and multi-host sharding paths."""
    import copy
    out = copy.copy(ds)
    for attr in DATASET_ARRAY_ATTRS:
        if not hasattr(ds, attr):
            continue
        v = getattr(ds, attr)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            setattr(out, attr, [fn(x) if x is not None else None
                                for x in v])
        else:
            setattr(out, attr, fn(v))
    return out


def pad_batch_to_multiple(x, n: int):
    """Pad the leading axis up to a multiple of ``n`` by repeating the
    final example; returns (padded, original_size). Training callers
    should instead trim (padding would bias gradients); inference
    callers pad then slice the output back."""
    import jax.numpy as jnp
    b = x.shape[0]
    rem = b % n
    if rem == 0:
        return x, b
    pad = n - rem
    reps = jnp.repeat(x[-1:], pad, axis=0)
    return jnp.concatenate([x, reps], axis=0), b


def shard_map(f, mesh: Mesh, *, in_specs, out_specs, check_rep=True):
    """jax.shard_map across jax versions (experimental alias pre-0.8).
    The package-public seam every parallel module builds on.

    ``check_rep=False`` disables the static replication / varying-
    manual-axes check (the kwarg is ``check_rep`` on older jax,
    ``check_vma`` on newer) — callers that opt out take over the
    cross-rank gradient reduction themselves and must say why at the
    call site."""
    if hasattr(jax, "shard_map"):
        _sm = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _sm
    kw = {}
    if not check_rep:
        import inspect
        params = inspect.signature(_sm).parameters
        kw["check_vma" if "check_vma" in params else "check_rep"] = False
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)


def axis_size(axis: str) -> int:
    """Concrete size of a mesh axis from inside shard_map tracing
    (the mesh is static, so this is a Python int on every jax we
    support)."""
    from jax import lax
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis))
    return int(lax.psum(1, axis))
