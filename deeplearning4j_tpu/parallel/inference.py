"""ParallelInference: batched multi-device inference.

Reference parity: ``org.deeplearning4j.parallelism.ParallelInference``
(SURVEY.md P6) — request batching across threads with per-device model
workers and observable round-trips.

TPU-first design: one jitted forward, batch sharded over the mesh
``data`` axis; XLA splits the work across devices. `BATCHED` mode's
request aggregation becomes a `batch_limit`-sized queue flushed through
the sharded program; `SEQUENTIAL` mode is a plain single call.
"""
from __future__ import annotations

import concurrent.futures
import logging
import queue as _queue
import threading
import time
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              data_sharding, make_mesh,
                                              pad_batch_to_multiple,
                                              replicate_tree)

log = logging.getLogger("deeplearning4j_tpu")


class InferenceMode:
    #: run each request directly on the shared jitted forward — no
    #: queue, lowest latency (reference: InferenceMode.INPLACE)
    INPLACE = "INPLACE"
    SEQUENTIAL = "SEQUENTIAL"
    #: aggregate requests into up-to-batch_limit batches (reference:
    #: InferenceMode.BATCHED via the observable queue)
    BATCHED = "BATCHED"


class ParallelInference:
    def __init__(self, model, mesh=None, *,
                 inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32,
                 queue_limit: int = 64,
                 batch_window_ms: float = 2.0):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.inference_mode = inference_mode
        self.batch_limit = batch_limit
        self.queue_limit = queue_limit
        #: how long the batching worker waits for more requests once
        #: it holds at least one (the latency/throughput knob)
        self.batch_window_ms = batch_window_ms
        self._fwd = None
        self._placed = False
        self._worker = None
        self._requests = None
        self._shutdown = False
        self._lock = threading.Lock()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._mode = InferenceMode.BATCHED
            self._batch_limit = 32
            self._queue_limit = 64
            self._workers = None
            self._batch_window_ms = 2.0

        def inference_mode(self, mode: str):
            self._mode = mode
            return self

        def batch_limit(self, n: int):
            self._batch_limit = n
            return self

        def queue_limit(self, n: int):
            self._queue_limit = n
            return self

        def workers(self, n: int):
            self._workers = n
            return self

        def batch_window_ms(self, ms: float):
            self._batch_window_ms = float(ms)
            return self

        def build(self) -> "ParallelInference":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                if self._workers:
                    devs = devs[:self._workers]
                mesh = make_mesh({DEFAULT_DATA_AXIS: len(devs)}, devs)
            return ParallelInference(self._model, mesh,
                                     inference_mode=self._mode,
                                     batch_limit=self._batch_limit,
                                     queue_limit=self._queue_limit,
                                     batch_window_ms=
                                     self._batch_window_ms)

    # ------------------------------------------------------------------
    @property
    def n_workers(self) -> int:
        return self.mesh.shape[DEFAULT_DATA_AXIS]

    def _ensure(self):
        m = self.model
        if not m._initialized:
            m.init()
        if not self._placed:
            m.params = replicate_tree(self.mesh, m.params)
            m.states = replicate_tree(self.mesh, m.states)
            self._placed = True
        if self._fwd is None:
            from deeplearning4j_tpu.common.compilecache import \
                enable_persistent_cache
            enable_persistent_cache()
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            is_graph = isinstance(m, ComputationGraph)

            def fwd(params, states, x):
                if is_graph:
                    acts, _ = m._forward(params, states, [x],
                                         training=False, rng=None,
                                         want_logits=False)
                    return acts[m.conf.network_outputs[0]]
                out, _ = m._forward(params, states, x, training=False,
                                    rng=None, want_logits=False)
                return out

            # idempotent lazy init: racing callers both build the same
            # jitted fn and the last assignment wins — no torn state
            # dl4j-lint: disable=lock-discipline
            self._fwd = jax.jit(fwd)

    def _place_chunk(self, x):
        """Pad to a shard multiple and device_put sharded over the mesh
        (an async dispatch — the H2D DMA proceeds in the background).
        Returns (placed, original_batch)."""
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(self.model._dtype)
        padded, orig = pad_batch_to_multiple(x, self.n_workers)
        placed = jax.device_put(
            padded, data_sharding(self.mesh, padded.ndim))
        return placed, orig

    def output(self, x) -> np.ndarray:
        """Run inference on ``x``; pads the batch to a shard multiple and
        slices the padding back off (padding is safe for inference,
        unlike training — mesh.py note)."""
        self._ensure()
        placed, orig = self._place_chunk(x)
        out = self._fwd(self.model.params, self.model.states, placed)
        return np.asarray(out[:orig])

    def output_batched(self, requests: List) -> List[np.ndarray]:
        """BATCHED mode: aggregate many small requests into shard-wide
        batches (the reference's observable queue, synchronously).

        Chunks are double-buffered: chunk i+1's sharded ``device_put``
        is dispatched BEFORE the host blocks on chunk i's result, so
        the next H2D DMA overlaps the current forward + D2H — the
        DevicePrefetcher discipline applied to the serving path
        (``DL4J_TPU_DEVICE_PREFETCH=0`` reverts to serial placement)."""
        if not requests:
            return []
        self._ensure()
        from deeplearning4j_tpu.common.environment import Environment
        arrays = [np.asarray(r) for r in requests]
        sizes = [a.shape[0] for a in arrays]
        big = np.concatenate(arrays, axis=0)
        chunks = [big[i:i + self.batch_limit]
                  for i in range(0, big.shape[0], self.batch_limit)]
        overlap = Environment.get().device_prefetch
        outs = []
        placed = self._place_chunk(chunks[0]) if chunks else None
        for i in range(len(chunks)):
            cur, orig = placed
            # device compute for the current chunk: dispatched async
            out = self._fwd(self.model.params, self.model.states, cur)
            if i + 1 < len(chunks):
                if overlap:
                    # stage chunk i+1 while chunk i computes/transfers
                    placed = self._place_chunk(chunks[i + 1])
                    outs.append(np.asarray(out[:orig]))   # sync point
                else:
                    outs.append(np.asarray(out[:orig]))
                    placed = self._place_chunk(chunks[i + 1])
            else:
                outs.append(np.asarray(out[:orig]))
        flat = np.concatenate(outs, axis=0)
        result, off = [], 0
        for s in sizes:
            result.append(flat[off:off + s])
            off += s
        return result

    # -- async observable serving (reference: ParallelInference's
    # request queue + worker batching; output(Observable) round) -------
    def submit(self, x) -> "concurrent.futures.Future":
        """Enqueue one request; returns a Future resolving to its
        result. In BATCHED mode a background worker drains the queue,
        aggregates up to ``batch_limit`` requests (or whatever is
        waiting after ``batch_window_ms``) into ONE forward, and
        distributes the slices — the reference's observable BATCHED
        serving loop. INPLACE/SEQUENTIAL run the request directly
        (no queue, no cross-request aggregation)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        telemetry.counter(
            "dl4j_inference_requests_total",
            "requests submitted to ParallelInference").inc(
                mode=self.inference_mode)
        if self.inference_mode != InferenceMode.BATCHED:
            if fut.set_running_or_notify_cancel():
                try:
                    fut.set_result(self.output(x))
                except BaseException as e:       # noqa: BLE001
                    fut.set_exception(e)
            return fut
        # the put happens UNDER the lock shutdown() takes to enqueue
        # its sentinel: a racing submit can therefore never land behind
        # the sentinel on a dead queue (which would strand its Future
        # forever). A submit that wins the lock AFTER shutdown sees
        # _worker None and _ensure_worker restarts the service. The
        # put can block briefly when the queue is full; the worker
        # never takes this lock, so it keeps draining and the put
        # always completes.
        with self._lock:
            self._ensure_worker()
            self._requests.put((x, fut, time.monotonic()))
        return fut

    def _ensure_worker(self):
        """Start the batching worker (caller holds ``self._lock``)."""
        if self._worker is not None:
            return
        self._requests = _queue.Queue(self.queue_limit)
        self._shutdown = False
        q = self._requests                       # bind THIS queue

        def loop():
            while True:
                try:
                    first = q.get(timeout=0.1)
                except _queue.Empty:
                    if self._shutdown:
                        return
                    continue
                if first is None:
                    return
                batch = [first]
                deadline = time.monotonic() + self.batch_window_ms / 1e3
                while len(batch) < self.batch_limit:
                    left = deadline - time.monotonic()
                    try:
                        nxt = q.get(timeout=max(left, 0) or 0.0001)
                    except _queue.Empty:
                        break
                    if nxt is None:
                        self._flush(batch)
                        return
                    batch.append(nxt)
                self._flush(batch)

        # caller holds self._lock (see docstring) — submit's
        # queue-bind and the worker start stay atomic
        # dl4j-lint: disable=lock-discipline
        self._worker = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-tpu-serving")
        self._worker.start()

    def _flush(self, batch):
        # a caller may have cancelled its future while queued (client
        # timeout) — skip those; one cancelled request must not kill
        # the worker or starve its batch-mates
        live = [(x, f, t) for x, f, t in batch
                if f.set_running_or_notify_cancel()]
        if not live:
            return
        if telemetry.enabled():
            now = time.monotonic()
            lat = telemetry.histogram(
                "dl4j_inference_queue_seconds",
                "submit-to-flush latency of a queued request "
                "(seconds)")
            for _, _, t in live:
                lat.observe(now - t)
            telemetry.histogram(
                "dl4j_inference_batch_occupancy",
                "aggregated-batch fill fraction per flush "
                "(requests / batch_limit)",
                buckets=telemetry.RATIO_BUCKETS).observe(
                    len(live) / max(1, self.batch_limit))
        try:
            with telemetry.span("inference.flush", requests=len(live)):
                outs = self.output_batched([x for x, _, _ in live])
        except BaseException as e:           # noqa: BLE001
            for _, f, _ in live:
                f.set_exception(e)
            return
        for (_, f, _), o in zip(live, outs):
            f.set_result(o)

    def shutdown(self):
        """Stop the batching worker (pending requests are flushed).

        After the worker exits, any requests still sitting in the queue
        (possible when the worker died abnormally, or raced its idle
        timeout against a submit) have their futures CANCELLED — no
        caller may block forever on a Future nobody will resolve
        (ADVICE.md round 5)."""
        with self._lock:
            worker, self._worker = self._worker, None
            if worker is None:
                return
            self._shutdown = True
            q = self._requests               # bind THIS queue
            q.put(None)
        worker.join()
        while True:
            try:
                item = q.get_nowait()
            except _queue.Empty:
                break
            if item is not None:
                item[1].cancel()
