"""ParallelInference: batched multi-device inference.

Reference parity: ``org.deeplearning4j.parallelism.ParallelInference``
(SURVEY.md P6) — request batching across threads with per-device model
workers and observable round-trips.

TPU-first design: one jitted forward, batch sharded over the mesh
``data`` axis; XLA splits the work across devices. `BATCHED` mode's
request aggregation becomes a `batch_limit`-sized queue flushed through
the sharded program; `SEQUENTIAL` mode is a plain single call.
"""
from __future__ import annotations

import logging
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              data_sharding, make_mesh,
                                              pad_batch_to_multiple,
                                              replicate_tree)

log = logging.getLogger("deeplearning4j_tpu")


class InferenceMode:
    SEQUENTIAL = "SEQUENTIAL"
    BATCHED = "BATCHED"


class ParallelInference:
    def __init__(self, model, mesh=None, *,
                 inference_mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32,
                 queue_limit: int = 64):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.inference_mode = inference_mode
        self.batch_limit = batch_limit
        self.queue_limit = queue_limit
        self._fwd = None
        self._placed = False

    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._mode = InferenceMode.BATCHED
            self._batch_limit = 32
            self._queue_limit = 64
            self._workers = None

        def inference_mode(self, mode: str):
            self._mode = mode
            return self

        def batch_limit(self, n: int):
            self._batch_limit = n
            return self

        def queue_limit(self, n: int):
            self._queue_limit = n
            return self

        def workers(self, n: int):
            self._workers = n
            return self

        def build(self) -> "ParallelInference":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                if self._workers:
                    devs = devs[:self._workers]
                mesh = make_mesh({DEFAULT_DATA_AXIS: len(devs)}, devs)
            return ParallelInference(self._model, mesh,
                                     inference_mode=self._mode,
                                     batch_limit=self._batch_limit,
                                     queue_limit=self._queue_limit)

    # ------------------------------------------------------------------
    @property
    def n_workers(self) -> int:
        return self.mesh.shape[DEFAULT_DATA_AXIS]

    def _ensure(self):
        m = self.model
        if not m._initialized:
            m.init()
        if not self._placed:
            m.params = replicate_tree(self.mesh, m.params)
            m.states = replicate_tree(self.mesh, m.states)
            self._placed = True
        if self._fwd is None:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            is_graph = isinstance(m, ComputationGraph)

            def fwd(params, states, x):
                if is_graph:
                    acts, _ = m._forward(params, states, [x],
                                         training=False, rng=None,
                                         want_logits=False)
                    return acts[m.conf.network_outputs[0]]
                out, _ = m._forward(params, states, x, training=False,
                                    rng=None, want_logits=False)
                return out

            self._fwd = jax.jit(fwd)

    def output(self, x) -> np.ndarray:
        """Run inference on ``x``; pads the batch to a shard multiple and
        slices the padding back off (padding is safe for inference,
        unlike training — mesh.py note)."""
        self._ensure()
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(self.model._dtype)
        padded, orig = pad_batch_to_multiple(x, self.n_workers)
        padded = jax.device_put(
            padded, data_sharding(self.mesh, padded.ndim))
        out = self._fwd(self.model.params, self.model.states, padded)
        return np.asarray(out[:orig])

    def output_batched(self, requests: List) -> List[np.ndarray]:
        """BATCHED mode: aggregate many small requests into shard-wide
        batches (the reference's observable queue, synchronously)."""
        self._ensure()
        arrays = [jnp.asarray(r) for r in requests]
        sizes = [a.shape[0] for a in arrays]
        big = jnp.concatenate(arrays, axis=0)
        outs = []
        for i in range(0, big.shape[0], self.batch_limit):
            outs.append(self.output(big[i:i + self.batch_limit]))
        flat = np.concatenate(outs, axis=0)
        result, off = [], 0
        for s in sizes:
            result.append(flat[off:off + s])
            off += s
        return result
