"""Parallelism & distribution (SURVEY.md §2.6, P1–P6, P11).

TPU-native replacement for the reference's parallelism stack:

- ``org.deeplearning4j.parallelism.ParallelWrapper`` (P1/P2) ->
  :class:`ParallelWrapper`: one SPMD program over a ``jax.sharding.Mesh``
  ``data`` axis; the gradient all-reduce is compiled INTO the train step
  by XLA's GSPMD partitioner and rides ICI — no trainer threads, no
  parameter copies, no encoded-update queues.
- ``org.deeplearning4j.spark.parameterserver.training.SharedTrainingMaster``
  (P4) -> :class:`SharedTrainingMaster`: multi-host DP via
  ``jax.distributed`` (gRPC control plane) + the same compiled collectives
  over ICI/DCN. Spark/Aeron disappear.
- ``org.deeplearning4j.parallelism.ParallelInference`` (P6) ->
  :class:`ParallelInference`: batched inference sharded over the mesh.
- threshold gradient encoding (P2 `EncodedGradientsAccumulator`) ->
  :mod:`.encoding` keeps the *semantics* as an optional compression
  transform; on TPU the north star replaces it with dense XLA AllReduce.
- pipeline parallelism -> :mod:`.pipeline`: :class:`PipelineTrainer`
  runs the real fit path over a ``pipe`` mesh axis (1F1B or GPipe
  microbatch schedule), composing with dp/ZeRO-1 and tp into a 3D
  ``(data, model, pipe)`` mesh via
  ``ParallelWrapper.Builder.pipeline_stages``.
"""
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              MeshFactory, data_sharding,
                                              make_mesh, replicate_tree,
                                              shard_batch)
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.sharedtraining import (
    ParameterAveragingTrainingMaster, SharedTrainingConfiguration,
    SharedTrainingMaster)
from deeplearning4j_tpu.parallel.sequence import (
    blockwise_attention, flash_attention, ring_attention,
    ring_self_attention, ulysses_attention, ulysses_self_attention)
from deeplearning4j_tpu.parallel.encoding import (
    AdaptiveThresholdAlgorithm, EncodingHandler, FixedThresholdAlgorithm,
    ResidualClippingPostProcessor, TargetSparsityThresholdAlgorithm,
    ThresholdAlgorithm, encode_threshold, decode_threshold)
from deeplearning4j_tpu.parallel.zero import (
    UpdateExchange, apply_update_sharded, resolve_update_exchange,
    states_to_dense, states_to_sharded, update_exchange_bytes)
from deeplearning4j_tpu.parallel.speclayout import SpecLayout, TpLeafSpec
from deeplearning4j_tpu.parallel.pipeline import (
    PIPE_AXIS, SCHEDULES, PipelineTrainer, StagePartition,
    bubble_fraction, build_schedule, peak_residency, stage_submesh)

__all__ = [
    "DEFAULT_DATA_AXIS", "MeshFactory", "make_mesh", "data_sharding",
    "replicate_tree", "shard_batch", "ParallelWrapper",
    "ParallelInference", "SharedTrainingMaster", "ParameterAveragingTrainingMaster",
    "SharedTrainingConfiguration", "ThresholdAlgorithm",
    "FixedThresholdAlgorithm", "AdaptiveThresholdAlgorithm",
    "TargetSparsityThresholdAlgorithm", "ResidualClippingPostProcessor",
    "EncodingHandler", "encode_threshold", "decode_threshold",
    "blockwise_attention", "flash_attention", "ring_attention",
    "ring_self_attention", "ulysses_attention",
    "ulysses_self_attention",
    "UpdateExchange", "apply_update_sharded", "resolve_update_exchange",
    "states_to_dense", "states_to_sharded", "update_exchange_bytes",
    "SpecLayout", "TpLeafSpec",
    "PIPE_AXIS", "SCHEDULES", "PipelineTrainer", "StagePartition",
    "bubble_fraction", "build_schedule", "peak_residency",
    "stage_submesh",
]
