"""Pipeline parallelism — microbatch schedules over the ``pipe`` axis.

The reference has NO pipeline parallelism (SURVEY.md §2.6 P8: ABSENT).
Two engines live here:

1. The scan engine (``pipeline_apply`` / ``pipeline_loss``): one
   homogeneous stage fn, ONE ``lax.scan`` over clock ticks inside
   ``shard_map`` with ``lax.ppermute`` neighbor handoffs that ride ICI.
   XLA sees a static loop (compiles once, overlaps the permute with the
   next tick's compute), the VJP of the scan IS the backward pipeline,
   and ``jax.checkpoint`` on the stage fn gives remat-per-microbatch.
   This is the all-forward-then-backward **GPipe reference schedule**
   (transformer block stacks still train through it).
2. The promoted real fit path (ISSUE 18): ``StagePartition`` splits an
   MLN layer stack / graph topology into contiguous byte-balanced
   stages, ``build_schedule`` emits an explicit GPipe or 1F1B tick
   table, and ``PipelineTrainer`` executes it stage by stage on the
   ``pipe`` axis of a 3D ``(data, model, pipe)`` mesh. Each backward
   re-runs its stage forward under ``jax.vjp`` inside the jit —
   remat-per-microbatch by construction, so only the stage *input* of
   each in-flight microbatch stays resident. 1F1B bounds that
   residency at ``min(M, S-s)`` microbatches per stage versus GPipe's
   ``M``; the bubble fraction ``(S-1)/(M+S-1)`` is identical.

Layout-axis ownership (the PR-12 cross-link convention): this module
owns the ``pipe`` mesh axis — which stage holds which contiguous slice
of the network, and the microbatch schedule that streams activations
between stages. ``parallel/speclayout.py`` owns the ``model``-axis
parameter specs (column/row tensor-parallel placement plus the fsdp
``data`` residency axis) and per-stage spec restriction;
``parallel/tensor.py`` owns the column/row sharded matmul math on the
``model`` axis. The three compose into the 3D mesh built by
``ParallelWrapper.Builder.pipeline_stages`` (parallel/wrapper.py).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PIPE_AXIS = "pipe"


def to_microbatches(x, n_micro: int):
    """[b, ...] -> [n_micro, b/n_micro, ...] (leading-dim split)."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def from_microbatches(x):
    return x.reshape((-1,) + x.shape[2:])


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis: str = PIPE_AXIS,
                   remat: bool = False,
                   with_aux: bool = False,
                   varying_axes: Optional[tuple] = None):
    """Run microbatches through the stage pipeline (inside shard_map).

    stage_fn(params, x) -> y with ``y.shape == x.shape`` (transformer
    blocks preserve [mb, t, d], so stacks satisfy this naturally).
    ``stage_params`` are THIS device's stage weights. ``x_micro`` is
    [n_micro, mb, ...], same on every stage (only stage 0 reads it).
    Returns [n_micro, mb, ...]; rows are valid on the LAST stage.

    With ``with_aux`` the stage fn returns ``(y, aux_scalar)`` (e.g. a
    MoE load-balancing loss); returns ``(outputs, aux_sum)`` where
    ``aux_sum`` accumulates only *valid* ticks — warm-up/drain bubble
    ticks compute on garbage activations and must not contribute.
    """
    n_st = _axis_size_concrete(axis)
    stage = lax.axis_index(axis)
    n_micro = x_micro.shape[0]
    raw = stage_fn if with_aux else (
        lambda p, x: (stage_fn(p, x), jnp.zeros((), x.dtype)))
    fn = jax.checkpoint(raw) if remat else raw
    perm = [(i, (i + 1) % n_st) for i in range(n_st)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = x_micro[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, inject, state)
        y, aux = fn(stage_params, x_in)
        valid = (t >= stage) & (t - stage < n_micro)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        out_idx = jnp.clip(t - (n_st - 1), 0, n_micro - 1)
        collect = (stage == n_st - 1) & (t >= n_st - 1)
        outputs = jnp.where(collect, outputs.at[out_idx].set(y), outputs)
        state = lax.ppermute(y, axis, perm)
        return (state, outputs, aux_acc), None

    vaxes = tuple(varying_axes) if varying_axes else (axis,)
    state0 = _varying(jnp.zeros_like(x_micro[0]), vaxes)
    out0 = _varying(jnp.zeros_like(x_micro), vaxes)
    aux0 = _varying(jnp.zeros((), x_micro.dtype), vaxes)
    (_, outputs, aux_sum), _ = lax.scan(
        tick, (state0, out0, aux0), jnp.arange(n_micro + n_st - 1))
    if with_aux:
        return outputs, aux_sum
    return outputs


def _varying(x, axes):
    """Mark x as device-varying over ``axes`` (shard_map VMA typing —
    the scan carry differs per stage even though it starts as zeros;
    with MoE/DP inside the stage fn it also varies over those axes)."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    have = getattr(getattr(x, "aval", None), "vma", frozenset())
    axes = tuple(a for a in axes if a not in have)
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


from .mesh import axis_size as _axis_size_concrete  # shared helper


def last_stage_only(value, axis: str = PIPE_AXIS):
    """Zero ``value`` except on the last pipeline stage, then psum —
    every stage ends up holding the last stage's value (the way a
    pipelined loss becomes a global scalar)."""
    n_st = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    keep = (stage == n_st - 1).astype(value.dtype)
    return lax.psum(value * keep, axis)


def pipeline_loss(stage_fn: Callable, loss_fn: Callable, stage_params,
                  x_micro, y_micro, axis: str = PIPE_AXIS,
                  remat: bool = False):
    """Forward the pipeline and reduce a mean loss on the last stage.

    loss_fn(outputs_mb, labels_mb) -> scalar mean loss per microbatch.
    Returns the same scalar on every stage (safe to grad through).
    """
    outs = pipeline_apply(stage_fn, stage_params, x_micro, axis, remat)
    n_micro = x_micro.shape[0]
    per_mb = jax.vmap(loss_fn)(outs, y_micro)
    return last_stage_only(jnp.mean(per_mb), axis)


def init_stage_params(init_fn: Callable, axis: str = PIPE_AXIS):
    """Build THIS stage's params inside shard_map:
    ``init_fn(stage_index) -> params pytree`` (use lax.switch or
    index-folded RNG keys inside)."""
    return init_fn(lax.axis_index(axis))


# ======================================================================
# ISSUE 18 — the promoted real fit path: explicit schedule tables,
# contiguous stage partitioning, and the host-level stage executor.
# ======================================================================
import logging
import time

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

#: microbatch schedules the real fit path understands
SCHEDULES = ("gpipe", "1f1b")


def bubble_fraction(n_stages: int, n_micro: int) -> float:
    """GPipe/1F1B pipeline bubble: ``(S-1)/(M+S-1)`` of the schedule's
    ticks are idle on some stage (warm-up + drain). Identical for both
    schedules — 1F1B trades activation residency, not bubble."""
    s, m = int(n_stages), int(n_micro)
    return (s - 1) / float(m + s - 1)


def build_schedule(n_stages: int, n_micro: int, kind: str = "1f1b"):
    """The explicit tick table for ``kind`` — a list of ticks, each a
    tuple of per-stage ops: ``("F", m)``, ``("B", m)`` or ``None``
    (idle/bubble).

    GPipe: every stage runs all ``M`` forwards, then backwards in
    reverse microbatch order (matching the scan engine's VJP).
    1F1B: after a ``S-s-1``-deep warm-up, stage ``s`` alternates one
    backward per forward, so at most ``min(M, S-s)`` microbatches are
    ever in flight (forwarded but not yet backwarded) on it.
    """
    s_n, m_n = int(n_stages), int(n_micro)
    if kind not in SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {kind!r} "
                         f"(know {SCHEDULES})")
    if s_n < 1 or m_n < 1:
        raise ValueError(f"need n_stages >= 1 and n_micro >= 1, got "
                         f"({s_n}, {m_n})")
    fwd = [0] * s_n            # forwards committed per stage
    bwd = [0] * s_n            # backwards committed per stage
    ticks = []
    while any(b < m_n for b in bwd):
        ops = []
        for s in range(s_n):
            op = None
            f_ready = fwd[s] < m_n and (s == 0 or fwd[s - 1] > fwd[s])
            if kind == "gpipe":
                if f_ready:
                    op = ("F", fwd[s])
                elif fwd[s] == m_n and bwd[s] < m_n:
                    m = m_n - 1 - bwd[s]     # reverse microbatch order
                    if s == s_n - 1 or bwd[s + 1] >= m_n - m:
                        op = ("B", m)
            else:                            # 1f1b, in-order backward
                in_flight = fwd[s] - bwd[s]
                prefer_b = fwd[s] == m_n or in_flight > s_n - s - 1
                b_ready = bwd[s] < m_n and fwd[s] > bwd[s] and \
                    (s == s_n - 1 or bwd[s + 1] > bwd[s])
                if prefer_b:
                    # no forward fallback: falling forward here is what
                    # would let residency grow past S-s
                    op = ("B", bwd[s]) if b_ready else None
                elif f_ready:
                    op = ("F", fwd[s])
            ops.append(op)
        for s, op in enumerate(ops):          # commit AFTER the tick
            if op is not None:
                if op[0] == "F":
                    fwd[s] += 1
                else:
                    bwd[s] += 1
        if not any(ops):
            raise RuntimeError("pipeline schedule deadlocked "
                               f"(kind={kind}, S={s_n}, M={m_n})")
        ticks.append(tuple(ops))
    return ticks


def peak_residency(schedule, n_stages: int):
    """Per-stage max in-flight microbatches (forwarded, backward still
    pending) over a tick table — the activation-stash bound. GPipe
    peaks at ``M`` on stage 0; 1F1B at ``min(M, S-s)``."""
    live = [0] * n_stages
    peak = [0] * n_stages
    for ops in schedule:
        for s, op in enumerate(ops):
            if op is None:
                continue
            live[s] += 1 if op[0] == "F" else -1
            peak[s] = max(peak[s], live[s])
    return peak


def schedule_idle_ticks(schedule, n_stages: int):
    """Per-stage count of bubble ticks (no op scheduled)."""
    return [sum(1 for ops in schedule if ops[s] is None)
            for s in range(n_stages)]


def stage_submesh(mesh, stage: int, pipe_axis: str = PIPE_AXIS):
    """The (data[, model]) submesh holding pipeline stage ``stage`` —
    the pipe axis is dropped, every other axis keeps its extent, so the
    existing dp/ZeRO-1/tp machinery runs unchanged *within* a stage."""
    from jax.sharding import Mesh
    names = list(mesh.axis_names)
    if pipe_axis not in names:
        raise ValueError(f"mesh axes {tuple(names)} have no "
                         f"{pipe_axis!r} axis")
    k = names.index(pipe_axis)
    devs = np.moveaxis(np.asarray(mesh.devices), k, -1)[..., stage]
    rest = tuple(n for n in names if n != pipe_axis)
    if not rest:                       # pp-only mesh: 1-device stages
        from .mesh import DEFAULT_DATA_AXIS
        return Mesh(devs.reshape((1,)), (DEFAULT_DATA_AXIS,))
    return Mesh(devs, rest)


def _entry_param_bytes(entry) -> int:
    total = 0
    for a in jax.tree_util.tree_leaves(entry):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            total += int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
    return total


class StagePartition:
    """Contiguous split of an ordered entry list (MLN ``layer_i`` keys,
    graph topo vertex names) into ``n_stages`` stages, greedily
    balanced by parameter bytes. Contiguity is what makes the handoff
    a single activation-edge cut per boundary."""

    def __init__(self, entries, boundaries):
        self.entries = list(entries)
        self.boundaries = list(boundaries)
        self.n_stages = len(self.boundaries) - 1

    @classmethod
    def build(cls, entries, params, n_stages: int) -> "StagePartition":
        entries = list(entries)
        s_n = int(n_stages)
        if s_n < 1:
            raise ValueError(f"n_stages must be >= 1, got {s_n}")
        if len(entries) < s_n:
            raise ValueError(
                f"cannot split {len(entries)} layers/vertices into "
                f"{s_n} pipeline stages — need at least one per stage")
        sizes = [float(_entry_param_bytes((params or {}).get(e, {})))
                 for e in entries]
        if not sum(sizes):
            sizes = [1.0] * len(entries)
        total = sum(sizes)
        bounds, acc = [0], 0.0
        for i, sz in enumerate(sizes):
            if len(bounds) == s_n:
                break
            acc += sz
            left = len(entries) - (i + 1)
            need = s_n - len(bounds)
            if left == need or (acc >= total / s_n and left >= need):
                bounds.append(i + 1)
                acc = 0.0
        bounds.append(len(entries))
        return cls(entries, bounds)

    def stage_entries(self, s: int):
        return self.entries[self.boundaries[s]:self.boundaries[s + 1]]

    def stage_of(self, entry) -> int:
        i = self.entries.index(entry)
        for s in range(self.n_stages):
            if self.boundaries[s] <= i < self.boundaries[s + 1]:
                return s
        raise ValueError(entry)

    def stage_param_bytes(self, params):
        return [sum(_entry_param_bytes(params.get(e, {}))
                    for e in self.stage_entries(s))
                for s in range(self.n_stages)]


def _tree_bytes(tree) -> int:
    return sum(int(getattr(a, "nbytes", 0))
               for a in jax.tree_util.tree_leaves(tree))


def _tree_add(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


# -- model adapters ---------------------------------------------------------
# The trainer is model-shape-agnostic; these adapters map MLN's linear
# layer stack and the graph's topo order onto the common contract:
# ordered entries, a per-stage forward, a last-stage loss, and the
# per-entry updater/constraint/regularization dispatch of the model's
# own dense train step.

class _MlnStages:
    def __init__(self, model):
        self.model = model
        self.part = None
        conf = model.conf
        self.n_layers = len(conf.layers)
        self.out_layer = conf.layers[-1]
        self.want_logits = self.out_layer.wants_logits()

    def entries(self):
        return [f"layer_{i}" for i in range(self.n_layers)]

    def finalize(self):
        pass

    def fwd_fn(self, s: int):
        lo = self.part.boundaries[s]
        hi = self.part.boundaries[s + 1]
        model = self.model

        def fwd(stage_params, states, h, fmask, rng):
            return model._forward(stage_params, states, h,
                                  training=True, rng=rng,
                                  want_logits=False, mask=fmask,
                                  start_at=lo, stop_at=hi)
        return fwd

    def loss_fn(self, s: int):
        lo = self.part.boundaries[s]
        model, out_layer = self.model, self.out_layer
        wl = self.want_logits

        def fn(stage_params, states, h, y, lmask, fmask, rng):
            out, ns = model._forward(stage_params, states, h,
                                     training=True, rng=rng,
                                     want_logits=True, mask=fmask,
                                     start_at=lo)
            loss = out_layer.compute_loss(y, out, from_logits=wl,
                                          mask=lmask)
            return loss, ns
        return fn

    def _layer(self, entry):
        return self.model.conf.layers[int(entry.split("_")[1])]

    def updater_for(self, entry):
        return self._layer(entry).updater or self.model.conf.updater

    def gn_threshold(self):
        c = self.model.conf
        return (c.gradient_normalization,
                c.gradient_normalization_threshold)

    def constrain(self, entry, new_p):
        from deeplearning4j_tpu.nn.conf.constraints import \
            apply_constraints
        return apply_constraints(self._layer(entry), new_p)

    def has_regularization(self, names) -> bool:
        return any(getattr(self._layer(n), "l1", 0.0) or
                   getattr(self._layer(n), "l2", 0.0) for n in names)

    def microbatch_views(self, ds, n_micro: int):
        model = self.model
        dt = getattr(model, "_dtype", jnp.float32)
        x, y = ds.features, ds.labels
        fm = getattr(ds, "features_mask", None)
        lm = getattr(ds, "labels_mask", None)
        xm = to_microbatches(jnp.asarray(x, dt), n_micro)
        ym = to_microbatches(jnp.asarray(y, dt), n_micro)
        fmm = (to_microbatches(jnp.asarray(fm), n_micro)
               if fm is not None else None)
        lmm = (to_microbatches(jnp.asarray(lm), n_micro)
               if lm is not None else None)
        return _MicroViews(
            batch_size=int(x.shape[0]),
            inject=lambda m: xm[m],
            labels=lambda m: ym[m],
            lmask=(lambda m: lmm[m]) if lmm is not None else None,
            fmask=(lambda m: fmm[m]) if fmm is not None else None)


class _GraphStages:
    def __init__(self, model):
        self.model = model
        self.part = None
        self.topo = list(model._topo)
        self.out_confs = model.output_layer_confs()

    def entries(self):
        return list(self.topo)

    def finalize(self):
        """Handoff sets per stage boundary: an activation produced at
        stage ``p`` and consumed at stage ``c > p`` (or by the loss)
        rides every boundary in between — including network inputs
        consumed past stage 0, which flow through like any other
        activation (honest wire accounting)."""
        conf = self.model.conf
        part = self.part
        s_n = part.n_stages
        slice_of = {}
        for s in range(s_n):
            for nm in part.stage_entries(s):
                slice_of[nm] = s
        for inp in conf.network_inputs:
            slice_of.setdefault(inp, 0)
        need = [set() for _ in range(s_n + 1)]

        def consume(name, s):
            ss = slice_of.get(name)
            if ss is None or ss >= s:
                return
            for t in range(ss + 1, s + 1):
                need[t].add(name)

        for s in range(s_n):
            for nm in part.stage_entries(s):
                for src in conf.vertices[nm].inputs:
                    consume(src, s)
        for out in conf.network_outputs:
            consume(out, s_n - 1)
        self.incoming = [sorted(need[s]) for s in range(s_n)]
        self.outgoing = [sorted(need[s + 1]) for s in range(s_n)]

    def fwd_fn(self, s: int):
        lo = self.part.boundaries[s]
        hi = self.part.boundaries[s + 1]
        model = self.model
        outs = self.outgoing[s]
        first = s == 0

        def fwd(stage_params, states, h, fmask, rng):
            acts, ns = model._forward(
                stage_params, states, h if first else [],
                training=True, rng=rng, want_logits=False,
                fmask=fmask, start_acts=None if first else h,
                topo_slice=(lo, hi))
            return {n: acts[n] for n in outs}, ns
        return fwd

    def loss_fn(self, s: int):
        lo = self.part.boundaries[s]
        hi = self.part.boundaries[s + 1]
        model, out_confs = self.model, self.out_confs
        conf = model.conf
        first = s == 0

        def fn(stage_params, states, h, labels, lmasks, fmask, rng):
            acts, ns = model._forward(
                stage_params, states, h if first else [],
                training=True, rng=rng, want_logits=True,
                fmask=fmask, start_acts=None if first else h,
                topo_slice=(lo, hi))
            loss = jnp.zeros((), jnp.float32)
            for i, out_name in enumerate(conf.network_outputs):
                layer = out_confs.get(out_name)
                if layer is None:
                    continue
                loss = loss + layer.compute_loss(
                    labels[i], acts[out_name],
                    from_logits=layer.wants_logits(),
                    mask=lmasks[i] if lmasks is not None else None)
            return loss, ns
        return fn

    def updater_for(self, entry):
        v = self.model.conf.vertices[entry]
        if v.is_layer and v.content.updater:
            return v.content.updater
        return self.model.conf.updater

    def gn_threshold(self):
        c = self.model.conf
        return (c.gradient_normalization,
                c.gradient_normalization_threshold)

    def constrain(self, entry, new_p):
        v = self.model.conf.vertices[entry]
        if not v.is_layer:
            return new_p
        from deeplearning4j_tpu.nn.conf.constraints import \
            apply_constraints
        return apply_constraints(v.content, new_p)

    def has_regularization(self, names) -> bool:
        for n in names:
            v = self.model.conf.vertices[n]
            if v.is_layer and (getattr(v.content, "l1", 0.0) or
                               getattr(v.content, "l2", 0.0)):
                return True
        return False

    def microbatch_views(self, ds, n_micro: int):
        model = self.model
        dt = getattr(model, "_dtype", jnp.float32)
        feats, labels = ds.features, ds.labels
        fl = list(feats) if isinstance(feats, (list, tuple)) else [feats]
        ll = list(labels) if isinstance(labels, (list, tuple)) else [labels]
        lm = getattr(ds, "labels_mask", None)
        fm = getattr(ds, "features_mask", None)
        fm0 = fm[0] if isinstance(fm, (list, tuple)) else fm
        lml = ((list(lm) if isinstance(lm, (list, tuple)) else [lm])
               if lm is not None else None)
        xm = [to_microbatches(jnp.asarray(a, dt), n_micro) for a in fl]
        ym = [to_microbatches(jnp.asarray(a, dt), n_micro) for a in ll]
        lmm = ([to_microbatches(jnp.asarray(a), n_micro)
                if a is not None else None for a in lml]
               if lml is not None else None)
        fmm = (to_microbatches(jnp.asarray(fm0), n_micro)
               if fm0 is not None else None)
        return _MicroViews(
            batch_size=int(fl[0].shape[0]),
            inject=lambda m: [a[m] for a in xm],
            labels=lambda m: [a[m] for a in ym],
            lmask=((lambda m: [a[m] if a is not None else None
                               for a in lmm])
                   if lmm is not None else None),
            fmask=(lambda m: fmm[m]) if fmm is not None else None)


class _MicroViews:
    """Per-microbatch accessors for one training batch."""

    def __init__(self, batch_size, inject, labels, lmask, fmask):
        self.batch_size = batch_size
        self.inject = inject
        self.labels = labels
        self.lmask = lmask
        self.fmask = fmask


def make_stage_adapter(model):
    """The stage adapter for a model — MLN layer stacks and graph
    topologies are the supported pipeline substrates."""
    if hasattr(model, "_topo"):
        return _GraphStages(model)
    if hasattr(model, "conf") and hasattr(model.conf, "layers"):
        return _MlnStages(model)
    raise ValueError(
        f"pipeline_stages: unsupported model type "
        f"{type(model).__name__} (need MultiLayerNetwork or "
        f"ComputationGraph)")


class PipelineTrainer:
    """Host-level stage executor: the promoted pipeline fit path.

    Walks the explicit tick table from :func:`build_schedule`, running
    each stage's forward/backward as its own jit on that stage's
    ``(data[, model])`` submesh of a 3D mesh, handing activations (and
    backward cotangents) across the ``pipe`` boundary with
    ``jax.device_put`` — the accounted pipe-axis wire traffic. Backward
    ops re-run their stage forward under ``jax.vjp`` inside the jit, so
    the only per-(stage, microbatch) residency is the stage *input*
    stash — exactly what :func:`peak_residency` bounds.

    Each stage applies its own update tail (dense or per-stage ZeRO-1,
    with tp pinning when stage specs exist), so updater flats stay
    local to the stage's pipe group (``parallel/zero.py``). Microbatch
    grads are summed and scaled by ``1/M`` — with mean losses this is
    bit-for-tolerance the full-batch gradient, which is what makes the
    pp trajectory match the dp-only dense one (tests/test_pipeline.py).
    """

    def __init__(self, model, mesh, *, n_micro=None, schedule="1f1b",
                 mode="dense", pipe_axis=PIPE_AXIS, data_axis=None,
                 model_axis=None):
        from .mesh import DEFAULT_DATA_AXIS, DEFAULT_MODEL_AXIS
        if schedule not in SCHEDULES:
            raise ValueError(f"unknown pipeline schedule {schedule!r} "
                             f"(know {SCHEDULES})")
        self.model = model
        self.mesh = mesh
        self.pipe_axis = pipe_axis
        self.data_axis = data_axis or DEFAULT_DATA_AXIS
        self.model_axis = model_axis or DEFAULT_MODEL_AXIS
        self.n_stages = int(dict(mesh.shape).get(pipe_axis, 1))
        if self.n_stages < 2:
            raise ValueError(
                f"pipeline training needs a {pipe_axis!r} mesh axis of "
                f">= 2 stages, got {self.n_stages}")
        self.schedule = schedule
        self.n_micro = int(n_micro) if n_micro else 2 * self.n_stages
        if self.n_micro < 1:
            raise ValueError(f"n_micro must be >= 1, got {self.n_micro}")
        mode_s = str(getattr(mode, "value", mode) or "dense").lower()
        if mode_s == "auto":
            mode_s = "sharded"
        if mode_s == "fsdp":
            # fsdp param residency needs whole-model gather scheduling;
            # per-stage ZeRO-1 already keeps every updater flat local
            # to its stage's pipe group, which is the locality the 3D
            # design asks of zero.py — params stay dense per stage.
            log.info("pipeline x fsdp: downgrading the update tail to "
                     "per-stage ZeRO-1 (updater flats local to each "
                     "stage's pipe group; stage params stay dense)")
            mode_s = "sharded"
        self.mode = mode_s
        self.dp = int(dict(mesh.shape).get(self.data_axis, 1))
        self.tp = int(dict(mesh.shape).get(self.model_axis, 1))
        self._tail = "sharded" if (mode_s == "sharded" and
                                   self.dp > 1) else "dense"
        if not model._initialized:
            model.init()
        self.adapter = make_stage_adapter(model)
        self._sched = build_schedule(self.n_stages, self.n_micro,
                                     schedule)
        self.part = None
        self.submeshes = None
        self._placed = False
        self._jits = None
        self.last_report = None

    # -- placement ----------------------------------------------------
    def place(self):
        """Partition the (densified) model over the stages and place
        each stage's params/states/updater-state on its submesh."""
        from .mesh import replicate_tree
        from .speclayout import SpecLayout
        from deeplearning4j_tpu.parallel import zero
        m = self.model
        if hasattr(m, "set_dp_mesh"):
            # densify any prior sharded/fsdp layout and invalidate the
            # model's own compiled steps — the trainer owns this fit
            m.set_dp_mesh(None, self.data_axis)
        if hasattr(m, "_sync_updater_layout"):
            m._sync_updater_layout()
        self.part = StagePartition.build(self.adapter.entries(),
                                         m.params, self.n_stages)
        self.adapter.part = self.part
        self.adapter.finalize()
        self.submeshes = [stage_submesh(self.mesh, s, self.pipe_axis)
                          for s in range(self.n_stages)]
        if self.tp > 1:
            layout = SpecLayout(self.mesh, model_axis=self.model_axis,
                                data_axis=self.data_axis,
                                stage_axis=self.pipe_axis)
            self._tp_specs = layout.infer_stages(m.params, self.part,
                                                 shard_over_data=False)
        else:
            self._tp_specs = [{} for _ in range(self.n_stages)]
        for s in range(self.n_stages):
            sub = self.submeshes[s]
            names = self.part.stage_entries(s)
            sp = {k: m.params[k] for k in names if k in m.params}
            specs = self._tp_specs[s]
            if specs:
                sp = zero.place_tp_params(sub, sp, specs)
            else:
                sp = replicate_tree(sub, sp)
            m.params.update(sp)
            st = {k: m.states[k] for k in names if k in m.states}
            m.states.update(replicate_tree(sub, st))
            us = {k: m.updater_states[k] for k in names
                  if k in m.updater_states}
            us = zero.states_to_dense(sp, us)
            if self._tail == "sharded":
                us = zero.states_to_sharded(sp, us, self.dp,
                                            tp_specs=specs or None)
                us = zero.place_updater_states(sub, us, self.data_axis,
                                               tp_specs=specs or None)
            else:
                us = replicate_tree(sub, us)
            m.updater_states.update(us)
        self._jits = None
        self._placed = True

    # -- jit construction ---------------------------------------------
    def _make_pin(self, s: int):
        specs = self._tp_specs[s]
        if not specs:
            return lambda p: p
        from deeplearning4j_tpu.parallel import zero
        sub = self.submeshes[s]

        def pin(params):
            return {k: (zero.pin_tp_entry(v, sub, specs[k])
                        if k in specs and isinstance(v, dict) else v)
                    for k, v in params.items()}
        return pin

    def _make_apply(self, s: int):
        ad = self.adapter
        names = list(self.part.stage_entries(s))
        ups = {k: ad.updater_for(k) for k in names}
        gn, thr = ad.gn_threshold()
        sub = self.submeshes[s]
        specs_all = self._tp_specs[s]
        tail = self._tail
        model = self.model
        data_axis = self.data_axis
        has_reg = ad.has_regularization(names)
        from deeplearning4j_tpu.nn.gradient import \
            apply_gradient_normalization
        from deeplearning4j_tpu.parallel import zero

        def apply_fn(stage_params, upd_states, gsum, scale, iteration):
            g_all = jax.tree_util.tree_map(lambda a: a * scale, gsum)
            reg = jnp.zeros((), jnp.float32)
            if has_reg:
                # regularization is per-batch, not per-microbatch: its
                # grad rides the apply step once, like the dense path
                reg_val, rg = jax.value_and_grad(
                    model._regularization)(stage_params)
                reg = jnp.asarray(reg_val, jnp.float32)
                g_all = _tree_add(g_all, rg)
            new_params, new_upd = {}, {}
            for k in names:
                g = g_all.get(k, {})
                p = stage_params.get(k, {})
                if not g or not p:
                    new_params[k] = p
                    new_upd[k] = upd_states.get(k, ())
                    continue
                up = ups[k]
                tps = specs_all.get(k)
                if tail == "sharded":
                    if tps:
                        g_rest, g_tp = zero.split_tp_entry(g, tps)
                        p_rest, p_tp = zero.split_tp_entry(p, tps)
                        st_rest, st_tp = zero.split_tp_state(
                            upd_states[k])
                        if g_rest:
                            n_rest, us = zero.apply_update_sharded(
                                up, g_rest, p_rest, st_rest, iteration,
                                sub, data_axis)
                        else:
                            n_rest, us = p_rest, st_rest
                        n_tp, us_tp = zero.apply_update_tp(
                            up, g_tp, p_tp, st_tp, iteration, sub,
                            tps, gather_params=True)
                        new_p = {**n_rest, **n_tp}
                        us = zero.merge_tp_state(us, us_tp)
                    else:
                        new_p, us = zero.apply_update_sharded(
                            up, g, p, upd_states[k], iteration, sub,
                            data_axis)
                else:
                    g2 = apply_gradient_normalization(gn, thr, g)
                    updates, us = up.apply(g2, upd_states[k], iteration)
                    new_p = jax.tree_util.tree_map(
                        lambda pp, uu: pp - uu, p, updates)
                new_params[k] = ad.constrain(k, new_p)
                new_upd[k] = us
            return new_params, new_upd, reg
        return jax.jit(apply_fn)

    def _build(self):
        s_n = self.n_stages
        ad = self.adapter
        pins = [self._make_pin(s) for s in range(s_n)]
        self._fwd_jit, self._bwd_jit = [], []
        for s in range(s_n - 1):
            fwd = ad.fwd_fn(s)
            pin = pins[s]

            def make_f(fwd=fwd, pin=pin):
                def f(stage_params, states, h, fmask, rng):
                    return fwd(pin(stage_params), states, h, fmask, rng)
                return jax.jit(f)

            def make_b(fwd=fwd, pin=pin):
                def b(stage_params, states, h, g_out, fmask, rng):
                    def core(p, hh):
                        out, _ = fwd(pin(p), states, hh, fmask, rng)
                        return out
                    _, vjp = jax.vjp(core, stage_params, h)
                    gp, gh = vjp(g_out)
                    return gp, gh
                return jax.jit(b)

            self._fwd_jit.append(make_f())
            self._bwd_jit.append(make_b())
        loss_fn = ad.loss_fn(s_n - 1)
        pin = pins[s_n - 1]

        def last(stage_params, states, h, y, lmask, fmask, rng):
            def core(p, hh):
                return loss_fn(pin(p), states, hh, y, lmask, fmask,
                               rng)
            (loss, ns), (gp, gh) = jax.value_and_grad(
                core, argnums=(0, 1), has_aux=True)(stage_params, h)
            return loss, ns, gp, gh
        self._last_jit = jax.jit(last)
        self._apply_jit = [self._make_apply(s) for s in range(s_n)]
        self._jits = True

    # -- execution ----------------------------------------------------
    def _put(self, s: int, tree):
        """Place a microbatch payload on stage ``s``'s submesh, sharded
        over the data axis (the pipe-boundary handoff)."""
        from .mesh import data_sharding
        sub = self.submeshes[s]

        def put_one(a):
            if not hasattr(a, "ndim") or a.ndim == 0:
                return a
            return jax.device_put(
                a, data_sharding(sub, a.ndim, self.data_axis))
        return jax.tree_util.tree_map(put_one, tree)

    def fit_batch(self, ds):
        """One training step over ``ds`` — schedule-driven microbatch
        pipeline, per-stage apply, model bookkeeping to match
        ``_fit_batch`` (score, iteration count, listeners, telemetry,
        step-breakdown ``pipeline`` phase)."""
        from deeplearning4j_tpu.common import diagnostics, stepstats
        from deeplearning4j_tpu.common import telemetry
        m = self.model
        if not self._placed:
            self.place()
        if self._jits is None:
            self._build()
        s_n, m_n = self.n_stages, self.n_micro
        views = self.adapter.microbatch_views(ds, m_n)
        mb = views.batch_size // m_n
        if self.dp > 1 and mb % self.dp:
            raise ValueError(
                f"microbatch of {mb} rows not divisible by {self.dp} "
                f"data-parallel shards; pick n_micro/batch so that "
                f"batch/n_micro is a multiple of dp")
        with telemetry.step_span(type(m).__name__) as sp:
            report = self._run_schedule(views)
            loss = report.pop("_loss")
            new_states = report.pop("_states")
            stepstats.collector().note_in_step(
                "pipeline", report["bubble_seconds"])
            if telemetry.enabled():
                telemetry.histogram(
                    "dl4j_pipeline_bubble_seconds",
                    "measured per-step pipeline bubble (sum of stage "
                    "idle time while peers compute)").observe(
                    report["bubble_seconds"], schedule=self.schedule,
                    stages=str(s_n))
                h = telemetry.histogram(
                    "dl4j_pipeline_stage_seconds",
                    "per-stage busy seconds inside one pipeline step")
                for s in range(s_n):
                    h.observe(report["stage_busy_seconds"][s],
                              stage=str(s))
            m.states.update(new_states)
            if hasattr(m, "_strip_rnn_states"):
                m.states = m._strip_rnn_states(m.states)
            m._score = loss
            m.last_batch_size = views.batch_size
            self.last_report = report
            diagnostics.record_step(m, type(m).__name__,
                                    m.iteration_count, loss, sp)
        m.iteration_count += 1
        for lis in getattr(m, "listeners", []) or []:
            lis.iteration_done(m, m.iteration_count - 1,
                               getattr(m, "epoch_count", 0))
        return loss

    def _run_schedule(self, views):
        m = self.model
        s_n, m_n = self.n_stages, self.n_micro
        part = self.part
        sp = []
        st = []
        for s in range(s_n):
            names = part.stage_entries(s)
            sp.append({k: m.params[k] for k in names if k in m.params})
            st.append({k: m.states[k] for k in names if k in m.states})
        rng = None
        if hasattr(m, "_rng"):
            m._rng, rng = jax.random.split(m._rng)
        else:
            rng = jax.random.PRNGKey(0)
        # the SAME per-microbatch key feeds forward and recompute-
        # backward of every stage — remat needs identical dropout masks
        rngs = [jax.random.fold_in(rng, mi) for mi in range(m_n)]
        inject = [self._put(0, views.inject(mi)) for mi in range(m_n)]
        y_put = [self._put(s_n - 1, views.labels(mi))
                 for mi in range(m_n)]
        lm_put = ([self._put(s_n - 1, views.lmask(mi))
                   for mi in range(m_n)]
                  if views.lmask is not None else [None] * m_n)
        fmask_put = None
        if views.fmask is not None:
            fmask_put = {(s, mi): self._put(s, views.fmask(mi))
                         for s in range(s_n) for mi in range(m_n)}

        def fm(s, mi):
            return fmask_put[(s, mi)] if fmask_put is not None else None

        h_store, h_next, g_next = {}, {}, {}
        stash_bytes = {}
        live = [0] * s_n
        live_b = [0] * s_n
        peak = [0] * s_n
        peak_b = [0] * s_n
        grads = [None] * s_n
        ns_by_stage = {}
        losses = []
        wire_fwd = 0
        wire_bwd = 0
        tick_durs = []
        for ops in self._sched:
            durs = [0.0] * s_n
            for s, op in enumerate(ops):
                if op is None:
                    continue
                kind, mi = op
                t0 = time.perf_counter()
                if kind == "F":
                    h_in = inject[mi] if s == 0 else h_next.pop((s, mi))
                    h_store[(s, mi)] = h_in
                    stash_bytes[(s, mi)] = _tree_bytes(h_in)
                    live[s] += 1
                    live_b[s] += stash_bytes[(s, mi)]
                    peak[s] = max(peak[s], live[s])
                    peak_b[s] = max(peak_b[s], live_b[s])
                    if s < s_n - 1:
                        h_out, ns = self._fwd_jit[s](
                            sp[s], st[s], h_in, fm(s, mi), rngs[mi])
                        jax.block_until_ready(h_out)
                        ns_by_stage[s] = ns
                        wire_fwd += _tree_bytes(h_out)
                        h_next[(s + 1, mi)] = self._put(s + 1, h_out)
                    # last stage: forward is fused into its backward
                    # (remat) — the F op only stashes the handoff
                else:
                    h_in = h_store.pop((s, mi))
                    if s == s_n - 1:
                        loss, ns, gp, gh = self._last_jit(
                            sp[s], st[s], h_in, y_put[mi], lm_put[mi],
                            fm(s, mi), rngs[mi])
                        losses.append(loss)
                    else:
                        gp, gh = self._bwd_jit[s](
                            sp[s], st[s], h_in, g_next.pop((s, mi)),
                            fm(s, mi), rngs[mi])
                        ns = None
                    jax.block_until_ready(gp)
                    live[s] -= 1
                    live_b[s] -= stash_bytes.pop((s, mi))
                    grads[s] = gp if grads[s] is None else \
                        _tree_add(grads[s], gp)
                    if s > 0:
                        wire_bwd += _tree_bytes(gh)
                        g_next[(s - 1, mi)] = self._put(s - 1, gh)
                    if ns is not None:
                        ns_by_stage[s] = ns
                durs[s] = time.perf_counter() - t0
            tick_durs.append(durs)
        # apply: one update per batch per stage, like the dense step
        it = jnp.asarray(m.iteration_count)
        scale = jnp.asarray(1.0 / m_n, jnp.float32)
        reg_total = 0.0
        new_states = {}
        for s in range(s_n):
            names = part.stage_entries(s)
            us = {k: m.updater_states.get(k, ()) for k in names}
            new_p, new_u, reg = self._apply_jit[s](
                sp[s], us, grads[s], scale, it)
            m.params.update(new_p)
            m.updater_states.update(new_u)
            reg_total += float(reg)
            if s in ns_by_stage:
                new_states.update(ns_by_stage[s])
        data_loss = sum(float(l) for l in losses) / m_n
        loss = data_loss + reg_total
        stage_busy = [sum(d[s] for d in tick_durs) for s in range(s_n)]
        stage_idle = [sum(max(d) - d[s] for d in tick_durs)
                      for s in range(s_n)]
        return {
            "_loss": loss,
            "_states": new_states,
            "schedule": self.schedule,
            "n_stages": s_n,
            "n_micro": m_n,
            "bubble_fraction": bubble_fraction(s_n, m_n),
            "bubble_seconds": sum(stage_idle),
            "stage_busy_seconds": stage_busy,
            "stage_idle_seconds": stage_idle,
            "peak_residency_microbatches": peak,
            "peak_residency_bytes": peak_b,
            "pipe_wire_fwd_bytes": wire_fwd,
            "pipe_wire_bwd_bytes": wire_bwd,
            "pipe_wire_bytes": wire_fwd + wire_bwd,
            "stage_param_bytes": part.stage_param_bytes(m.params),
        }
