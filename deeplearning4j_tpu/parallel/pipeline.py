"""Pipeline parallelism — GPipe-style microbatching over a mesh axis.

The reference has NO pipeline parallelism (SURVEY.md §2.6 P8: ABSENT).
This is the TPU-native extension: the layer stack is split into
``n_stages`` contiguous stages laid out along a mesh ``pipe`` axis;
microbatches stream through the stages with activations handed to the
next stage via ``lax.ppermute`` (a neighbor exchange that rides ICI).

Everything is expressed as ONE ``lax.scan`` over clock ticks inside
``shard_map``, so:
- XLA sees a static loop — compiles once, overlaps the ppermute with
  the next tick's compute where possible;
- the schedule is fully differentiable: the VJP of ``ppermute`` is the
  reverse permute and the VJP of ``scan`` is a reverse-time scan, so
  ``jax.grad`` of a pipelined loss IS the backward pipeline (bubbles
  and all) with no hand-written 1F1B machinery;
- ``jax.checkpoint`` on the stage fn gives the standard
  remat-per-microbatch memory policy.

Bubble fraction is the GPipe ``(S-1)/(M+S-1)``; pick
``n_micro >> n_stages`` to amortise.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PIPE_AXIS = "pipe"


def to_microbatches(x, n_micro: int):
    """[b, ...] -> [n_micro, b/n_micro, ...] (leading-dim split)."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} not divisible by {n_micro} microbatches")
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def from_microbatches(x):
    return x.reshape((-1,) + x.shape[2:])


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis: str = PIPE_AXIS,
                   remat: bool = False,
                   with_aux: bool = False,
                   varying_axes: Optional[tuple] = None):
    """Run microbatches through the stage pipeline (inside shard_map).

    stage_fn(params, x) -> y with ``y.shape == x.shape`` (transformer
    blocks preserve [mb, t, d], so stacks satisfy this naturally).
    ``stage_params`` are THIS device's stage weights. ``x_micro`` is
    [n_micro, mb, ...], same on every stage (only stage 0 reads it).
    Returns [n_micro, mb, ...]; rows are valid on the LAST stage.

    With ``with_aux`` the stage fn returns ``(y, aux_scalar)`` (e.g. a
    MoE load-balancing loss); returns ``(outputs, aux_sum)`` where
    ``aux_sum`` accumulates only *valid* ticks — warm-up/drain bubble
    ticks compute on garbage activations and must not contribute.
    """
    n_st = _axis_size_concrete(axis)
    stage = lax.axis_index(axis)
    n_micro = x_micro.shape[0]
    raw = stage_fn if with_aux else (
        lambda p, x: (stage_fn(p, x), jnp.zeros((), x.dtype)))
    fn = jax.checkpoint(raw) if remat else raw
    perm = [(i, (i + 1) % n_st) for i in range(n_st)]

    def tick(carry, t):
        state, outputs, aux_acc = carry
        inject = x_micro[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, inject, state)
        y, aux = fn(stage_params, x_in)
        valid = (t >= stage) & (t - stage < n_micro)
        aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        out_idx = jnp.clip(t - (n_st - 1), 0, n_micro - 1)
        collect = (stage == n_st - 1) & (t >= n_st - 1)
        outputs = jnp.where(collect, outputs.at[out_idx].set(y), outputs)
        state = lax.ppermute(y, axis, perm)
        return (state, outputs, aux_acc), None

    vaxes = tuple(varying_axes) if varying_axes else (axis,)
    state0 = _varying(jnp.zeros_like(x_micro[0]), vaxes)
    out0 = _varying(jnp.zeros_like(x_micro), vaxes)
    aux0 = _varying(jnp.zeros((), x_micro.dtype), vaxes)
    (_, outputs, aux_sum), _ = lax.scan(
        tick, (state0, out0, aux0), jnp.arange(n_micro + n_st - 1))
    if with_aux:
        return outputs, aux_sum
    return outputs


def _varying(x, axes):
    """Mark x as device-varying over ``axes`` (shard_map VMA typing —
    the scan carry differs per stage even though it starts as zeros;
    with MoE/DP inside the stage fn it also varies over those axes)."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    have = getattr(getattr(x, "aval", None), "vma", frozenset())
    axes = tuple(a for a in axes if a not in have)
    if not axes:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


from .mesh import axis_size as _axis_size_concrete  # shared helper


def last_stage_only(value, axis: str = PIPE_AXIS):
    """Zero ``value`` except on the last pipeline stage, then psum —
    every stage ends up holding the last stage's value (the way a
    pipelined loss becomes a global scalar)."""
    n_st = lax.psum(1, axis)
    stage = lax.axis_index(axis)
    keep = (stage == n_st - 1).astype(value.dtype)
    return lax.psum(value * keep, axis)


def pipeline_loss(stage_fn: Callable, loss_fn: Callable, stage_params,
                  x_micro, y_micro, axis: str = PIPE_AXIS,
                  remat: bool = False):
    """Forward the pipeline and reduce a mean loss on the last stage.

    loss_fn(outputs_mb, labels_mb) -> scalar mean loss per microbatch.
    Returns the same scalar on every stage (safe to grad through).
    """
    outs = pipeline_apply(stage_fn, stage_params, x_micro, axis, remat)
    n_micro = x_micro.shape[0]
    per_mb = jax.vmap(loss_fn)(outs, y_micro)
    return last_stage_only(jnp.mean(per_mb), axis)


def init_stage_params(init_fn: Callable, axis: str = PIPE_AXIS):
    """Build THIS stage's params inside shard_map:
    ``init_fn(stage_index) -> params pytree`` (use lax.switch or
    index-folded RNG keys inside)."""
    return init_fn(lax.axis_index(axis))
