"""Scaling-efficiency measurement harness (BASELINE.md protocol step 3
for the north-star metric: aggregate throughput at 8..256 chips,
efficiency = (aggregate at N / aggregate at 8) * (8/N), pass >= 0.70
at N=256).

Runs the data-parallel train step on meshes built from device SUBSETS
(the same chips-per-run discipline a pod sweep uses), times a fixed
number of steps with a device-resident per-chip batch, and reports
per-size throughput + efficiency relative to the smallest size. On a
virtual CPU mesh the numbers validate only the MACHINERY — real
efficiency comes from an ICI-connected pod run of this same function.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Sequence

import jax
import numpy as np

from deeplearning4j_tpu.parallel.mesh import DEFAULT_DATA_AXIS, make_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def measure_dp_scaling(model_factory: Callable[[], object],
                       make_batch: Callable[[int], object],
                       chip_counts: Sequence[int],
                       *, per_chip_batch: int = 8, steps: int = 10,
                       warmup: int = 2,
                       devices: Optional[Sequence] = None) -> Dict:
    """Time DP training at each mesh size (weak scaling: the per-chip
    batch stays constant, the pod protocol).

    - ``model_factory()`` -> a fresh MultiLayerNetwork/ComputationGraph
    - ``make_batch(global_batch)`` -> a DataSet of that many examples
    - ``chip_counts`` e.g. (1, 2, 4, 8) locally; (8, 32, 64, 128, 256)
      on a pod.

    Returns {"sizes": [...], "throughput": {n: examples/sec},
    "efficiency": {n: eff vs smallest}, "base": n0}.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = [int(n) for n in chip_counts if n <= len(devices)]
    if not sizes:
        raise ValueError(f"no chip_counts fit {len(devices)} devices")
    throughput: Dict[int, float] = {}
    for n in sizes:
        mesh = make_mesh({DEFAULT_DATA_AXIS: n}, devices=devices[:n])
        net = model_factory()
        pw = ParallelWrapper(net, mesh)
        ds = make_batch(n * per_chip_batch)
        for _ in range(warmup):
            pw.fit_batch(ds)
        _sync(net)
        t0 = time.perf_counter()
        for _ in range(steps):
            pw.fit_batch(ds)
        _sync(net)
        dt = time.perf_counter() - t0
        global_batch = _batch_size(ds)
        throughput[n] = steps * global_batch / dt
    base = min(sizes)
    efficiency = {n: (throughput[n] / throughput[base]) * (base / n)
                  for n in sizes}
    return {"sizes": sizes, "throughput": throughput,
            "efficiency": efficiency, "base": base}


def _batch_size(ds) -> int:
    f = ds.features
    f = f[0] if isinstance(f, (list, tuple)) else f
    return int(np.asarray(f.shape[0]))


def _sync(net):
    jax.block_until_ready(net.params)
    s = net.score() if callable(getattr(net, "score", None)) else None
    if s is not None:
        float(s)


def scaling_report(result: Dict) -> str:
    """Human-readable table (the BASELINE.md step-3 artifact)."""
    lines = [f"{'chips':>6} {'examples/sec':>14} {'efficiency':>11}"]
    for n in result["sizes"]:
        lines.append(f"{n:>6} {result['throughput'][n]:>14.1f} "
                     f"{result['efficiency'][n]:>10.1%}")
    return "\n".join(lines)
