"""Expert parallelism — Mixture-of-Experts with all_to_all dispatch.

The reference has NO MoE (SURVEY.md §2.6 P10: ABSENT). TPU-native
extension, GShard/Switch style:

- **gating** is dense one-hot dispatch/combine einsums (MXU-friendly;
  no dynamic shapes, so XLA can tile it);
- **expert parallelism** shards the expert dimension over a mesh axis
  (canonically aliased to the ``data`` axis, DeepSpeed-style: expert
  weights replace the DP replication for expert params);
- tokens move to their experts and back via TWO ``lax.all_to_all``
  collectives (ICI), the canonical EP exchange.

Capacity model: each expert processes at most
``C = ceil(k * tokens/E * capacity_factor)`` tokens per shard;
overflow tokens are dropped (their combine weight is 0 and the
residual connection carries them through — standard Switch behavior).

All functions run inside ``shard_map``. Gradients flow through
dispatch/combine einsums and all_to_all transposes automatically.
"""
from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

EXPERT_AXIS = "expert"


def topk_gating(logits, k: int = 2, capacity: Optional[int] = None,
                capacity_factor: float = 1.25,
                rng: Optional[jax.Array] = None,
                noise_std: float = 0.0):
    """Top-k gating with capacity (GShard §3.2 / Switch top-1).

    logits: [n, E]. Returns (combine [n, E, C], dispatch [n, E, C]
    bool, aux_loss scalar, C).
    """
    n, e = logits.shape
    if capacity is None:
        capacity = max(4, math.ceil(k * n / e * capacity_factor))
    c = capacity
    if rng is not None and noise_std > 0.0:
        logits = logits + noise_std * jax.random.normal(
            rng, logits.shape, logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)          # [n, E]

    combine = jnp.zeros((n, e, c), logits.dtype)
    dispatch = jnp.zeros((n, e, c), bool)
    # running per-expert fill count, updated between the k passes
    fill = jnp.zeros((e,), jnp.int32)
    masked = probs
    gate_sum = jnp.zeros((n,), logits.dtype)
    picks = []
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)            # [n]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)   # [n, E]
        pos = fill[None, :] + jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum(pos * onehot, axis=-1)         # [n] queue slot
        keep = pos < c
        gate = jnp.take_along_axis(probs, idx[:, None], -1)[:, 0]
        gate = jnp.where(keep, gate, 0.0)
        gate_sum = gate_sum + gate
        picks.append((idx, pos, keep, gate))
        fill = fill + jnp.sum(onehot * keep[:, None], axis=0)
        masked = masked * (1 - onehot)               # exclude chosen

    # renormalize the kept gates so they sum to 1 per token (GShard)
    denom = jnp.maximum(gate_sum, 1e-9)
    for idx, pos, keep, gate in picks:
        w = (gate / denom)[:, None, None]
        hot = (jax.nn.one_hot(idx, e, dtype=logits.dtype)[:, :, None]
               * jax.nn.one_hot(pos, c, dtype=logits.dtype)[:, None, :])
        hot = hot * keep[:, None, None]
        combine = combine + w * hot
        dispatch = dispatch | (hot > 0)

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=probs.dtype)
    f = jnp.mean(top1, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)
    return combine, dispatch, aux, c


def moe_ffn(x, params, axis: Optional[str] = EXPERT_AXIS, k: int = 2,
            capacity_factor: float = 1.25,
            capacity: Optional[int] = None,
            activation: Callable = jax.nn.gelu,
            rng: Optional[jax.Array] = None,
            noise_std: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """MoE feed-forward over [b, t, d] activations.

    params: ``Wg [d, E]`` gate (replicated), ``Wi [E_local, d, ff]``,
    ``Wo [E_local, ff, d]`` expert weights (sharded over ``axis``).
    ``axis=None`` runs all experts locally (no EP — the tp=1 path).
    Returns (out [b, t, d], aux_loss).
    """
    b, t, d = x.shape
    xf = x.reshape(b * t, d)
    ep = _axis_size(axis)
    e_local = params["Wi"].shape[0]
    e = e_local * ep

    logits = xf @ params["Wg"]                        # [n, E]
    combine, dispatch, aux, c = topk_gating(
        logits, k=k, capacity=capacity,
        capacity_factor=capacity_factor, rng=rng, noise_std=noise_std)

    # dispatch tokens into per-expert slots: [E, C, d]
    slots = jnp.einsum("nec,nd->ecd", dispatch.astype(x.dtype), xf)
    if ep > 1:
        # [E, C, d] -> exchange expert dim for slot dim:
        # each device keeps its E/ep experts, receives every shard's
        # slots for them -> [E/ep, C*ep, d]
        slots = lax.all_to_all(slots, axis, split_axis=0,
                               concat_axis=1, tiled=True)

    h = activation(jnp.einsum("ecd,edf->ecf", slots, params["Wi"]))
    out = jnp.einsum("ecf,efd->ecd", h, params["Wo"])

    if ep > 1:
        out = lax.all_to_all(out, axis, split_axis=1, concat_axis=0,
                             tiled=True)
    y = jnp.einsum("nec,ecd->nd", combine, out)
    return y.reshape(b, t, d), aux


def _axis_size(axis: Optional[str]) -> int:
    from .mesh import axis_size
    return 1 if axis is None else axis_size(axis)


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    ep: int, ep_rank, dtype=jnp.float32):
    """One EP-shard of MoE params, sliced from globally-initialized
    weights so (ep=k) == (ep=1) numerically. ``ep_rank`` may be traced."""
    kg, ki, ko = jax.random.split(key, 3)
    wg = jax.random.normal(kg, (d_model, n_experts), dtype) \
        * (d_model ** -0.5)
    wi = jax.random.normal(
        ki, (n_experts, d_model, d_ff), dtype) * (d_model ** -0.5)
    wo = jax.random.normal(
        ko, (n_experts, d_ff, d_model), dtype) * (d_ff ** -0.5)
    el = n_experts // ep
    return {
        "Wg": wg,
        "Wi": lax.dynamic_slice_in_dim(wi, ep_rank * el, el, axis=0),
        "Wo": lax.dynamic_slice_in_dim(wo, ep_rank * el, el, axis=0),
    }
