"""ParallelWrapper: single-process multi-device data-parallel training.

Reference parity: ``org.deeplearning4j.parallelism.ParallelWrapper``
(SURVEY.md P1/P2, call stack 3.4) — N trainer threads with per-device
model replicas exchanging either periodically-averaged parameters
(``averagingFrequency``) or threshold-encoded shared gradients.

TPU-first design: there are no trainer threads and no replicas. The
model's jitted train step is already a pure SPMD function; sharding the
minibatch over the mesh ``data`` axis makes XLA's GSPMD partitioner
compile the per-shard forward/backward plus a single fused gradient
all-reduce (psum over ICI) into ONE program. Parameters live replicated
on the mesh and stay bit-identical on every device — exact synchronous
SGD every step, which is *stronger* than the reference's periodic
averaging and threshold-encoded (lossy) modes. `averagingFrequency` /
`TrainingMode` are accepted for API familiarity and ignored; see
`parallel.encoding` for the preserved compression semantics.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              DEFAULT_MODEL_AXIS, make_mesh,
                                              data_sharding,
                                              map_dataset_arrays,
                                              replicate_tree)

log = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Wrap a MultiLayerNetwork / ComputationGraph for multi-device DP.

    Usage (mirrors the reference builder)::

        pw = (ParallelWrapper.Builder(net)
              .workers(len(jax.devices()))
              .prefetch_buffer(2)
              .build())
        pw.fit(train_iterator)
    """

    #: reference TrainingMode values (accepted; all lower to the same
    #: exact in-step collective exchange on TPU)
    KNOWN_TRAINING_MODES = ("AVERAGING", "SHARED_GRADIENTS", "CUSTOM")

    def __init__(self, model, mesh=None, *,
                 data_axis: str = DEFAULT_DATA_AXIS,
                 model_axis: str = DEFAULT_MODEL_AXIS,
                 pipe_axis: str = "pipe",
                 prefetch_buffer: int = 2,
                 averaging_frequency: int = 1,
                 report_score_after_averaging: bool = True,
                 accumulation_steps: int = 1,
                 update_exchange="auto",
                 encoding=None,
                 n_micro: Optional[int] = None,
                 pipeline_schedule: str = "1f1b"):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.pipe_axis = pipe_axis
        #: tp degree, read off the mesh (1 on a pure-DP mesh)
        self.tensor_parallel = int(self.mesh.shape.get(model_axis, 1))
        #: pp degree, read off the mesh (1 = no pipeline stage axis)
        self.pipeline_stages = int(self.mesh.shape.get(pipe_axis, 1))
        self.n_micro = n_micro
        self.pipeline_schedule = pipeline_schedule
        #: the PipelineTrainer owning the fit path when pp > 1
        self._pipeline = None
        self.prefetch_buffer = prefetch_buffer
        self.averaging_frequency = averaging_frequency  # API parity only
        self.report_score = report_score_after_averaging
        self.accumulation_steps = max(int(accumulation_steps), 1)
        #: requested exchange ('auto'|'dense'|'sharded'|'fsdp');
        #: resolved to the effective UpdateExchange at placement time
        self.requested_exchange = update_exchange
        self.update_exchange = None
        #: EncodingSpec request for update_exchange='encoded' (None ->
        #: resolve_encoding default); resolved at placement
        self.requested_encoding = encoding
        self.encoding = None
        self._exchange_bytes = 0
        #: dense counterfactual of the encoded exchange (what the same
        #: step would move uncompressed) — 0 unless mode is encoded
        self._dense_wire_bytes = 0
        self._fsdp_gather_bytes = 0
        #: {entry: {name: TpLeafSpec}} inferred at placement (tp > 1)
        self._tp_specs = {}
        #: per-axis wire accounting (update_exchange_axis_bytes)
        self._axis_bytes = None
        self._placed = False
        if averaging_frequency != 1:
            log.info("averagingFrequency=%d ignored: pjit DP is exactly "
                     "synchronous every iteration", averaging_frequency)

    # -- Builder (reference API shape) ---------------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._prefetch = 2
            self._avg_freq = 1
            self._workers = None
            self._accum = 1
            self._exchange = "auto"
            self._encoding = None
            self._tp = 1
            self._pp = 1
            self._n_micro = None
            self._pp_sched = "1f1b"

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = n
            return self

        def pipeline_stages(self, n: int) -> "ParallelWrapper.Builder":
            """Split the layer stack into ``n`` contiguous pipeline
            stages over a third ``pipe`` mesh axis
            (parallel.pipeline.PipelineTrainer — the promoted 1F1B/
            GPipe microbatch engine). Composes with ``workers`` (dp)
            and ``tensor_parallel`` into a 3D ``(data, model, pipe)``
            mesh; total devices = workers * tp * pp. An ``fsdp``
            update_exchange downgrades to per-stage ZeRO-1 (flats stay
            local to each stage's pipe group)."""
            n = int(n)
            if n < 1:
                raise ValueError(f"pipeline_stages must be >= 1, got {n}")
            self._pp = n
            return self

        def microbatches(self, n: int) -> "ParallelWrapper.Builder":
            """Microbatches per step for the pipeline schedule (default
            ``2 * pipeline_stages``); the batch must divide by it."""
            self._n_micro = int(n)
            return self

        def pipeline_schedule(self, kind: str) -> "ParallelWrapper.Builder":
            """'1f1b' (default — bounded activation residency) or
            'gpipe' (the all-forward-then-backward reference)."""
            from deeplearning4j_tpu.parallel.pipeline import SCHEDULES
            if kind not in SCHEDULES:
                raise ValueError(f"unknown pipeline schedule {kind!r} "
                                 f"(know {SCHEDULES})")
            self._pp_sched = kind
            return self

        def tensor_parallel(self, n: int) -> "ParallelWrapper.Builder":
            """Shard model weights ``n``-ways over a second ``model``
            mesh axis (megatron-style column/row splits inferred per
            layer — parallel.speclayout). Composes with every
            update_exchange mode: dense×tp, sharded×tp, fsdp×tp. The
            built mesh is 2D ``(data, model)``; the data-parallel
            world size becomes ``devices // n``."""
            n = int(n)
            if n < 1:
                raise ValueError(f"tensor_parallel must be >= 1, got {n}")
            self._tp = n
            return self

        def mesh(self, mesh) -> "ParallelWrapper.Builder":
            self._mesh = mesh
            return self

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            self._prefetch = n
            return self

        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            self._avg_freq = n
            return self

        def accumulation_steps(self, n: int) -> "ParallelWrapper.Builder":
            """Apply the updater every ``n`` micro-batches on the mean
            gradient (reference: GradientsAccumulator) — effective
            batch scales n-fold with no extra activation HBM."""
            self._accum = n
            return self

        def update_exchange(self, mode) -> "ParallelWrapper.Builder":
            """'dense' | 'sharded' | 'fsdp' | 'encoded' | 'auto'
            (zero.UpdateExchange): how replicas exchange the weight
            update. 'fsdp' (ZeRO-3) additionally keeps params + grads
            resident 1/N per replica with per-layer just-in-time
            all-gather — opt-in; 'encoded' compresses the dp gradient
            exchange (quantized/threshold-sparsified collective with
            error feedback — see :meth:`encoding`); 'auto' resolves
            to 'sharded'."""
            from deeplearning4j_tpu.parallel.zero import UpdateExchange
            self._exchange = UpdateExchange(
                mode.lower() if isinstance(mode, str) else mode)
            return self

        def encoding(self, spec) -> "ParallelWrapper.Builder":
            """Codec for ``update_exchange('encoded')``: an
            ``EncodingSpec`` or a scheme string (``'threshold'`` —
            sign·tau sparse stream with adaptive tau, ``'int8'``,
            ``'1bit'`` — parallel.encoding). Ignored under every
            other exchange mode."""
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            self._encoding = resolve_encoding(spec)
            return self

        def training_mode(self, mode) -> "ParallelWrapper.Builder":
            # AVERAGING / SHARED_GRADIENTS / CUSTOM: all lower to the
            # same exact in-step collective exchange on TPU
            name = str(getattr(mode, "name", mode)).upper()
            if name not in ParallelWrapper.KNOWN_TRAINING_MODES:
                log.warning(
                    "unknown training_mode %r (known: %s); every known "
                    "mode lowers to the same exact in-step exchange",
                    mode, ", ".join(ParallelWrapper.KNOWN_TRAINING_MODES))
            return self

        def build(self) -> "ParallelWrapper":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                group = self._tp * self._pp
                if group > 1:
                    # 2D/3D (data, model[, pipe]) mesh: ``workers``
                    # counts the data-parallel groups; total devices =
                    # workers * tp * pp
                    if self._workers:
                        devs = devs[:self._workers * group]
                    if len(devs) % group or len(devs) < group:
                        raise ValueError(
                            f"tensor_parallel={self._tp} x "
                            f"pipeline_stages={self._pp} does not "
                            f"divide {len(devs)} devices")
                    axes = {DEFAULT_DATA_AXIS: -1}
                    if self._tp > 1:
                        axes[DEFAULT_MODEL_AXIS] = self._tp
                    if self._pp > 1:
                        from deeplearning4j_tpu.parallel.pipeline \
                            import PIPE_AXIS
                        axes[PIPE_AXIS] = self._pp
                    mesh = make_mesh(axes, devs)
                else:
                    if self._workers:
                        devs = devs[:self._workers]
                    mesh = make_mesh({DEFAULT_DATA_AXIS: len(devs)}, devs)
            return ParallelWrapper(self._model, mesh,
                                   prefetch_buffer=self._prefetch,
                                   averaging_frequency=self._avg_freq,
                                   accumulation_steps=self._accum,
                                   update_exchange=self._exchange,
                                   encoding=self._encoding,
                                   n_micro=self._n_micro,
                                   pipeline_schedule=self._pp_sched)

    # ------------------------------------------------------------------
    @property
    def n_workers(self) -> int:
        return self.mesh.shape[self.data_axis]

    def _place_model(self):
        """Place params/opt-state on the mesh (one-time device_put;
        afterwards XLA keeps them resident and in sync). Params/states
        go replicated; with the ZeRO-1 sharded exchange the updater
        state goes 1/N per replica along the data axis instead
        (parallel.zero — the Adam-family HBM win). On a 2D
        ``(data, model)`` mesh the tp leaves (parallel.speclayout
        inference) are additionally placed at their megatron
        column/row shardings — GSPMD inserts the activation psums, and
        the update exchange stays strictly inside the ``data`` axis."""
        m = self.model
        if not m._initialized:
            m.init()
        from deeplearning4j_tpu.parallel.zero import (
            UpdateExchange, ensure_encoded_states, exchange_report,
            place_tp_params, place_updater_states,
            resolve_update_exchange, states_to_dense, states_to_sharded,
            strip_encoded_states, update_exchange_axis_bytes,
            update_exchange_bytes)
        mode = resolve_update_exchange(self.mesh, self.data_axis,
                                       self.requested_exchange, m)
        if mode is UpdateExchange.ENCODED and \
                not hasattr(m, "set_dp_mesh"):
            log.info("%s has no set_dp_mesh; encoded request lowers to "
                     "dense", type(m).__name__)
            mode = UpdateExchange.DENSE
        self.update_exchange = mode
        if mode is UpdateExchange.ENCODED:
            from deeplearning4j_tpu.parallel.encoding import \
                resolve_encoding
            self.encoding = resolve_encoding(self.requested_encoding)
        else:
            self.encoding = None
        if self.pipeline_stages > 1:
            if mode is UpdateExchange.ENCODED:
                log.info("encoded update exchange does not compose "
                         "with pipeline stages yet; using per-stage "
                         "sharded (ZeRO-1, uncompressed)")
                mode = self.update_exchange = UpdateExchange.SHARDED
                self.encoding = None
            self._place_pipeline(mode)
            return
        tp = self.tensor_parallel
        if tp > 1 and not hasattr(m, "set_dp_mesh"):
            log.info("%s has no set_dp_mesh; tensor_parallel=%d lowers "
                     "to replicated weights", type(m).__name__, tp)
            tp = 1
        if hasattr(m, "_params_are_fsdp") and m._params_are_fsdp():
            # elastic re-place: params still resident as 1/N flats from
            # a previous mesh.  If the world size changed, the mode
            # did, or a tp partition is requested (the specs below are
            # inferred from dense shapes), round-trip through the dense
            # layout so the wire accounting and the re-entry see real
            # shapes.
            from deeplearning4j_tpu.parallel.zero import fsdp_spec_shards
            stale_n = fsdp_spec_shards(getattr(m, "_fsdp_specs", {}) or {})
            if (mode is not UpdateExchange.FSDP
                    or stale_n != self.n_workers or tp > 1
                    or getattr(m, "_tp_specs", None)):
                m.set_dp_mesh(None, self.data_axis)
        self._tp_specs = {}
        if tp > 1:
            from deeplearning4j_tpu.parallel.speclayout import SpecLayout
            layout = SpecLayout(self.mesh, model_axis=self.model_axis,
                                data_axis=self.data_axis)
            # ZeRO tails keep the tp leaves' between-step residency
            # additionally sharded over data (1/(dp*tp) per chip)
            self._tp_specs = layout.infer(
                m.params, shard_over_data=mode in (
                    UpdateExchange.SHARDED, UpdateExchange.FSDP,
                    UpdateExchange.ENCODED))
        import numpy as np
        # wire accounting while params are still in the dense layout
        # (the fsdp conversion below folds them into padded flats)
        n = self.n_workers
        param_bytes = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves(m.params)
            if hasattr(a, "shape"))
        self._exchange_bytes = update_exchange_bytes(m.params, n, mode)
        self._dense_wire_bytes = 0
        self._fsdp_gather_bytes = (
            int((n - 1) * param_bytes / n) if n > 1 else 0)
        self._axis_bytes = None
        if self._tp_specs:
            self._axis_bytes = update_exchange_axis_bytes(
                m.params, n, tp, self._tp_specs)
            # dp collectives only ever move each model-shard group's
            # own 1/tp slice of the tp leaves
            self._exchange_bytes = self._axis_bytes["data"]
            tpb = self._axis_bytes["tp_param_bytes"]
            self._fsdp_gather_bytes = (
                int((n - 1) * ((param_bytes - tpb) + tpb // tp) / n)
                if n > 1 else 0)
            if telemetry.enabled():
                telemetry.gauge(
                    "dl4j_tp_param_shard_bytes",
                    "per-chip bytes of the tensor-parallel weight "
                    "shards after 2D placement (1/tp of the tp leaves; "
                    "x1/dp more under fsdp residency)").set(
                        tpb // (tp * (n if mode is UpdateExchange.FSDP
                                      else 1)),
                        model_shards=tp, mode=mode.value)
        if mode is UpdateExchange.ENCODED:
            # analytic codec estimate (planning sparsity) while params
            # are dense; run_epochs refines the live series per step
            # from the observed sparsity gauge
            rep = exchange_report(
                m.params, n, mode, model_shards=tp,
                tp_specs=self._tp_specs or None, encoding=self.encoding)
            self._dense_wire_bytes = rep["dense_wire_bytes"]
            self._exchange_bytes = rep["encoded_wire_bytes"]
        if mode is UpdateExchange.FSDP and not hasattr(m, "set_dp_mesh"):
            log.info("%s has no set_dp_mesh; fsdp request lowers to "
                     "dense", type(m).__name__)
            mode = self.update_exchange = UpdateExchange.DENSE
        if mode is UpdateExchange.FSDP:
            # ZeRO-3: the model owns param + updater-state conversion
            # and placement (1/N flat shards per replica) — params are
            # NOT replicated here, that would defeat the residency win
            m.states = replicate_tree(self.mesh, m.states)
            m.set_dp_mesh(self.mesh, self.data_axis, mode="fsdp",
                          model_axis=self.model_axis,
                          tp_specs=self._tp_specs)
        else:
            if self._tp_specs:
                # dense layout, 2D placement: tp leaves at their
                # compute sharding, everything else replicated
                m.params = place_tp_params(self.mesh, m.params,
                                           self._tp_specs)
            else:
                m.params = replicate_tree(self.mesh, m.params)
            m.states = replicate_tree(self.mesh, m.states)
            if hasattr(m, "set_dp_mesh"):
                if self._tp_specs:
                    # the mesh must install even for the dense tail so
                    # the step pins tp leaves (mode="dense" keeps the
                    # dp-flat machinery out of the update)
                    m.set_dp_mesh(
                        self.mesh, self.data_axis,
                        mode=("encoded"
                              if mode is UpdateExchange.ENCODED
                              else "sharded"
                              if mode is UpdateExchange.SHARDED
                              else "dense"),
                        model_axis=self.model_axis,
                        tp_specs=self._tp_specs,
                        encoding=self.encoding)
                elif mode is UpdateExchange.ENCODED:
                    m.set_dp_mesh(self.mesh, self.data_axis,
                                  mode="encoded",
                                  encoding=self.encoding)
                else:
                    m.set_dp_mesh(self.mesh
                                  if mode is UpdateExchange.SHARDED
                                  else None, self.data_axis)
        if hasattr(m, "set_accumulation_steps"):
            m.set_accumulation_steps(self.accumulation_steps)
        elif self.accumulation_steps > 1:
            log.warning("accumulation_steps=%d ignored: %s has no "
                        "gradient accumulation support",
                        self.accumulation_steps, type(m).__name__)
        if mode is UpdateExchange.FSDP:
            pass    # set_dp_mesh(mode="fsdp") placed the updater state
        elif mode is UpdateExchange.ENCODED:
            # ZeRO-1 flats + error-feedback residual (zero residual
            # injected unless a checkpoint restored one — any device
            # count: the flats re-ravel for this mesh)
            m.updater_states = place_updater_states(
                self.mesh,
                ensure_encoded_states(m.params, m.updater_states,
                                      self.n_workers, self.encoding,
                                      tp_specs=self._tp_specs),
                self.data_axis, tp_specs=self._tp_specs)
        elif mode is UpdateExchange.SHARDED:
            m.updater_states = place_updater_states(
                self.mesh,
                states_to_sharded(m.params,
                                  strip_encoded_states(m.updater_states),
                                  self.n_workers,
                                  tp_specs=self._tp_specs),
                self.data_axis, tp_specs=self._tp_specs)
        else:
            # a sharded/encoded layout left by a previous placement (or
            # a restored ZeRO-1 checkpoint) converts back to dense
            # first (the encoded residual belongs to that exchange)
            m.updater_states = replicate_tree(
                self.mesh, strip_encoded_states(
                    states_to_dense(m.params, m.updater_states)))
        self._placed = True

    def _place_pipeline(self, mode):
        """pp > 1: hand placement and the fit path to the
        PipelineTrainer (parallel.pipeline). Params stay logically
        dense per stage — checkpoints remain stage-count-portable —
        and each stage's update tail (dense or per-stage ZeRO-1, tp
        pinned) stays local to its pipe group."""
        from deeplearning4j_tpu.parallel.pipeline import PipelineTrainer
        from deeplearning4j_tpu.parallel.zero import (
            update_exchange_axis_bytes, update_exchange_bytes)
        m = self.model
        tr = PipelineTrainer(
            m, self.mesh, n_micro=self.n_micro,
            schedule=self.pipeline_schedule, mode=mode,
            pipe_axis=self.pipe_axis, data_axis=self.data_axis,
            model_axis=self.model_axis)
        tr.place()
        self._pipeline = tr
        self._tp_specs = {}
        for specs in tr._tp_specs:
            self._tp_specs.update(specs)
        # per-stage wire accounting: each stage's dp group exchanges
        # only its OWN stage's params (never crossing the pipe axis)
        self._exchange_bytes = sum(
            update_exchange_bytes(
                {k: m.params[k] for k in tr.part.stage_entries(s)
                 if k in m.params}, tr.dp, mode)
            for s in range(tr.n_stages))
        self._fsdp_gather_bytes = 0
        self._axis_bytes = None
        if self._tp_specs:
            self._axis_bytes = update_exchange_axis_bytes(
                m.params, tr.dp, self.tensor_parallel, self._tp_specs)
        self._placed = True

    def _fit_model(self, ds):
        """One training batch through whichever engine owns the fit
        path — the model's own fused step, or the pipeline schedule."""
        if self._pipeline is not None:
            self._pipeline.fit_batch(ds)
        else:
            self.model.fit(ds)

    def _shard(self, a):
        if a is None or not hasattr(a, "ndim") or getattr(a, "ndim", 0) == 0:
            return a
        return jax.device_put(
            jnp.asarray(a),
            data_sharding(self.mesh, a.ndim if hasattr(a, "ndim")
                          else jnp.asarray(a).ndim, self.data_axis))

    def _shard_dataset(self, ds):
        """Return a shallow copy of the DataSet/MultiDataSet with every
        array trimmed to a data-axis multiple and sharded over the mesh."""
        n = self.n_workers

        def trim(a):
            a = jnp.asarray(a)
            b = (a.shape[0] // n) * n
            if b == 0:
                raise ValueError(
                    f"minibatch of {a.shape[0]} < {n} data-parallel "
                    f"shards; increase batch size")
            if b != a.shape[0]:
                log.warning("trimming minibatch %d -> %d for %d-way DP",
                            a.shape[0], b, n)
                a = a[:b]
            if self._pipeline is not None:
                # the PipelineTrainer splits into microbatches and
                # places each on its stage's submesh itself (and its
                # to_microbatches raises the non-divisible error with
                # the batch intact)
                return a
            return self._shard(a)

        return map_dataset_arrays(ds, trim)

    # ------------------------------------------------------------------
    def fit(self, iterator, *, n_epochs: int = 1) -> "ParallelWrapper":
        """fit(DataSetIterator) — same contract as model.fit, executed
        as one SPMD program over the mesh."""
        return self.run_epochs(iterator, n_epochs, self._shard_dataset)

    def run_epochs(self, iterator, n_epochs, shard_fn):
        """The one epoch/reset/listener loop, parameterized by how each
        batch is placed on the mesh (single-host shard vs multi-host
        global assembly — SharedTrainingMaster passes its own).

        Placement runs via DevicePrefetcher a batch ahead of the step
        loop (feeder-thread on accelerator backends), so the per-shard
        H2D DMA of batch n+1 overlaps the device step on batch n (the
        reference's prefetch workers; ``prefetch_buffer`` is the
        staging depth)."""
        if not self._placed:
            self._place_model()
        from deeplearning4j_tpu.common import stepstats
        from deeplearning4j_tpu.datasets.prefetch import \
            maybe_device_prefetch
        # label this process's breakdowns for the scaling observatory
        # (single-host: worker 0 of 1; SharedTrainingMaster re-labels
        # per jax process before handing off to this loop)
        stepstats.collector().set_worker(jax.process_index(),
                                         jax.process_count())
        n = self.n_workers
        shard_fn = self._timed_place(shard_fn, n)
        staged = maybe_device_prefetch(iterator, place_fn=shard_fn,
                                       depth=self.prefetch_buffer)
        if staged is not iterator:
            shard_fn = lambda ds: ds     # noqa: E731 — already placed
        for _ in range(n_epochs):
            if hasattr(staged, "reset"):
                staged.reset()
            for lis in self.model.listeners:
                lis.on_epoch_start(self.model)
            for ds in staged:
                ds = shard_fn(ds)
                if telemetry.enabled():
                    # the sharded step COMPILES the update exchange in
                    # (dense: gradient all-reduce; ZeRO-1: reduce-
                    # scatter + all-gather) — this is the whole
                    # replica-sync step the reference's trainer threads
                    # + averaging round performed. The span bounds the
                    # fused step and carries the exchange volume, so
                    # the collective cost shows on the one timeline.
                    mode = self.update_exchange.value
                    t0 = time.perf_counter()
                    from deeplearning4j_tpu.common.diagnostics import \
                        collective_span
                    with collective_span("update_exchange",
                                         self.data_axis,
                                         self._exchange_bytes,
                                         mode=mode):
                        self._fit_model(ds)
                    telemetry.histogram(
                        "dl4j_dp_step_seconds",
                        "data-parallel sharded step wall time incl. "
                        "the fused in-step gradient all-reduce "
                        "(seconds)").observe(
                            time.perf_counter() - t0, workers=n)
                    telemetry.counter(
                        "dl4j_dp_update_exchange_bytes_total",
                        "estimated per-replica wire bytes moved by the "
                        "in-step update exchange (ring collectives)"
                    ).inc(self._exchange_bytes, mode=mode)
                    if self._axis_bytes is not None:
                        axis_c = telemetry.counter(
                            "dl4j_update_exchange_axis_bytes_total",
                            "per-mesh-axis wire bytes of the update "
                            "exchange on a 2D (data, model) mesh; the "
                            "model-axis series staying at 0 is the 2D "
                            "layout invariant (dp collectives never "
                            "cross the model axis)")
                        axis_c.inc(self._axis_bytes["data"],
                                   axis=self.data_axis)
                        axis_c.inc(self._axis_bytes["model"],
                                   axis=self.model_axis)
                    if mode == "fsdp":
                        telemetry.counter(
                            "dl4j_fsdp_gather_bytes_total",
                            "estimated per-replica wire bytes moved by "
                            "the per-layer just-in-time fsdp param "
                            "all-gathers (ring model, analytic)"
                        ).inc(self._fsdp_gather_bytes, workers=n)
                    elif mode == "encoded":
                        self._emit_encoded_telemetry(n)
                else:
                    self._fit_model(ds)
                from deeplearning4j_tpu.common import faults
                if faults.preemption_requested():
                    # coordinated resumable exit: close the partial
                    # accumulation window, then unwind to whoever owns
                    # the checkpoint (FaultTolerantTrainer /
                    # SharedTrainingMaster saves before re-raising)
                    if hasattr(self.model, "flush_accumulated"):
                        self.model.flush_accumulated()
                    raise faults.TrainingPreempted(
                        "preempted at iteration %d" %
                        self.model.iteration_count)
            if hasattr(self.model, "flush_accumulated"):
                # a partial accumulation window must not leak into the
                # next epoch
                self.model.flush_accumulated()
            self.model.epoch_count += 1
            for lis in self.model.listeners:
                lis.on_epoch_end(self.model)
        return self

    def _observed_encoding_sparsity(self):
        """Size-weighted mean of the per-entry transmitted-fraction
        scalars the encoded step tail left in updater state
        (``learning.updaters.ENCODED_KEY``) — ``None`` before the
        first applied step or when no entry runs the encoded tail."""
        from deeplearning4j_tpu.learning.updaters import (ENCODED_KEY,
                                                          is_encoded)
        states = getattr(self.model, "updater_states", None)
        if not isinstance(states, dict):
            return None
        num, den = 0.0, 0
        for s in states.values():
            if is_encoded(s):
                enc = s[ENCODED_KEY]
                elems = sum(int(v.size)
                            for v in enc["residual"].values())
                num += float(enc["sparsity"]) * elems
                den += elems
        return (num / den) if den else None

    def _emit_encoded_telemetry(self, workers: int):
        """Per-step encoded-exchange series: the LIVE transmitted
        fraction read back from updater state (not a host-side shadow
        encode), the codec wire bytes it implies, and the ratio vs the
        dense counterfactual the same step would have moved."""
        from deeplearning4j_tpu.parallel.zero import exchange_report
        sp = self._observed_encoding_sparsity()
        rep = exchange_report(
            self.model.params, workers, self.update_exchange,
            model_shards=self.tensor_parallel,
            tp_specs=self._tp_specs or None,
            encoding=self.encoding, observed_sparsity=sp)
        scheme = self.encoding.scheme
        telemetry.gauge(
            "dl4j_dp_encoding_sparsity",
            "fraction of gradient elements the encoder transmits "
            "(live per-step encoded-rung wire density; drives the "
            "adaptive tau)").set(
                rep["encoding_sparsity"], scheme=scheme)
        telemetry.counter(
            "dl4j_encoded_wire_bytes_total",
            "per-replica wire bytes the compressed update exchange "
            "moved (ring model over the codec payload; the dense "
            "counterfactual is dl4j_dp_update_exchange_bytes_total "
            "at mode=dense)").inc(
                rep["encoded_wire_bytes"], scheme=scheme)
        telemetry.gauge(
            "dl4j_encoded_compression_ratio",
            "dense-counterfactual wire bytes / encoded wire bytes of "
            "the update exchange (strictly > 1 while the codec is "
            "winning)").set(
                rep["compression_ratio"], scheme=scheme)
        # the span/counter estimate tracks the live sparsity too
        self._exchange_bytes = rep["encoded_wire_bytes"]
        self._dense_wire_bytes = rep["dense_wire_bytes"]

    @staticmethod
    def _timed_place(shard_fn, workers: int):
        """Wrap a batch-placement fn so per-batch shard/assembly time
        (which runs on the prefetch feeder thread) is measured."""
        def place(ds):
            if not telemetry.enabled():
                return shard_fn(ds)
            with telemetry.span("dp.place", workers=workers):
                t0 = time.perf_counter()
                out = shard_fn(ds)
                telemetry.histogram(
                    "dl4j_dp_place_seconds",
                    "per-batch shard/global-assembly dispatch time on "
                    "the feeder thread (seconds)").observe(
                        time.perf_counter() - t0, workers=workers)
            return out
        return place

    def remesh(self, mesh=None, *, workers: Optional[int] = None
               ) -> "ParallelWrapper":
        """Elastic world-size change: re-place the model onto ``mesh``
        (or onto the first ``workers`` devices).  The update exchange is
        re-resolved for the new mesh and any dense/sharded/fsdp layout
        resident for the old world size round-trips through the dense
        layout during ``_place_model`` — training continues the exact
        dense trajectory with the new device count.  A tp degree from
        :meth:`Builder.tensor_parallel` is preserved (``workers`` again
        counts data-parallel groups); pass an explicit 1D ``mesh`` to
        restore a 2D run onto a pure-DP world.

        A pipe axis is different: while pipeline stages are placed, a
        remesh that would CHANGE the pipe degree is rejected — the
        stage partition, per-stage jits, and per-stage updater flats
        are all keyed to it, and silently re-slicing mid-run would
        leave a stale stage layout. Call :meth:`shutdown` first (the
        checkpoint stays dense and stage-count-portable), or rebuild
        via ``ParallelWrapper.Builder.pipeline_stages``."""
        if mesh is None:
            devs = jax.devices()
            tp = self.tensor_parallel
            pp = self.pipeline_stages
            group = tp * pp
            if group > 1:
                if workers:
                    devs = devs[:workers * group]
                if len(devs) % group:
                    raise ValueError(
                        f"tensor_parallel={tp} x pipeline_stages={pp} "
                        f"does not divide {len(devs)} devices")
                axes = {self.data_axis: -1}
                if tp > 1:
                    axes[self.model_axis] = tp
                if pp > 1:
                    axes[self.pipe_axis] = pp
                mesh = make_mesh(axes, devs)
            else:
                if workers:
                    devs = devs[:workers]
                mesh = make_mesh({self.data_axis: len(devs)}, devs)
        new_pp = int(mesh.shape.get(self.pipe_axis, 1))
        if self._pipeline is not None and self._placed \
                and new_pp != self.pipeline_stages:
            raise ValueError(
                f"remesh cannot change the pipe axis while pipeline "
                f"stages are placed (pipeline_stages="
                f"{self.pipeline_stages} -> {new_pp}): the stage "
                f"partition and per-stage updater flats are keyed to "
                f"it. shutdown() first (checkpoints are dense and "
                f"stage-count-portable), then rebuild with "
                f"ParallelWrapper.Builder.pipeline_stages({new_pp}).")
        self.mesh = mesh
        self.tensor_parallel = int(mesh.shape.get(self.model_axis, 1))
        self.pipeline_stages = new_pp
        self._pipeline = None
        self.update_exchange = None
        self._placed = False
        self._place_model()
        return self

    def fit_batch(self, ds):
        if not self._placed:
            self._place_model()
        self._fit_model(self._shard_dataset(ds))
        return self

    def average_score(self) -> float:
        return self.model.score()

    def shutdown(self):
        """Reference API: stop trainer threads. Releases the pipeline
        stage layout (if any), so a later remesh may change the pipe
        degree."""
        self._placed = False
        self._pipeline = None
