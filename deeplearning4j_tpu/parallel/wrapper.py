"""ParallelWrapper: single-process multi-device data-parallel training.

Reference parity: ``org.deeplearning4j.parallelism.ParallelWrapper``
(SURVEY.md P1/P2, call stack 3.4) — N trainer threads with per-device
model replicas exchanging either periodically-averaged parameters
(``averagingFrequency``) or threshold-encoded shared gradients.

TPU-first design: there are no trainer threads and no replicas. The
model's jitted train step is already a pure SPMD function; sharding the
minibatch over the mesh ``data`` axis makes XLA's GSPMD partitioner
compile the per-shard forward/backward plus a single fused gradient
all-reduce (psum over ICI) into ONE program. Parameters live replicated
on the mesh and stay bit-identical on every device — exact synchronous
SGD every step, which is *stronger* than the reference's periodic
averaging and threshold-encoded (lossy) modes. `averagingFrequency` /
`TrainingMode` are accepted for API familiarity and ignored; see
`parallel.encoding` for the preserved compression semantics.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS, make_mesh,
                                              data_sharding,
                                              map_dataset_arrays,
                                              replicate_tree)

log = logging.getLogger("deeplearning4j_tpu")


class ParallelWrapper:
    """Wrap a MultiLayerNetwork / ComputationGraph for multi-device DP.

    Usage (mirrors the reference builder)::

        pw = (ParallelWrapper.Builder(net)
              .workers(len(jax.devices()))
              .prefetch_buffer(2)
              .build())
        pw.fit(train_iterator)
    """

    def __init__(self, model, mesh=None, *,
                 data_axis: str = DEFAULT_DATA_AXIS,
                 prefetch_buffer: int = 2,
                 averaging_frequency: int = 1,
                 report_score_after_averaging: bool = True):
        self.model = model
        self.mesh = mesh if mesh is not None else make_mesh()
        self.data_axis = data_axis
        self.prefetch_buffer = prefetch_buffer
        self.averaging_frequency = averaging_frequency  # API parity only
        self.report_score = report_score_after_averaging
        self._placed = False
        if averaging_frequency != 1:
            log.info("averagingFrequency=%d ignored: pjit DP is exactly "
                     "synchronous every iteration", averaging_frequency)

    # -- Builder (reference API shape) ---------------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._prefetch = 2
            self._avg_freq = 1
            self._workers = None

        def workers(self, n: int) -> "ParallelWrapper.Builder":
            self._workers = n
            return self

        def mesh(self, mesh) -> "ParallelWrapper.Builder":
            self._mesh = mesh
            return self

        def prefetch_buffer(self, n: int) -> "ParallelWrapper.Builder":
            self._prefetch = n
            return self

        def averaging_frequency(self, n: int) -> "ParallelWrapper.Builder":
            self._avg_freq = n
            return self

        def training_mode(self, _mode) -> "ParallelWrapper.Builder":
            # AVERAGING / SHARED_GRADIENTS / CUSTOM: all lower to the
            # same exact in-step all-reduce on TPU
            return self

        def build(self) -> "ParallelWrapper":
            mesh = self._mesh
            if mesh is None:
                devs = jax.devices()
                if self._workers:
                    devs = devs[:self._workers]
                mesh = make_mesh({DEFAULT_DATA_AXIS: len(devs)}, devs)
            return ParallelWrapper(self._model, mesh,
                                   prefetch_buffer=self._prefetch,
                                   averaging_frequency=self._avg_freq)

    # ------------------------------------------------------------------
    @property
    def n_workers(self) -> int:
        return self.mesh.shape[self.data_axis]

    def _place_model(self):
        """Replicate params/opt-state on the mesh (one-time device_put;
        afterwards XLA keeps them resident and in sync)."""
        m = self.model
        if not m._initialized:
            m.init()
        m.params = replicate_tree(self.mesh, m.params)
        m.states = replicate_tree(self.mesh, m.states)
        m.updater_states = replicate_tree(self.mesh, m.updater_states)
        self._placed = True

    def _shard(self, a):
        if a is None or not hasattr(a, "ndim") or getattr(a, "ndim", 0) == 0:
            return a
        return jax.device_put(
            jnp.asarray(a),
            data_sharding(self.mesh, a.ndim if hasattr(a, "ndim")
                          else jnp.asarray(a).ndim, self.data_axis))

    def _shard_dataset(self, ds):
        """Return a shallow copy of the DataSet/MultiDataSet with every
        array trimmed to a data-axis multiple and sharded over the mesh."""
        n = self.n_workers

        def trim(a):
            a = jnp.asarray(a)
            b = (a.shape[0] // n) * n
            if b == 0:
                raise ValueError(
                    f"minibatch of {a.shape[0]} < {n} data-parallel "
                    f"shards; increase batch size")
            if b != a.shape[0]:
                log.warning("trimming minibatch %d -> %d for %d-way DP",
                            a.shape[0], b, n)
                a = a[:b]
            return self._shard(a)

        return map_dataset_arrays(ds, trim)

    # ------------------------------------------------------------------
    def fit(self, iterator, *, n_epochs: int = 1) -> "ParallelWrapper":
        """fit(DataSetIterator) — same contract as model.fit, executed
        as one SPMD program over the mesh."""
        return self.run_epochs(iterator, n_epochs, self._shard_dataset)

    def run_epochs(self, iterator, n_epochs, shard_fn):
        """The one epoch/reset/listener loop, parameterized by how each
        batch is placed on the mesh (single-host shard vs multi-host
        global assembly — SharedTrainingMaster passes its own).

        Placement runs via DevicePrefetcher a batch ahead of the step
        loop (feeder-thread on accelerator backends), so the per-shard
        H2D DMA of batch n+1 overlaps the device step on batch n (the
        reference's prefetch workers; ``prefetch_buffer`` is the
        staging depth)."""
        if not self._placed:
            self._place_model()
        from deeplearning4j_tpu.datasets.prefetch import \
            maybe_device_prefetch
        n = self.n_workers
        shard_fn = self._timed_place(shard_fn, n)
        staged = maybe_device_prefetch(iterator, place_fn=shard_fn,
                                       depth=self.prefetch_buffer)
        if staged is not iterator:
            shard_fn = lambda ds: ds     # noqa: E731 — already placed
        for _ in range(n_epochs):
            if hasattr(staged, "reset"):
                staged.reset()
            for lis in self.model.listeners:
                lis.on_epoch_start(self.model)
            for ds in staged:
                ds = shard_fn(ds)
                if telemetry.enabled():
                    # the sharded step COMPILES the gradient all-reduce
                    # in (psum over the data axis) — this is the whole
                    # replica-sync step the reference's trainer threads
                    # + averaging round performed
                    t0 = time.perf_counter()
                    self.model.fit(ds)
                    telemetry.histogram(
                        "dl4j_dp_step_seconds",
                        "data-parallel sharded step wall time incl. "
                        "the fused in-step gradient all-reduce "
                        "(seconds)").observe(
                            time.perf_counter() - t0, workers=n)
                else:
                    self.model.fit(ds)
            self.model.epoch_count += 1
            for lis in self.model.listeners:
                lis.on_epoch_end(self.model)
        return self

    @staticmethod
    def _timed_place(shard_fn, workers: int):
        """Wrap a batch-placement fn so per-batch shard/assembly time
        (which runs on the prefetch feeder thread) is measured."""
        def place(ds):
            if not telemetry.enabled():
                return shard_fn(ds)
            with telemetry.span("dp.place", workers=workers):
                t0 = time.perf_counter()
                out = shard_fn(ds)
                telemetry.histogram(
                    "dl4j_dp_place_seconds",
                    "per-batch shard/global-assembly dispatch time on "
                    "the feeder thread (seconds)").observe(
                        time.perf_counter() - t0, workers=workers)
            return out
        return place

    def fit_batch(self, ds):
        if not self._placed:
            self._place_model()
        self.model.fit(self._shard_dataset(ds))
        return self

    def average_score(self) -> float:
        return self.model.score()

    def shutdown(self):
        """Reference API: stop trainer threads. Nothing to stop here."""
        self._placed = False
