"""Sequence/context parallelism (SURVEY.md §2.6 P9, §5.7).

The reference has NO sequence parallelism — long sequences are handled
only by truncated BPTT (SURVEY.md 5.7). This module is the TPU-native
extension that makes long-context first-class:

- :func:`blockwise_attention` — memory-efficient attention: online
  softmax over key/value blocks (`lax.scan`), O(t) activation memory
  instead of O(t^2); exact same function as dense softmax attention.
- :func:`flash_attention` — the same computation as a Pallas TPU
  kernel (tiled into VMEM, MXU matmuls, fp32 accumulators); backward
  pass recomputes via the blockwise form (flash-style recompute trades
  FLOPs for HBM, the standard TPU tradeoff).
- :func:`ring_attention` — context parallelism over a mesh ``seq``
  axis: Q/K/V sharded along time; K/V blocks rotate around the ring
  via ``lax.ppermute`` (ICI neighbor exchange) while each device
  accumulates online-softmax partials. Memory per chip: O(t/n_sp).
- :func:`ulysses_attention` — all-to-all sequence parallelism: swap
  the sharded axis from time to heads (``lax.all_to_all``), run local
  full-sequence attention on h/n heads, swap back.

All forms compute the identical function as dense attention (up to
float associativity), so tests compare against
:func:`deeplearning4j_tpu.ops.attention.dot_product_attention`.

Conventions: activations [batch, heads, time, head_dim]; causal masks
use *global* positions, so sharded forms mask correctly across shards.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


from .mesh import shard_map as _shard_map  # public seam, re-exported


# ---------------------------------------------------------------------------
# blockwise (online-softmax) attention — pure JAX, differentiable
# ---------------------------------------------------------------------------
def _block_update(carry, qb, kb, vb, mask_b, scale):
    """One online-softmax step: fold K/V block into (o, l, m)."""
    o, l, m = carry                      # o:[...,tq,d] l,m:[...,tq]
    s = jnp.einsum("...qd,...kd->...qk", qb, kb) * scale
    if mask_b is not None:
        s = jnp.where(mask_b, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # renormalize previous accumulator, fold in this block. exp() of
    # masked scores must be EXACTLY 0 (not exp(NEG_INF - NEG_INF) = 1)
    # so fully-masked rows accumulate l = 0 and finalize to zeros,
    # matching the dense reference's fully-masked-row semantics.
    corr = jnp.exp(m - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("...qk,...kd->...qd", p, vb)
    return (o_new, l_new, m_new)


def _finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def blockwise_attention(q, k, v, *, causal: bool = False,
                        block_k: int = 256,
                        q_offset=0, k_offset=0,
                        key_mask: Optional[jax.Array] = None):
    """Exact attention with O(t) memory via online softmax.

    q: [..., tq, d]; k/v: [..., tk, d]; key_mask: [..., tk] (0=masked).
    ``q_offset``/``k_offset`` are the global positions of element 0 —
    the hook ring attention uses for cross-shard causal masking.
    """
    tq, d = q.shape[-2], q.shape[-1]
    tk = k.shape[-2]
    scale = 1.0 / (d ** 0.5)
    block_k = min(block_k, tk)
    n_blocks = -(-tk // block_k)
    pad = n_blocks * block_k - tk
    if pad:
        kp = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
        km = jnp.pad(key_mask if key_mask is not None else
                     jnp.ones(k.shape[:-1], bool),
                     [(0, 0)] * (k.ndim - 2) + [(0, pad)])
    else:
        kp, vp, km = k, v, key_mask

    q_pos = q_offset + jnp.arange(tq)

    def scan_body(carry, i):
        s = i * block_k
        kb = lax.dynamic_slice_in_dim(kp, s, block_k, axis=-2)
        vb = lax.dynamic_slice_in_dim(vp, s, block_k, axis=-2)
        k_pos = k_offset + s + jnp.arange(block_k)
        mask_b = None
        if causal:
            mask_b = q_pos[:, None] >= k_pos[None, :]
        if km is not None:
            kmb = lax.dynamic_slice_in_dim(km, s, block_k, axis=-1)
            kmb = kmb[..., None, :]
            mask_b = kmb if mask_b is None else (mask_b & (kmb > 0))
        return _block_update(carry, q, kb, vb, mask_b, scale), None

    # carry derived from q so it inherits q's varying-manual-axes when
    # called inside shard_map (e.g. the Ulysses local attention)
    o0 = (q * 0).astype(jnp.promote_types(q.dtype, jnp.float32))
    l0 = o0[..., 0]
    m0 = l0 + NEG_INF
    (o, l, _), _ = lax.scan(scan_body, (o0, l0, m0),
                            jnp.arange(n_blocks))
    return _finalize(o, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel (TPU)
# ---------------------------------------------------------------------------
def _flash_kernel(q_ref, k_ref, v_ref, *rest, n_kb: int, causal: bool,
                  scale: float, has_mask: bool):
    """One (bh, iq, jk) grid cell: fold K/V block jk into the online-
    softmax accumulator for query block iq. Only [block, d] slabs are
    VMEM-resident — K/V stream through the grid (O(block) VMEM).
    Accumulators live in VMEM scratch, which persists across the
    innermost (jk) grid dimension; l/m are stored lane-replicated
    (block_q, 128) to respect the (8, 128) VPU tile. Optional key
    mask streams as a (1, block_k) slab per key block."""
    import jax.experimental.pallas as pl

    if has_mask:
        mask_ref, o_ref, o_acc, l_acc, m_acc = rest
    else:
        o_ref, o_acc, l_acc, m_acc = rest
        mask_ref = None
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        l_acc[:] = jnp.zeros_like(l_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)

    def _update():
        # operands stay bf16 — the MXU runs bf16×bf16→f32 natively at
        # 2x the f32 rate; accumulation is f32 via
        # preferred_element_type (casting inputs to f32 halves
        # matmul throughput for zero accuracy gain)
        q = q_ref[:]
        kb = k_ref[:]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if mask_ref is not None:
            s = jnp.where(mask_ref[:1, :] > 0, s, NEG_INF)
        m_prev = m_acc[:, :1]
        l_prev = l_acc[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_acc[:] = o_acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_acc[:] = m_new + jnp.zeros_like(m_acc)
        l_acc[:] = l_new + jnp.zeros_like(l_acc)

    if causal:
        # skip key blocks entirely in the masked future (~2x FLOPs)
        @pl.when((iq + 1) * block_q > jk * block_k)
        def _():
            _update()
    else:
        _update()

    @pl.when(jk == n_kb - 1)
    def _finalize_out():
        l = jnp.maximum(l_acc[:, :1], 1e-30)
        o_ref[:] = (o_acc[:] / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, key_mask, causal: bool, block_q: int,
                   block_k: int, interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = 1.0 / (d ** 0.5)

    def _fit(block, t):
        # largest divisor of t that is <= the requested block (halve
        # until it divides): a 1536-long sequence runs with 512-blocks
        # rather than erroring on the 1024 default
        block = min(block, t)
        while t % block:
            block //= 2
        return max(block, 1)

    block_q = _fit(block_q, tq)
    block_k = _fit(block_k, tk)
    n_kb = tk // block_k
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    has_mask = key_mask is not None

    kernel = functools.partial(_flash_kernel, n_kb=n_kb, causal=causal,
                               scale=scale, has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((None, block_q, d),
                     lambda bh, iq, jk: (bh, iq, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda bh, iq, jk: (bh, jk, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda bh, iq, jk: (bh, jk, 0)),
    ]
    inputs = [qr, kr, vr]
    if has_mask:
        # [b, tk] key mask broadcast to (b*h, 1, tk): a (1, block_k)
        # VMEM slab per key block (sublane dim 1 == full array dim, the
        # only sub-8 block shape Mosaic accepts); XLA materializes the
        # broadcast lazily so HBM cost stays ~b*tk
        km = jnp.broadcast_to(
            key_mask.astype(jnp.float32)[:, None, None, :],
            (b, h, 1, tk)).reshape(b * h, 1, tk)
        inputs.append(km)
        in_specs.append(pl.BlockSpec((None, 1, block_k),
                                     lambda bh, iq, jk: (bh, 0, jk)))
    out = pl.pallas_call(
        kernel,
        grid=(b * h, tq // block_q, n_kb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, iq, jk: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out.reshape(b, h, tq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 1024,
                    block_k: int = 1024,
                    interpret: Optional[bool] = None, key_mask=None):
    """Fused attention kernel, [b, h, t, d]. Equals dense softmax
    attention; O(block) VMEM. ``key_mask``: [b, tk], 0 = masked.
    Backward = flash-style recompute through
    :func:`blockwise_attention` (jax.grad-differentiable).

    Default 1024x1024 blocks measured 4.2x faster than 128x256 at seq
    8192 on v5e (fewer grid steps amortize the per-block overhead; the
    f32 score block is 4 MB of VMEM) — BENCH_notes_r03.md. Blocks
    clamp to the sequence length, so short sequences still work;
    below ~4k prefer plain XLA attention, which wins outright there."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, key_mask, causal, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret,
               key_mask=None):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret,
                          key_mask)
    return out, (q, k, v, key_mask)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, key_mask = res
    km = None if key_mask is None else key_mask[:, None, :]
    _, vjp = jax.vjp(
        lambda q, k, v: blockwise_attention(q, k, v, causal=causal,
                                            block_k=block_k,
                                            key_mask=km), q, k, v)
    return vjp(g) + (None,)      # no cotangent for the mask


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# ring attention — context parallelism over a mesh axis
# ---------------------------------------------------------------------------
def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   block_k: int = 256):
    """Attention with Q/K/V sharded along time over ``axis_name``.

    Call INSIDE ``shard_map``: q/k/v are the local shards
    [b, h, t_local, d]. K/V shards rotate around the ring with
    ``lax.ppermute`` (neighbor ICI hop per step) while each device
    folds the visiting block into its online-softmax accumulator —
    t_local^2 compute per step, O(t_local) memory, n_sp steps.
    Causal masking uses global positions so the result equals dense
    causal attention on the gathered sequence.
    """
    n_sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    q_pos = my * t_local + jnp.arange(t_local)

    # derive the carry from q so it carries q's varying-manual-axes
    # (jax>=0.8 shard_map type-checks vma through scan carries)
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    o0 = (q * 0).astype(acc_dt)
    l0 = o0[..., 0]
    m0 = l0 + NEG_INF

    def step(carry, s):
        (o, l, m), (kb, vb) = carry
        src = (my - s) % n_sp              # who produced this block
        mask = None
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
        acc = _block_update((o, l, m), q, kb, vb, mask, scale)
        # rotate: send our current block to the next device in the ring
        perm = [(i, (i + 1) % n_sp) for i in range(n_sp)]
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (acc, (kb, vb)), None

    (acc, _), _ = lax.scan(step, ((o0, l0, m0), (k, v)),
                           jnp.arange(n_sp))
    o, l, _ = acc
    return _finalize(o, l).astype(q.dtype)


def _seq_sharded_call(local_fn, mesh, q, k, v, seq_axis, causal):
    """Common shard_map plumbing: q/k/v are GLOBAL [b, h, t, d] arrays;
    time sharded over ``seq_axis``, batch over ``data`` when present."""
    from jax.sharding import PartitionSpec as P

    data = "data" if "data" in mesh.axis_names else None
    spec = P(data, None, seq_axis, None)
    fn = _shard_map(
        functools.partial(local_fn, axis_name=seq_axis, causal=causal),
        mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ring_self_attention(mesh, q, k, v, *, seq_axis: str = "seq",
                        causal: bool = False):
    return _seq_sharded_call(ring_attention, mesh, q, k, v, seq_axis,
                             causal)


# ---------------------------------------------------------------------------
# Ulysses — all-to-all sequence parallelism
# ---------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      block_k: int = 256):
    """DeepSpeed-Ulysses-style SP. Call INSIDE shard_map with
    [b, h, t_local, d] shards, h divisible by the axis size: all-to-all
    re-shards time->heads, local attention sees the FULL sequence for
    h/n heads, then all-to-all back. Two collectives total; better
    ICI utilisation than a ring when h >= n_sp."""
    # [b, h, t/n, d] -> [b, h/n, t, d]
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    o = blockwise_attention(qh, kh, vh, causal=causal, block_k=block_k)
    # [b, h/n, t, d] -> [b, h, t/n, d]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_self_attention(mesh, q, k, v, *, seq_axis: str = "seq",
                           causal: bool = False):
    return _seq_sharded_call(ulysses_attention, mesh, q, k, v, seq_axis,
                             causal)
