"""Sequence/context parallelism (SURVEY.md §2.6 P9, §5.7).

The reference has NO sequence parallelism — long sequences are handled
only by truncated BPTT (SURVEY.md 5.7). This module is the TPU-native
extension that makes long-context first-class:

- :func:`blockwise_attention` — memory-efficient attention: online
  softmax over key/value blocks (`lax.scan`), O(t) activation memory
  instead of O(t^2); exact same function as dense softmax attention.
- :func:`flash_attention` — the same computation as a Pallas TPU
  kernel (tiled into VMEM, MXU matmuls, fp32 accumulators); backward
  is a pair of Pallas dq / dk+dv kernels recomputing probabilities
  from the saved log-sum-exp (flash-style recompute trades FLOPs for
  HBM, the standard TPU tradeoff).
- :func:`ring_attention` — context parallelism over a mesh ``seq``
  axis: Q/K/V sharded along time; K/V blocks rotate around the ring
  via ``lax.ppermute`` (ICI neighbor exchange) while each device
  accumulates online-softmax partials. Memory per chip: O(t/n_sp).
- :func:`ulysses_attention` — all-to-all sequence parallelism: swap
  the sharded axis from time to heads (``lax.all_to_all``), run local
  full-sequence attention on h/n heads, swap back.

All forms compute the identical function as dense attention (up to
float associativity), so tests compare against
:func:`deeplearning4j_tpu.ops.attention.dot_product_attention`.

Conventions: activations [batch, heads, time, head_dim]; causal masks
use *global* positions, so sharded forms mask correctly across shards.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


from .mesh import shard_map as _shard_map  # public seam, re-exported


# ---------------------------------------------------------------------------
# blockwise (online-softmax) attention — pure JAX, differentiable
# ---------------------------------------------------------------------------
def _block_update(carry, qb, kb, vb, mask_b, scale):
    """One online-softmax step: fold K/V block into (o, l, m)."""
    o, l, m = carry                      # o:[...,tq,d] l,m:[...,tq]
    s = jnp.einsum("...qd,...kd->...qk", qb, kb) * scale
    if mask_b is not None:
        s = jnp.where(mask_b, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # renormalize previous accumulator, fold in this block. exp() of
    # masked scores must be EXACTLY 0 (not exp(NEG_INF - NEG_INF) = 1)
    # so fully-masked rows accumulate l = 0 and finalize to zeros,
    # matching the dense reference's fully-masked-row semantics.
    corr = jnp.exp(m - m_new)
    p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new[..., None]))
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum("...qk,...kd->...qd", p, vb)
    return (o_new, l_new, m_new)


def _finalize(o, l):
    return o / jnp.maximum(l, 1e-30)[..., None]


def blockwise_attention(q, k, v, *, causal: bool = False,
                        block_k: int = 256,
                        q_offset=0, k_offset=0,
                        key_mask: Optional[jax.Array] = None):
    """Exact attention with O(t) memory via online softmax.

    q: [..., tq, d]; k/v: [..., tk, d]; key_mask: [..., tk] (0=masked).
    ``q_offset``/``k_offset`` are the global positions of element 0 —
    the hook ring attention uses for cross-shard causal masking.
    """
    tq, d = q.shape[-2], q.shape[-1]
    tk = k.shape[-2]
    scale = 1.0 / (d ** 0.5)
    block_k = min(block_k, tk)
    n_blocks = -(-tk // block_k)
    pad = n_blocks * block_k - tk
    if pad:
        kp = jnp.pad(k, [(0, 0)] * (k.ndim - 2) + [(0, pad), (0, 0)])
        vp = jnp.pad(v, [(0, 0)] * (v.ndim - 2) + [(0, pad), (0, 0)])
        km = jnp.pad(key_mask if key_mask is not None else
                     jnp.ones(k.shape[:-1], bool),
                     [(0, 0)] * (k.ndim - 2) + [(0, pad)])
    else:
        kp, vp, km = k, v, key_mask

    q_pos = q_offset + jnp.arange(tq)

    def scan_body(carry, i):
        s = i * block_k
        kb = lax.dynamic_slice_in_dim(kp, s, block_k, axis=-2)
        vb = lax.dynamic_slice_in_dim(vp, s, block_k, axis=-2)
        k_pos = k_offset + s + jnp.arange(block_k)
        mask_b = None
        if causal:
            mask_b = q_pos[:, None] >= k_pos[None, :]
        if km is not None:
            kmb = lax.dynamic_slice_in_dim(km, s, block_k, axis=-1)
            kmb = kmb[..., None, :]
            mask_b = kmb if mask_b is None else (mask_b & (kmb > 0))
        return _block_update(carry, q, kb, vb, mask_b, scale), None

    # carry derived from q so it inherits q's varying-manual-axes when
    # called inside shard_map (e.g. the Ulysses local attention)
    o0 = (q * 0).astype(jnp.promote_types(q.dtype, jnp.float32))
    l0 = o0[..., 0]
    m0 = l0 + NEG_INF
    (o, l, _), _ = lax.scan(scan_body, (o0, l0, m0),
                            jnp.arange(n_blocks))
    return _finalize(o, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention kernel (TPU)
# ---------------------------------------------------------------------------
def _masked_scores(q_ref, k_ref, mask_ref, iq, jk, causal: bool,
                   scale: float):
    """The score block shared by forward and both backward kernels:
    q @ k^T * scale with the causal iota mask and the key mask
    applied as NEG_INF — ONE definition, so the masked-score
    semantics (incl. the exact-zero invariant downstream) can never
    desynchronize between passes."""
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    s = jax.lax.dot_general(q_ref[:], k_ref[:],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    if mask_ref is not None:
        s = jnp.where(mask_ref[:1, :] > 0, s, NEG_INF)
    return s


#: lane-replication width for the lse/delta residuals ((block_q, REP)
#: slabs whose lane dim equals the full array dim — the same sub-128
#: shape rule the key-mask slab uses on its sublane)
_RESID_REP = 8


def _sds_like(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-manual-axes, so
    pallas_call outputs type-check under shard_map (ring attention
    runs the kernels inside the ``seq`` manual axis)."""
    vma = getattr(jax.core.get_aval(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_kernel(q_ref, k_ref, v_ref, *rest, n_kb: int, causal: bool,
                  scale: float, has_mask: bool,
                  want_lse: bool = False):
    """One (bh, iq, jk) grid cell: fold K/V block jk into the online-
    softmax accumulator for query block iq. Only [block, d] slabs are
    VMEM-resident — K/V stream through the grid (O(block) VMEM).
    Accumulators live in VMEM scratch, which persists across the
    innermost (jk) grid dimension; l/m are stored lane-replicated
    (block_q, 128) to respect the (8, 128) VPU tile. Optional key
    mask streams as a (1, block_k) slab per key block."""
    import jax.experimental.pallas as pl

    lse_ref = None
    if has_mask and want_lse:
        mask_ref, o_ref, lse_ref, o_acc, l_acc, m_acc = rest
    elif has_mask:
        mask_ref, o_ref, o_acc, l_acc, m_acc = rest
    elif want_lse:
        o_ref, lse_ref, o_acc, l_acc, m_acc = rest
        mask_ref = None
    else:
        o_ref, o_acc, l_acc, m_acc = rest
        mask_ref = None
    block_q, d = q_ref.shape
    block_k = k_ref.shape[0]
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        o_acc[:] = jnp.zeros_like(o_acc)
        l_acc[:] = jnp.zeros_like(l_acc)
        m_acc[:] = jnp.full_like(m_acc, NEG_INF)

    def _update():
        # operands stay bf16 — the MXU runs bf16×bf16→f32 natively at
        # 2x the f32 rate; accumulation is f32 via
        # preferred_element_type (casting inputs to f32 halves
        # matmul throughput for zero accuracy gain)
        s = _masked_scores(q_ref, k_ref, mask_ref, iq, jk, causal,
                           scale)
        m_prev = m_acc[:, :1]
        l_prev = l_acc[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        o_acc[:] = o_acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_acc[:] = m_new + jnp.zeros_like(m_acc)
        l_acc[:] = l_new + jnp.zeros_like(l_acc)

    if causal:
        # skip key blocks entirely in the masked future (~2x FLOPs)
        @pl.when((iq + 1) * block_q > jk * block_k)
        def _():
            _update()
    else:
        _update()

    @pl.when(jk == n_kb - 1)
    def _finalize_out():
        l = jnp.maximum(l_acc[:, :1], 1e-30)
        o_ref[:] = (o_acc[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # per-row log-sum-exp of the SCALED scores, the flash
            # backward's softmax residual; replicated only _RESID_REP
            # lanes wide (128-wide residuals held fwd->bwd cost 128x
            # the HBM of the data present)
            lse_ref[:] = (m_acc[:, :_RESID_REP]
                          + jnp.log(jnp.maximum(
                              l_acc[:, :_RESID_REP], 1e-30)))


def _fit_block(block, t):
    # largest divisor of t that is <= the requested block (halve
    # until it divides): a 1536-long sequence runs with 512-blocks
    # rather than erroring on the 1024 default
    block = min(block, t)
    while t % block:
        block //= 2
    return max(block, 1)


def _flash_forward(q, k, v, key_mask, causal: bool, block_q: int,
                   block_k: int, interpret: bool,
                   want_lse: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = 1.0 / (d ** 0.5)

    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    n_kb = tk // block_k
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    has_mask = key_mask is not None

    kernel = functools.partial(_flash_kernel, n_kb=n_kb, causal=causal,
                               scale=scale, has_mask=has_mask,
                               want_lse=want_lse)
    in_specs = [
        pl.BlockSpec((None, block_q, d),
                     lambda bh, iq, jk: (bh, iq, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda bh, iq, jk: (bh, jk, 0)),
        pl.BlockSpec((None, block_k, d),
                     lambda bh, iq, jk: (bh, jk, 0)),
    ]
    inputs = [qr, kr, vr]
    if has_mask:
        # [b, tk] key mask broadcast to (b*h, 1, tk): a (1, block_k)
        # VMEM slab per key block (sublane dim 1 == full array dim, the
        # only sub-8 block shape Mosaic accepts); XLA materializes the
        # broadcast lazily so HBM cost stays ~b*tk
        km = jnp.broadcast_to(
            key_mask.astype(jnp.float32)[:, None, None, :],
            (b, h, 1, tk)).reshape(b * h, 1, tk)
        inputs.append(km)
        in_specs.append(pl.BlockSpec((None, 1, block_k),
                                     lambda bh, iq, jk: (bh, 0, jk)))
    out_specs = pl.BlockSpec((None, block_q, d),
                             lambda bh, iq, jk: (bh, iq, 0))
    out_shape = _sds_like((b * h, tq, d), q.dtype, qr)
    if want_lse:
        out_specs = [out_specs,
                     pl.BlockSpec((None, block_q, _RESID_REP),
                                  lambda bh, iq, jk: (bh, iq, 0))]
        out_shape = [out_shape,
                     _sds_like((b * h, tq, _RESID_REP), jnp.float32,
                               qr)]
    res = pl.pallas_call(
        kernel,
        grid=(b * h, tq // block_q, n_kb),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    if want_lse:
        out, lse = res
        return out.reshape(b, h, tq, d), lse
    return res.reshape(b, h, tq, d)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, *rest, n_kb: int, causal: bool,
                         scale: float, has_mask: bool):
    """dq for one (bh, iq, jk) grid cell: recompute the probability
    block from the saved log-sum-exp (the flash residual), form
    ds = p * (do.v^T - delta), accumulate dq += ds @ k * scale.  Only
    [block, d] slabs + one (block_q, block_k) f32 score block are
    VMEM-resident."""
    import jax.experimental.pallas as pl

    if has_mask:
        mask_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        mask_ref = None
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _update():
        s = _masked_scores(q_ref, k_ref, mask_ref, iq, jk, causal,
                           scale)
        p = jnp.where(s <= NEG_INF / 2, 0.0,
                      jnp.exp(s - lse_ref[:, :1]))
        dp = jax.lax.dot_general(
            do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[:, :1])
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[:],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when((iq + 1) * block_q > jk * block_k)
        def _():
            _update()
    else:
        _update()

    @pl.when(jk == n_kb - 1)
    def _finalize():
        dq_ref[:] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                          delta_ref, *rest, n_qb: int, causal: bool,
                          scale: float, has_mask: bool):
    """dk/dv for one (bh, jk, iq) grid cell (q blocks innermost so
    the [block_k, d] accumulators persist per key block):
    dv += p^T @ do,  dk += ds^T @ q * scale."""
    import jax.experimental.pallas as pl

    if has_mask:
        mask_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        mask_ref = None
    block_q = q_ref.shape[0]
    block_k = k_ref.shape[0]
    jk = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _update():
        s = _masked_scores(q_ref, k_ref, mask_ref, iq, jk, causal,
                           scale)
        p = jnp.where(s <= NEG_INF / 2, 0.0,
                      jnp.exp(s - lse_ref[:, :1]))
        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[:],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[:], v_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[:, :1])
        # dk += ds^T @ q * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[:],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when((iq + 1) * block_q > jk * block_k)
        def _():
            _update()
    else:
        _update()

    @pl.when(iq == n_qb - 1)
    def _finalize():
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, key_mask, out, lse, g, causal: bool,
                    block_q: int, block_k: int, interpret: bool,
                    g_lse=None):
    """Pallas flash backward: dq via a (bh, iq, jk) sweep, dk/dv via a
    (bh, jk, iq) sweep, probabilities recomputed from the saved
    log-sum-exp.  Replaces the r3 jax.vjp-through-blockwise backward,
    whose differentiated lax.scan both lost 2.4x to XLA dense at seq
    8k AND failed to compile beyond [4, 8, 8192, 128] on the v5e
    compile helper (BENCH_notes_r04.md)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_q = _fit_block(block_q, tq)
    block_k = _fit_block(block_k, tk)
    n_qb, n_kb = tq // block_q, tk // block_k
    qr = q.reshape(b * h, tq, d)
    kr = k.reshape(b * h, tk, d)
    vr = v.reshape(b * h, tk, d)
    gr = g.reshape(b * h, tq, d)
    # delta_i = sum_d dO_i . O_i — the softmax-jacobian row term;
    # cheap elementwise+reduce, lane-replicated like lse.  An lse
    # cotangent folds in EXACTLY here: d lse_i / d s_ij = p_ij, so
    # ds = p*(dp - delta + g_lse) — i.e. delta' = delta - g_lse
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(b * h, tq)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32).reshape(b * h, tq)
    delta = jnp.broadcast_to(delta[:, :, None],
                             (b * h, tq, _RESID_REP))
    has_mask = key_mask is not None

    qkv_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, iq, jk: (bh, jk, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, iq, jk: (bh, jk, 0)),
        pl.BlockSpec((None, block_q, d), lambda bh, iq, jk: (bh, iq, 0)),
        pl.BlockSpec((None, block_q, _RESID_REP),
                     lambda bh, iq, jk: (bh, iq, 0)),
        pl.BlockSpec((None, block_q, _RESID_REP),
                     lambda bh, iq, jk: (bh, iq, 0)),
    ]
    inputs = [qr, kr, vr, gr, lse, delta]
    if has_mask:
        km = jnp.broadcast_to(
            key_mask.astype(jnp.float32)[:, None, None, :],
            (b, h, 1, tk)).reshape(b * h, 1, tk)
        inputs.append(km)
        qkv_specs.append(pl.BlockSpec((None, 1, block_k),
                                      lambda bh, iq, jk: (bh, 0, jk)))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_kb=n_kb,
                          causal=causal, scale=scale,
                          has_mask=has_mask),
        grid=(b * h, n_qb, n_kb),
        in_specs=qkv_specs,
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda bh, iq, jk: (bh, iq, 0)),
        out_shape=_sds_like((b * h, tq, d), q.dtype, qr),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    # same inputs, (bh, jk, iq) grid — index maps swap the roles
    kv_specs = [
        pl.BlockSpec((None, block_q, d), lambda bh, jk, iq: (bh, iq, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, jk, iq: (bh, jk, 0)),
        pl.BlockSpec((None, block_k, d), lambda bh, jk, iq: (bh, jk, 0)),
        pl.BlockSpec((None, block_q, d), lambda bh, jk, iq: (bh, iq, 0)),
        pl.BlockSpec((None, block_q, _RESID_REP),
                     lambda bh, jk, iq: (bh, iq, 0)),
        pl.BlockSpec((None, block_q, _RESID_REP),
                     lambda bh, jk, iq: (bh, iq, 0)),
    ]
    if has_mask:
        kv_specs.append(pl.BlockSpec((None, 1, block_k),
                                     lambda bh, jk, iq: (bh, 0, jk)))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, n_qb=n_qb,
                          causal=causal, scale=scale,
                          has_mask=has_mask),
        grid=(b * h, n_kb, n_qb),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, d),
                         lambda bh, jk, iq: (bh, jk, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda bh, jk, iq: (bh, jk, 0)),
        ],
        out_shape=[
            _sds_like((b * h, tk, d), k.dtype, kr),
            _sds_like((b * h, tk, d), v.dtype, vr),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    return (dq.reshape(b, h, tq, d), dk.reshape(b, h, tk, d),
            dv.reshape(b, h, tk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 1024,
                    block_k: int = 1024,
                    interpret: Optional[bool] = None, key_mask=None):
    """Fused attention kernel, [b, h, t, d]. Equals dense softmax
    attention; O(block) VMEM. ``key_mask``: [b, tk], 0 = masked.
    Backward = Pallas dq/dk/dv kernels recomputing probabilities from
    the saved log-sum-exp (r4; the r3 jax.vjp-through-blockwise
    backward lost 2.4x to XLA dense at seq 8k and failed to compile
    beyond [4, 8, 8192, 128] — BENCH_notes_r04.md). Measured train
    step (fwd+bwd, v5e): 1.55-1.6x FASTER than XLA dense at seq
    8k-16k, and runs at 32k where dense attention cannot materialize
    the score matrix at all.

    Default 1024x1024 forward blocks measured 4.2x faster than
    128x256 at seq 8192 on v5e (fewer grid steps amortize the
    per-block overhead; the f32 score block is 4 MB of VMEM) —
    BENCH_notes_r03.md; the backward caps blocks at 512 (it keeps
    score + dp + ds f32 blocks live). Blocks clamp to the sequence
    length, so short sequences still work; below ~4k prefer plain
    XLA attention, which wins outright there."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, key_mask, causal, block_q, block_k,
                          interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret,
               key_mask=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, key_mask, causal, block_q,
                              block_k, interpret, want_lse=True)
    return out, (q, k, v, key_mask, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, key_mask, out, lse = res
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # backward blocks default to 512: the bwd keeps an extra f32
    # score block + dp/ds live, so the fwd's 1024x1024 tuning would
    # overflow VMEM
    dq, dk, dv = _flash_backward(
        q, k, v, key_mask, out, lse, g, causal,
        min(block_q, 512), min(block_k, 512), interpret)
    return dq, dk, dv, None      # no cotangent for the mask


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal: bool = False,
                             block_q: int = 1024, block_k: int = 1024,
                             interpret: Optional[bool] = None,
                             key_mask=None):
    """:func:`flash_attention` that ALSO returns the per-row
    log-sum-exp of the scaled scores, [b, h, t] f32 — the residual
    that lets partial attentions over different key sets be merged
    exactly (ring attention's per-step form).  Differentiable in the
    lse output too: its cotangent folds into the backward's delta
    term (d lse/d s = p)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, key_mask, causal, block_q,
                              block_k, interpret, want_lse=True)
    b, h, tq, _ = q.shape
    return out, lse[:, :, 0].reshape(b, h, tq)


def _flash_lse_fwd(q, k, v, causal, block_q, block_k, interpret,
                   key_mask=None):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, key_mask, causal, block_q,
                              block_k, interpret, want_lse=True)
    b, h, tq, _ = q.shape
    return ((out, lse[:, :, 0].reshape(b, h, tq)),
            (q, k, v, key_mask, out, lse))


def _flash_lse_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, key_mask, out, lse = res
    g_out, g_lse = g
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    dq, dk, dv = _flash_backward(
        q, k, v, key_mask, out, lse, g_out, causal,
        min(block_q, 512), min(block_k, 512), interpret,
        g_lse=g_lse)
    return dq, dk, dv, None


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# ring attention — context parallelism over a mesh axis
# ---------------------------------------------------------------------------
def _ref_attention_with_lse(q, k, v, causal: bool, scale: float):
    """Dense attention returning (out, lse) — the non-kernel twin of
    :func:`flash_attention_with_lse` for backends without Mosaic."""
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        i = jnp.arange(t_q)[:, None]
        j = jnp.arange(t_k)[None, :]
        s = jnp.where(i >= j, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.where(s <= NEG_INF / 2, 0.0,
                  jnp.exp(s - lse[..., None]))
    return jnp.einsum("...qk,...kd->...qd", p, v), lse


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   block_k: int = 256, use_flash: bool = False,
                   flash_block_q: int = 1024,
                   flash_block_k: int = 1024):
    """Attention with Q/K/V sharded along time over ``axis_name``.

    Call INSIDE ``shard_map``: q/k/v are the local shards
    [b, h, t_local, d]. K/V shards rotate around the ring with
    ``lax.ppermute`` (neighbor ICI hop per step) while each device
    folds the visiting block into its accumulator — t_local^2 compute
    per step, O(t_local) memory, n_sp steps.  Causal masking uses
    global positions so the result equals dense causal attention on
    the gathered sequence.

    ``use_flash=True`` (r4): each ring step runs the Pallas
    :func:`flash_attention_with_lse` kernel on the visiting shard and
    the normalized partials are merged EXACTLY via their
    log-sum-exps; the causal diagonal decomposes per the standard
    ring recipe (earlier shards fully visible, own shard locally
    causal, later shards skipped).  Backward rides the Pallas dq/dkv
    kernels per step through the scan.  Needs [b, h, t, d] inputs
    (the kernel's layout); the default path accepts any [..., t, d].
    """
    n_sp = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    scale = 1.0 / (q.shape[-1] ** 0.5)
    q_pos = my * t_local + jnp.arange(t_local)

    # derive the carry from q so it carries q's varying-manual-axes
    # (jax>=0.8 shard_map type-checks vma through scan carries)
    acc_dt = jnp.promote_types(q.dtype, jnp.float32)
    o0 = (q * 0).astype(acc_dt)
    l0 = o0[..., 0]
    m0 = l0 + NEG_INF
    perm = None  # built per step below

    def rotate(kb, vb):
        p = [(i, (i + 1) % n_sp) for i in range(n_sp)]
        return (lax.ppermute(kb, axis_name, p),
                lax.ppermute(vb, axis_name, p))

    if use_flash:
        on_tpu = jax.default_backend() == "tpu"

        def partial_fn(causal_local):
            def f(q, kb, vb):
                if on_tpu:
                    o_s, lse_s = flash_attention_with_lse(
                        q, kb, vb, causal_local, flash_block_q,
                        flash_block_k, None)
                else:
                    # interpret-mode pallas does not propagate
                    # varying-manual-axes through the kernel body, so
                    # the CPU mesh runs the exact dense-with-lse
                    # reference (the MERGE algebra — the part ring
                    # adds — is still fully exercised; the kernels
                    # themselves are interpret-tested standalone)
                    o_s, lse_s = _ref_attention_with_lse(
                        q, kb, vb, causal_local, scale)
                return o_s.astype(acc_dt), lse_s
            return f

        def skip_fn(q, kb, vb):
            # derive from q so the outputs carry q's varying-manual-
            # axes (lax.switch requires matching branch types)
            return ((q * 0).astype(acc_dt),
                    (q[..., 0] * 0 + NEG_INF).astype(jnp.float32))

        def step(carry, s):
            (o, l, m), (kb, vb) = carry
            src = (my - s) % n_sp          # who produced this block
            if causal:
                # ring-causal decomposition: src < my fully visible,
                # src == my locally causal, src > my fully masked
                idx = jnp.where(src == my, 1,
                                jnp.where(src < my, 0, 2))
                o_s, lse_s = lax.switch(
                    idx, (partial_fn(False), partial_fn(True),
                          skip_fn), q, kb, vb)
            else:
                o_s, lse_s = partial_fn(False)(q, kb, vb)
            # exact merge of normalized partials via log-sum-exps;
            # fully-masked rows (lse == -inf) contribute zero weight
            m_new = jnp.maximum(m, lse_s)
            c_old = jnp.where(m <= NEG_INF / 2, 0.0,
                              jnp.exp(m - m_new))
            c_new = jnp.where(lse_s <= NEG_INF / 2, 0.0,
                              jnp.exp(lse_s - m_new))
            o = o * c_old[..., None] + o_s * c_new[..., None]
            l = l * c_old + c_new
            return ((o, l, m_new), rotate(kb, vb)), None
    else:
        def step(carry, s):
            (o, l, m), (kb, vb) = carry
            src = (my - s) % n_sp          # who produced this block
            mask = None
            if causal:
                k_pos = src * t_local + jnp.arange(t_local)
                mask = q_pos[:, None] >= k_pos[None, :]
            acc = _block_update((o, l, m), q, kb, vb, mask, scale)
            return (acc, rotate(kb, vb)), None

    (acc, _), _ = lax.scan(step, ((o0, l0, m0), (k, v)),
                           jnp.arange(n_sp))
    o, l, _ = acc
    return _finalize(o, l).astype(q.dtype)


def _seq_sharded_call(local_fn, mesh, q, k, v, seq_axis, causal,
                      **kw):
    """Common shard_map plumbing: q/k/v are GLOBAL [b, h, t, d] arrays;
    time sharded over ``seq_axis``, batch over ``data`` when present."""
    from jax.sharding import PartitionSpec as P

    data = "data" if "data" in mesh.axis_names else None
    spec = P(data, None, seq_axis, None)
    # check_rep=False: the causal ring's lax.switch (fully-visible /
    # locally-causal / skipped branches) makes jax's static
    # replication checker raise "branches of cond produced mismatched
    # replication types" (jax suggests exactly this workaround).  It
    # is safe here: every input and output is seq-sharded — nothing
    # is claimed replicated, so no transpose psum depends on the
    # check — and test_sequence_parallel pins the gradients against
    # dense attention.
    fn = _shard_map(
        functools.partial(local_fn, axis_name=seq_axis, causal=causal,
                          **kw),
        mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)


def ring_self_attention(mesh, q, k, v, *, seq_axis: str = "seq",
                        causal: bool = False, use_flash: bool = False):
    return _seq_sharded_call(ring_attention, mesh, q, k, v, seq_axis,
                             causal, use_flash=use_flash)


# ---------------------------------------------------------------------------
# Ulysses — all-to-all sequence parallelism
# ---------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      block_k: int = 256, use_flash: bool = False):
    """DeepSpeed-Ulysses-style SP. Call INSIDE shard_map with
    [b, h, t_local, d] shards, h divisible by the axis size: all-to-all
    re-shards time->heads, local attention sees the FULL sequence for
    h/n heads, then all-to-all back. Two collectives total; better
    ICI utilisation than a ring when h >= n_sp.

    ``use_flash=True`` (r4): the local full-sequence attention runs
    the Pallas flash kernels (fwd + the dq/dkv backward) on TPU; CPU
    backends keep the blockwise form (interpret-mode pallas cannot
    propagate varying-manual-axes under shard_map)."""
    # [b, h, t/n, d] -> [b, h/n, t, d]
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    if use_flash and jax.default_backend() == "tpu":
        o = flash_attention(qh, kh, vh, causal)
    else:
        o = blockwise_attention(qh, kh, vh, causal=causal,
                                block_k=block_k)
    # [b, h/n, t, d] -> [b, h, t/n, d]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_self_attention(mesh, q, k, v, *, seq_axis: str = "seq",
                           causal: bool = False,
                           use_flash: bool = False):
    return _seq_sharded_call(ulysses_attention, mesh, q, k, v, seq_axis,
                             causal, use_flash=use_flash)
