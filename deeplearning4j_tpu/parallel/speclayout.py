"""SpecLayout — GSPMD tensor-parallel parameter partitioner.

Promotes the megatron-style splits from the :mod:`.tensor` dryrun
(manual shard_map + psum) into first-class ``PartitionSpec`` inference
over real model param trees, entry by entry (MLN ``layer_i`` / graph
vertex / SameDiff variable scope). The spec vocabulary is exactly
:func:`parallel.tensor.megatron_specs` — column ``P(None, model)``,
row ``P(model, None)``, sharded bias ``P(model)``, replicated ``P()``
— generalized by shape inference instead of hand-written per-key maps:

- a 2-D weight is **column**-sharded over ``model`` when its output
  dim divides the tp degree (embeddings, qkv, ffn-in), **row**-sharded
  when only the input dim does (ffn-out, attention out-proj), and left
  replicated otherwise;
- a 1-D bias is sharded ``P(model)`` when it pairs with a
  column-sharded weight in the same entry (same output width); biases
  of row-sharded weights and norm gains/offsets stay replicated.

Lowering happens through ``with_sharding_constraint`` pins inside the
jitted step (``parallel.zero.pin_tp_entry`` / ``tp_gather_leaf``), so
XLA's SPMD partitioner inserts the collectives — no hand-written psums.
Every spec keeps the leaf's FULL logical shape; sharding is purely
physical placement, which is why the dense/ZeRO-1 update math is
untouched by tp.

Under the ZeRO layouts the **resident** spec additionally shards one
free dimension over the ``data`` axis (the fsdp×tp scheme of
SNIPPETS.md [2]: embeddings/qkv/ffn sharded over ``fsdp×tp``); the
**compute** spec is the resident spec minus ``data``. The asymmetric
pin pair (gather to compute in forward, pin cotangent to resident in
backward — ``zero.tp_gather_leaf``) keeps params + grads + updater
state resident at ``1/(dp·tp)`` while dp collectives never cross the
``model`` axis.

Layout-axis ownership (the PR-12 cross-link convention): this module
owns the ``model``-axis parameter specs (and the fsdp ``data``
residency dimension). ``parallel/tensor.py`` owns the column/row
sharded matmul math those specs lower to. ``parallel/pipeline.py``
owns the ``pipe`` axis — a *stage* partition of whole entries, not a
within-leaf sharding, so the two compose by restriction:
:meth:`SpecLayout.infer_stages` runs the same inference per stage
against the stage's ``(data, model)`` submesh, and the specs for each
entry are identical to the 2D run's (the pipe axis never appears in a
``PartitionSpec``).
"""
from __future__ import annotations

from typing import Dict, NamedTuple

import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              DEFAULT_MODEL_AXIS)


class TpLeafSpec(NamedTuple):
    """Compute vs resident PartitionSpec for one tensor-parallel leaf.

    ``compute``: how the forward/backward math sees the leaf (model
    axis only). ``resident``: how the leaf lives between steps — equal
    to ``compute`` for the dense tail, plus a ``data``-axis dimension
    under the ZeRO tails (sharded/fsdp)."""
    compute: P
    resident: P


def _is_flat_array_dict(entry) -> bool:
    return (isinstance(entry, dict) and bool(entry) and
            all(hasattr(a, "shape") and hasattr(a, "ndim")
                for a in entry.values()))


class SpecLayout:
    """Per-entry tp spec inference for a ``{entry: {name: array}}``
    param tree (or a single flat ``{name: array}`` dict via
    :meth:`infer_entry`)."""

    def __init__(self, mesh, model_axis: str = DEFAULT_MODEL_AXIS,
                 data_axis: str = DEFAULT_DATA_AXIS,
                 stage_axis: str = "pipe"):
        self.mesh = mesh
        self.model_axis = model_axis
        self.data_axis = data_axis
        self.stage_axis = stage_axis
        self.tp = int(mesh.shape.get(model_axis, 1))
        self.dp = int(mesh.shape.get(data_axis, 1))
        #: pipeline-stage degree, read off the mesh (1 = no pipe axis)
        self.pp = int(mesh.shape.get(stage_axis, 1))

    # -- per-leaf rules ----------------------------------------------------
    def _resident(self, shape, compute: P,
                  shard_over_data: bool) -> P:
        """Add the data axis to a free dimension when the ZeRO layouts
        want the leaf resident 1/(dp·tp); 1-D leaves and indivisible
        dims keep compute == resident."""
        if not shard_over_data or self.dp <= 1 or len(shape) != 2:
            return compute
        m, d = self.model_axis, self.data_axis
        if compute == P(None, m) and shape[0] % self.dp == 0:
            return P(d, m)
        if compute == P(m, None) and shape[1] % self.dp == 0:
            return P(m, d)
        return compute

    def infer_entry(self, entry,
                    shard_over_data: bool = False
                    ) -> Dict[str, TpLeafSpec]:
        """{name: TpLeafSpec} for one entry; names whose leaves stay
        replicated are omitted. Entries that are not flat
        ``{name: array}`` dicts get no tp specs (they ride the dp-only
        paths untouched)."""
        if self.tp <= 1 or not _is_flat_array_dict(entry):
            return {}
        m = self.model_axis
        specs: Dict[str, P] = {}
        col_widths = set()
        for name, a in entry.items():
            if a.ndim != 2:
                continue
            if a.shape[1] % self.tp == 0 and a.shape[1] >= self.tp:
                specs[name] = P(None, m)          # column (out-dim)
                col_widths.add(int(a.shape[1]))
            elif a.shape[0] % self.tp == 0 and a.shape[0] >= self.tp:
                specs[name] = P(m, None)          # row (in-dim)
        for name, a in entry.items():
            if (a.ndim == 1 and int(a.shape[0]) in col_widths
                    and a.shape[0] % self.tp == 0):
                specs[name] = P(m)                # column bias
        return {name: TpLeafSpec(sp, self._resident(entry[name].shape,
                                                    sp, shard_over_data))
                for name, sp in specs.items()}

    def infer(self, params,
              shard_over_data: bool = False
              ) -> Dict[str, Dict[str, TpLeafSpec]]:
        """{entry: {name: TpLeafSpec}} over a two-level param tree;
        entries with nothing to shard are omitted."""
        out = {}
        for k, sub in (params or {}).items():
            specs = self.infer_entry(sub, shard_over_data)
            if specs:
                out[k] = specs
        return out

    def infer_stages(self, params, partition,
                     shard_over_data: bool = False):
        """Per-stage tp specs under a pipeline partition: one
        ``{entry: {name: TpLeafSpec}}`` dict per stage, inferred
        against that stage's ``(data, model)`` submesh
        (:func:`parallel.pipeline.stage_submesh`). The pipe axis is a
        partition of whole entries, never a dimension in a spec, so
        each entry's specs equal what a 2D run would infer for it —
        the stage axis only decides *which* submesh pins them.

        ``partition`` is a :class:`parallel.pipeline.StagePartition`;
        when the mesh has no pipe axis (``self.pp == 1``) the single
        "stage" is inferred against the full mesh."""
        from deeplearning4j_tpu.parallel.pipeline import stage_submesh
        out = []
        for s in range(partition.n_stages):
            if self.pp > 1:
                sub = stage_submesh(self.mesh, s, self.stage_axis)
            else:
                sub = self.mesh
            layout = SpecLayout(sub, model_axis=self.model_axis,
                                data_axis=self.data_axis,
                                stage_axis=self.stage_axis)
            stage_params = {k: params[k]
                            for k in partition.stage_entries(s)
                            if k in (params or {})}
            out.append(layout.infer(stage_params, shard_over_data))
        return out


def tp_param_bytes(params, tp_specs) -> int:
    """Total bytes of the tensor-parallel leaves (dense accounting —
    each replica holds 1/tp of this once placed)."""
    total = 0
    for k, names in (tp_specs or {}).items():
        sub = params.get(k, {})
        for name in names:
            a = sub.get(name) if isinstance(sub, dict) else None
            if hasattr(a, "shape"):
                total += int(np.prod(a.shape)) * a.dtype.itemsize
    return total
