"""ZeRO-1 cross-replica sharded weight update (Xu et al., "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training").

The dense DP step ends in ``AllReduce(grads) -> every replica runs the
full updater on a full copy of the optimizer state``.  This module
replaces that tail with ``ReduceScatter -> each replica updates its 1/N
parameter shard + shard-local updater state -> AllGather of the new
params``: the optimizer state (2x params for Adam-family) lives sharded
along the ``data`` axis instead of replicated, freeing HBM, and the
update-phase HBM traffic drops ~N-fold.

Mechanics: params/grads ravel into one padded flat vector per dtype
(``learning.updaters.dp_ravel``); ``with_sharding_constraint`` pins the
summed flat gradient and the updater state to ``P(data)``, so XLA's
SPMD partitioner lowers the gradient all-reduce to a reduce-scatter and
runs the (purely elementwise) updater math on 1/N of the elements per
replica; constraining the new flat params back to replicated inserts
the all-gather.  Per-element arithmetic is identical to the dense path,
so SGD results stay bitwise equal and stateful updaters agree to float
tolerance.

Kill switch: ``DL4J_TPU_SHARDED_UPDATE=0`` (common.environment) forces
the dense tail everywhere, restoring the exact pre-ZeRO behavior.
"""
from __future__ import annotations

import enum
import logging
from typing import Dict

import jax
import numpy as np

from deeplearning4j_tpu.learning.updaters import (DP_SHARDED_KEY, dp_ravel,
                                                  dp_flatten_spec, dp_unravel,
                                                  is_dp_sharded)
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              flat_sharding, replicated)

log = logging.getLogger("deeplearning4j_tpu")


class UpdateExchange(str, enum.Enum):
    """How replicas exchange the weight update (the successor of the
    reference's threshold-encoding `TrainingMode` stance): ``dense`` =
    AllReduce + fully replicated update, ``sharded`` = ZeRO-1
    ReduceScatter/AllGather, ``auto`` = sharded whenever legal."""
    DENSE = "dense"
    SHARDED = "sharded"
    AUTO = "auto"


def resolve_update_exchange(mesh, axis: str = DEFAULT_DATA_AXIS,
                            requested=UpdateExchange.AUTO,
                            model=None) -> UpdateExchange:
    """Resolve ``auto``/validate a request down to DENSE or SHARDED.

    DENSE whenever the sharded tail cannot apply: env kill switch off,
    no mesh / dp axis of 1 (nothing to shard across), or the model uses
    norm-based gradient normalization (it needs the full summed
    gradient before any slicing)."""
    if isinstance(requested, str):
        try:
            requested = UpdateExchange(requested.lower())
        except ValueError:
            raise ValueError(
                f"unknown update_exchange {requested!r}; expected one "
                f"of {[e.value for e in UpdateExchange]}") from None
    from deeplearning4j_tpu.common.environment import Environment
    if not Environment.get().sharded_update:
        if requested is UpdateExchange.SHARDED:
            log.info("update_exchange=sharded requested but "
                     "DL4J_TPU_SHARDED_UPDATE=0; using dense")
        return UpdateExchange.DENSE
    if requested is UpdateExchange.DENSE:
        return UpdateExchange.DENSE
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return UpdateExchange.DENSE
    if model is not None:
        gn = getattr(getattr(model, "conf", None),
                     "gradient_normalization", None)
        if gn is not None and getattr(gn, "name", "NONE") != "NONE":
            log.info("gradient_normalization=%s needs the full summed "
                     "gradient; update exchange stays dense", gn.name)
            return UpdateExchange.DENSE
    return UpdateExchange.SHARDED


# ---------------------------------------------------------------------------
def apply_update_sharded(updater, grads, params, state, iteration, mesh,
                         axis: str = DEFAULT_DATA_AXIS, *, epoch=0):
    """The ZeRO-1 step tail for one param subtree, traced inside the
    caller's jit.  Returns ``(new_params, new_state)`` with new params
    fully replicated (post-all-gather) and new state in the sharded
    flat layout (``{DP_SHARDED_KEY: {slot: {dtype: flat}}}``; stateless
    updaters pass ``()`` through)."""
    n = mesh.shape[axis]
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def pin(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, sh), tree)

    flat_p, spec = dp_ravel(params, n)
    flat_g, _ = dp_ravel(grads, n, spec)
    # grads arrive as a per-shard sum pending all-reduce; pinning the
    # flat view to P(axis) turns that all-reduce into a reduce-scatter
    flat_g = pin(flat_g, shard)
    flat_p = pin(flat_p, shard)
    inner = state[DP_SHARDED_KEY] if is_dp_sharded(state) else state
    inner = pin(inner, shard)
    updates, new_inner = updater.apply(flat_g, inner, iteration, epoch)
    # updater math may run in f32 (Adam bias correction is a strong
    # f32); keep each dtype bucket's own dtype, as the dense tail does
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat, full)           # <- the all-gather
    new_params = dp_unravel(new_flat, spec)
    new_inner = pin(new_inner, shard)
    new_state = ({DP_SHARDED_KEY: new_inner} if is_dp_sharded(state)
                 else new_inner)
    return new_params, new_state


# -- layout conversions ------------------------------------------------------
def to_sharded_state(params, state, n_shards: int):
    """One subtree's dense updater state -> ZeRO-1 flat layout."""
    if not state or is_dp_sharded(state):
        return state
    return {DP_SHARDED_KEY: {slot: dp_ravel(tree, n_shards)[0]
                             for slot, tree in state.items()}}


def to_dense_state(params, state):
    """Inverse of :func:`to_sharded_state` (padding dropped)."""
    if not is_dp_sharded(state):
        return state
    spec = dp_flatten_spec(params, 1)
    return {slot: dp_unravel(flats, spec)
            for slot, flats in state[DP_SHARDED_KEY].items()}


def states_to_sharded(params: Dict, states: Dict, n_shards: int) -> Dict:
    """Model-level convenience: convert every layer/vertex entry."""
    return {k: to_sharded_state(params.get(k, {}), s, n_shards)
            for k, s in states.items()}


def states_to_dense(params: Dict, states: Dict) -> Dict:
    return {k: to_dense_state(params.get(k, {}), s)
            for k, s in states.items()}


def place_updater_states(mesh, states: Dict,
                         axis: str = DEFAULT_DATA_AXIS) -> Dict:
    """Device-put updater states on the mesh: sharded flat entries along
    ``P(axis)`` (1/N per replica — the whole HBM win), everything else
    replicated (the pre-ZeRO placement)."""
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def put(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh) if hasattr(a, "shape") else a,
            tree)

    from deeplearning4j_tpu.common.diagnostics import collective_span
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                 for s in states.values()
                 for a in jax.tree_util.tree_leaves(s)
                 if hasattr(a, "shape"))
    out = {}
    with collective_span("state_placement", axis, nbytes,
                         entries=len(states)):
        for k, s in states.items():
            if is_dp_sharded(s):
                out[k] = {DP_SHARDED_KEY: put(s[DP_SHARDED_KEY], shard)}
            else:
                out[k] = put(s, full)
    return out


# -- accounting --------------------------------------------------------------
def update_exchange_bytes(params, n_shards: int) -> int:
    """Per-replica wire bytes one update exchange moves (ring
    collectives): dense AllReduce = 2(N-1)/N * P bytes; the sharded
    ReduceScatter + AllGather pair moves the same total — the ZeRO-1
    win is HBM residency and update-phase HBM traffic, not wire bytes."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    if n_shards <= 1:
        return 0
    return int(2 * (n_shards - 1) * total / n_shards)


def exchange_report(params, n_shards: int, mode=None) -> dict:
    """Scaling-observatory accounting for one step's update exchange:
    parameter bytes, per-replica wire bytes (ring-collective model),
    and the wire:param ratio — the numbers a `scaling` block needs to
    say whether an efficiency drop tracks the collective budget or a
    straggler (`bench.py` folds this in next to the efficiency curve)."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    wire = update_exchange_bytes(params, n_shards)
    return {
        "mode": getattr(mode, "value", mode) or "dense",
        "shards": int(n_shards),
        "param_bytes": int(total),
        "wire_bytes_per_replica": int(wire),
        "wire_to_param_ratio": round(wire / total, 3) if total else 0.0,
    }


def sharded_state_bytes(states: Dict) -> int:
    """Total bytes of flat sharded updater state (whole-mesh; each
    replica holds 1/N of this)."""
    total = 0
    for s in states.values():
        if is_dp_sharded(s):
            total += sum(int(np.prod(a.shape)) * a.dtype.itemsize
                         for a in
                         jax.tree_util.tree_leaves(s[DP_SHARDED_KEY]))
    return total
