"""ZeRO-1 cross-replica sharded weight update (Xu et al., "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training").

The dense DP step ends in ``AllReduce(grads) -> every replica runs the
full updater on a full copy of the optimizer state``.  This module
replaces that tail with ``ReduceScatter -> each replica updates its 1/N
parameter shard + shard-local updater state -> AllGather of the new
params``: the optimizer state (2x params for Adam-family) lives sharded
along the ``data`` axis instead of replicated, freeing HBM, and the
update-phase HBM traffic drops ~N-fold.

Mechanics: params/grads ravel into one padded flat vector per dtype
(``learning.updaters.dp_ravel``); ``with_sharding_constraint`` pins the
summed flat gradient and the updater state to ``P(data)``, so XLA's
SPMD partitioner lowers the gradient all-reduce to a reduce-scatter and
runs the (purely elementwise) updater math on 1/N of the elements per
replica; constraining the new flat params back to replicated inserts
the all-gather.  Per-element arithmetic is identical to the dense path,
so SGD results stay bitwise equal and stateful updaters agree to float
tolerance.

Full FSDP (ZeRO-3) extends this to parameters and gradients: params
stay resident as the 1/N flat shard (``{FSDP_KEY: {dtype: flat}}``),
the forward all-gathers each layer's flats just-in-time through a
``custom_vjp`` gather whose transpose pins the cotangent back to
``P(axis)`` — so gradients are born reduce-scattered and a full grad
never materializes — and the update tail keeps the new flat params
pinned to the shard (no trailing all-gather). Per-chip residency for
params + grads + updater state drops to ~1/N; the wire total per step
is unchanged (param AllGather + grad ReduceScatter = one AllReduce).

Kill switches: ``DL4J_TPU_SHARDED_UPDATE=0`` (common.environment)
forces the dense tail everywhere, restoring the exact pre-ZeRO
behavior; ``DL4J_TPU_FSDP=0`` demotes fsdp requests to ZeRO-1;
``DL4J_TPU_FSDP_PREFETCH=0`` disables the layer k+1 gather prefetch.
"""
from __future__ import annotations

import enum
import functools
import logging
import time
from typing import Dict

import jax
import numpy as np

from deeplearning4j_tpu.learning.updaters import (DP_SHARDED_KEY, FSDP_KEY,
                                                  dp_ravel, dp_flatten_spec,
                                                  dp_unravel, is_dp_sharded,
                                                  is_fsdp)
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              flat_sharding, replicated)

log = logging.getLogger("deeplearning4j_tpu")


class UpdateExchange(str, enum.Enum):
    """How replicas exchange the weight update (the successor of the
    reference's threshold-encoding `TrainingMode` stance): ``dense`` =
    AllReduce + fully replicated update, ``sharded`` = ZeRO-1
    ReduceScatter/AllGather (updater state resident 1/N), ``fsdp`` =
    ZeRO-3 (params + grads + state resident 1/N, per-layer just-in-time
    param all-gather), ``auto`` = sharded whenever legal (fsdp is
    opt-in only: it trades gather latency for residency)."""
    DENSE = "dense"
    SHARDED = "sharded"
    FSDP = "fsdp"
    AUTO = "auto"


#: attrs nn.conf.constraints.apply_constraints keys off; any of them
#: set on a layer conf means the step tail must see full tensors
#: post-update, which the fsdp tail (params never gathered after the
#: update) cannot provide
_CONSTRAINT_ATTRS = ("constrain_weights", "constrain_bias",
                     "constrain_all", "constrain_params")


def _has_weight_constraints(model) -> bool:
    conf = getattr(model, "conf", None)
    layers = list(getattr(conf, "layers", None) or [])
    for v in (getattr(conf, "vertices", None) or {}).values():
        if getattr(v, "is_layer", False) and v.content is not None:
            layers.append(v.content)
    return any(getattr(layer, a, None)
               for layer in layers for a in _CONSTRAINT_ATTRS)


def resolve_update_exchange(mesh, axis: str = DEFAULT_DATA_AXIS,
                            requested=UpdateExchange.AUTO,
                            model=None) -> UpdateExchange:
    """Resolve ``auto``/validate a request down to DENSE, SHARDED or
    FSDP.

    DENSE whenever no sharded tail can apply: env kill switch off, no
    mesh / dp axis of 1 (nothing to shard across), or the model uses
    norm-based gradient normalization (it needs the full summed
    gradient before any slicing). An explicit FSDP request additionally
    falls back to SHARDED when ``DL4J_TPU_FSDP=0`` or the model carries
    weight constraints (the post-update projection needs full
    tensors)."""
    if isinstance(requested, str):
        try:
            requested = UpdateExchange(requested.lower())
        except ValueError:
            raise ValueError(
                f"unknown update_exchange {requested!r}; expected one "
                f"of {[e.value for e in UpdateExchange]}") from None
    from deeplearning4j_tpu.common.environment import Environment
    env = Environment.get()
    if not env.sharded_update:
        if requested in (UpdateExchange.SHARDED, UpdateExchange.FSDP):
            log.info("update_exchange=%s requested but "
                     "DL4J_TPU_SHARDED_UPDATE=0; using dense",
                     requested.value)
        return UpdateExchange.DENSE
    if requested is UpdateExchange.DENSE:
        return UpdateExchange.DENSE
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return UpdateExchange.DENSE
    if model is not None:
        gn = getattr(getattr(model, "conf", None),
                     "gradient_normalization", None)
        if gn is not None and getattr(gn, "name", "NONE") != "NONE":
            log.info("gradient_normalization=%s needs the full summed "
                     "gradient; update exchange stays dense", gn.name)
            return UpdateExchange.DENSE
    if requested is UpdateExchange.FSDP:
        if not env.fsdp:
            log.info("update_exchange=fsdp requested but DL4J_TPU_FSDP=0;"
                     " using sharded (ZeRO-1)")
            return UpdateExchange.SHARDED
        if model is not None and _has_weight_constraints(model):
            log.info("model has weight constraints (post-update "
                     "projection needs full tensors); update exchange "
                     "falls back to sharded (ZeRO-1)")
            return UpdateExchange.SHARDED
        return UpdateExchange.FSDP
    return UpdateExchange.SHARDED


# ---------------------------------------------------------------------------
def apply_update_sharded(updater, grads, params, state, iteration, mesh,
                         axis: str = DEFAULT_DATA_AXIS, *, epoch=0):
    """The ZeRO-1 step tail for one param subtree, traced inside the
    caller's jit.  Returns ``(new_params, new_state)`` with new params
    fully replicated (post-all-gather) and new state in the sharded
    flat layout (``{DP_SHARDED_KEY: {slot: {dtype: flat}}}``; stateless
    updaters pass ``()`` through)."""
    n = mesh.shape[axis]
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def pin(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, sh), tree)

    flat_p, spec = dp_ravel(params, n)
    flat_g, _ = dp_ravel(grads, n, spec)
    # grads arrive as a per-shard sum pending all-reduce; pinning the
    # flat view to P(axis) turns that all-reduce into a reduce-scatter
    flat_g = pin(flat_g, shard)
    flat_p = pin(flat_p, shard)
    inner = state[DP_SHARDED_KEY] if is_dp_sharded(state) else state
    inner = pin(inner, shard)
    updates, new_inner = updater.apply(flat_g, inner, iteration, epoch)
    # updater math may run in f32 (Adam bias correction is a strong
    # f32); keep each dtype bucket's own dtype, as the dense tail does
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat, full)           # <- the all-gather
    new_params = dp_unravel(new_flat, spec)
    new_inner = pin(new_inner, shard)
    new_state = ({DP_SHARDED_KEY: new_inner} if is_dp_sharded(state)
                 else new_inner)
    return new_params, new_state


# -- FSDP (ZeRO-3) -----------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_flats(flats, mesh, axis):
    """All-gather a layer's flat param shards to replicated.

    The custom vjp exists because ``with_sharding_constraint``'s
    transpose pins the cotangent to the SAME sharding — a plain
    replicated pin would force the gradient to replicate (all-reduce,
    full grad resident). Here the backward pins the cotangent to
    ``P(axis)`` instead, so the partitioner lowers the pending
    cross-replica gradient sum to a reduce-scatter and each replica
    only ever holds its 1/N grad shard."""
    full = replicated(mesh)
    return {k: jax.lax.with_sharding_constraint(v, full)
            for k, v in flats.items()}


def _gather_flats_fwd(flats, mesh, axis):
    return _gather_flats(flats, mesh, axis), None


def _gather_flats_bwd(mesh, axis, _res, ct):
    shard = flat_sharding(mesh, axis)
    return ({k: jax.lax.with_sharding_constraint(v, shard)
             for k, v in ct.items()},)


_gather_flats.defvjp(_gather_flats_fwd, _gather_flats_bwd)


def fsdp_gather(flats, spec, mesh, axis: str = DEFAULT_DATA_AXIS,
                cast_dtype=None):
    """One layer's flat shards -> dense param dict, traced inside the
    caller's jit (the just-in-time all-gather). ``cast_dtype`` applies
    the mixed-precision compute cast per-layer, post-gather."""
    dense = dp_unravel(_gather_flats(flats, mesh, axis), spec)
    if cast_dtype is not None:
        from deeplearning4j_tpu.common.dtypes import cast_floats
        dense = cast_floats(dense, cast_dtype)
    return dense


class FsdpParamView:
    """Trace-time lazy mapping over an fsdp-flat param tree.

    The step builders hand this to ``_forward`` in place of the dense
    param dict; each ``get(key)`` on an fsdp entry emits that layer's
    all-gather at its point of use, so gathers interleave with compute
    in program order instead of front-loading the full param tree.
    With ``prefetch`` the next layer's gather (in ``order``) is also
    emitted when layer k is touched, giving XLA's scheduler the room to
    overlap it with layer k's compute. ``cast`` mirrors
    ``dtypes.cast_floats`` for the compute-dtype path."""

    def __init__(self, params, specs, mesh, axis=DEFAULT_DATA_AXIS,
                 order=None, prefetch=True, cast_dtype=None):
        self._params = params
        self._specs = specs
        self._mesh = mesh
        self._axis = axis
        self._order = [k for k in (params if order is None else order)
                       if is_fsdp(params.get(k, {}))]
        self._prefetch = prefetch
        self._cast_dtype = cast_dtype
        self._cache = {}

    def cast(self, dtype):
        return FsdpParamView(self._params, self._specs, self._mesh,
                             self._axis, order=self._order,
                             prefetch=self._prefetch, cast_dtype=dtype)

    def _dense(self, key):
        if key not in self._cache:
            self._cache[key] = fsdp_gather(
                self._params[key][FSDP_KEY], self._specs[key],
                self._mesh, self._axis, cast_dtype=self._cast_dtype)
        return self._cache[key]

    def get(self, key, default=None):
        sub = self._params.get(key, default)
        if not is_fsdp(sub):
            if self._cast_dtype is not None and sub:
                from deeplearning4j_tpu.common.dtypes import cast_floats
                return cast_floats(sub, self._cast_dtype)
            return sub
        dense = self._dense(key)
        if self._prefetch and key in self._order:
            i = self._order.index(key)
            if i + 1 < len(self._order):
                self._dense(self._order[i + 1])
        return dense

    def __getitem__(self, key):
        if key not in self._params:
            raise KeyError(key)
        return self.get(key)

    def __contains__(self, key):
        return key in self._params

    def keys(self):
        return self._params.keys()


def params_to_fsdp(params: Dict, n_shards: int):
    """Model params -> per-entry fsdp flat layout. Returns
    ``(flat_params, specs)``; empty/already-flat entries pass through
    (and keep no spec)."""
    out, specs = {}, {}
    for k, sub in params.items():
        if not sub or is_fsdp(sub):
            out[k] = sub
            continue
        flats, spec = dp_ravel(sub, n_shards)
        out[k] = {FSDP_KEY: flats}
        specs[k] = spec
    return out, specs


def fsdp_spec_shards(specs) -> "int | None":
    """World size a set of fsdp specs was raveled for (None when there
    are no specs).  The elastic re-mesh check: resident flats whose
    spec shard count differs from the mesh about to consume them must
    round-trip through the dense layout first."""
    for spec in (specs or {}).values():
        return int(spec.n_shards)
    return None


def params_to_dense(params: Dict, specs: Dict) -> Dict:
    """Inverse of :func:`params_to_fsdp` (padding dropped). Runs on the
    host at layout-sync boundaries (checkpoint, inference outside the
    jitted step, mesh teardown); the gather wall time lands in the
    ``dl4j_fsdp_gather_seconds`` histogram."""
    if not any(is_fsdp(s) for s in params.values()
               if isinstance(s, dict)):
        return params
    t0 = time.perf_counter()
    out = {}
    for k, sub in params.items():
        out[k] = dp_unravel(sub[FSDP_KEY], specs[k]) if is_fsdp(sub) else sub
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    from deeplearning4j_tpu.common import telemetry
    if telemetry.enabled():
        telemetry.histogram(
            "dl4j_fsdp_gather_seconds",
            "host-observed wall time of a full fsdp param densify "
            "(all-gather + unravel) at a layout-sync boundary"
        ).observe(time.perf_counter() - t0)
    return out


def place_fsdp_params(mesh, params: Dict,
                      axis: str = DEFAULT_DATA_AXIS) -> Dict:
    """Device-put fsdp params on the mesh: flat entries along
    ``P(axis)`` (1/N resident per replica — the ZeRO-3 win), non-fsdp
    entries replicated. Sets the ``dl4j_fsdp_param_shard_bytes``
    residency gauge."""
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)
    n = mesh.shape.get(axis, 1)
    out, flat_bytes = {}, 0
    from deeplearning4j_tpu.common.diagnostics import collective_span
    with collective_span("fsdp_param_placement", axis, 0,
                         entries=len(params)):
        for k, sub in params.items():
            if is_fsdp(sub):
                flats = {dt: jax.device_put(v, shard)
                         for dt, v in sub[FSDP_KEY].items()}
                flat_bytes += sum(int(np.prod(v.shape)) * v.dtype.itemsize
                                  for v in flats.values())
                out[k] = {FSDP_KEY: flats}
            else:
                out[k] = jax.tree_util.tree_map(
                    lambda a: (jax.device_put(a, full)
                               if hasattr(a, "shape") else a), sub)
    from deeplearning4j_tpu.common import telemetry
    if telemetry.enabled():
        telemetry.gauge(
            "dl4j_fsdp_param_shard_bytes",
            "per-replica resident bytes of the fsdp flat parameter "
            "shards (1/N of the flat param total)"
        ).set(flat_bytes // max(n, 1))
    return out


def apply_update_fsdp(updater, flat_g, flat_p, state, iteration, mesh,
                      axis: str = DEFAULT_DATA_AXIS, *, epoch=0):
    """The ZeRO-3 step tail for one entry's flat shards, traced inside
    the caller's jit. Unlike :func:`apply_update_sharded` the inputs
    are already flat (grads arrive as the reduce-scattered cotangent of
    :func:`_gather_flats`) and the new params stay pinned to
    ``P(axis)`` — there is no trailing all-gather; the next step's
    forward re-gathers per-layer."""
    shard = flat_sharding(mesh, axis)

    def pin(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, shard), tree)

    flat_g = pin(flat_g)
    flat_p = pin(flat_p)
    inner = state[DP_SHARDED_KEY] if is_dp_sharded(state) else state
    inner = pin(inner)
    updates, new_inner = updater.apply(flat_g, inner, iteration, epoch)
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat)     # params stay 1/N resident: no all-gather
    new_inner = pin(new_inner)
    new_state = ({DP_SHARDED_KEY: new_inner} if is_dp_sharded(state)
                 else new_inner)
    return new_flat, new_state


# -- layout conversions ------------------------------------------------------
def _flats_match_spec(inner, spec) -> bool:
    """True when every flat's length equals the spec's PADDED length —
    i.e. the state was raveled for the same shard count."""
    for flats in inner.values():
        for dt, flat in flats.items():
            sizes = spec.sizes.get(dt)
            if sizes is None or int(flat.shape[0]) != sizes[1]:
                return False
    return True


def to_sharded_state(params, state, n_shards: int):
    """One subtree's dense updater state -> ZeRO-1 flat layout.

    A state that is ALREADY flat is checked against the padded sizes
    for ``n_shards``: flats raveled for a DIFFERENT world size (an
    elastic resume — padding is a multiple of the shard count) round-
    trip through the dense layout and re-ravel, so the layout always
    matches the mesh about to consume it (ROADMAP item 4's
    ``DpFlatSpec`` re-ravel)."""
    if not state:
        return state
    if is_dp_sharded(state):
        spec = dp_flatten_spec(params, n_shards)
        if _flats_match_spec(state[DP_SHARDED_KEY], spec):
            return state
        state = to_dense_state(params, state)
    return {DP_SHARDED_KEY: {slot: dp_ravel(tree, n_shards)[0]
                             for slot, tree in state.items()}}


def to_dense_state(params, state):
    """Inverse of :func:`to_sharded_state` (padding dropped)."""
    if not is_dp_sharded(state):
        return state
    spec = dp_flatten_spec(params, 1)
    return {slot: dp_unravel(flats, spec)
            for slot, flats in state[DP_SHARDED_KEY].items()}


def states_to_sharded(params: Dict, states: Dict, n_shards: int) -> Dict:
    """Model-level convenience: convert every layer/vertex entry."""
    return {k: to_sharded_state(params.get(k, {}), s, n_shards)
            for k, s in states.items()}


def states_to_dense(params: Dict, states: Dict) -> Dict:
    return {k: to_dense_state(params.get(k, {}), s)
            for k, s in states.items()}


def place_updater_states(mesh, states: Dict,
                         axis: str = DEFAULT_DATA_AXIS) -> Dict:
    """Device-put updater states on the mesh: sharded flat entries along
    ``P(axis)`` (1/N per replica — the whole HBM win), everything else
    replicated (the pre-ZeRO placement)."""
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def put(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh) if hasattr(a, "shape") else a,
            tree)

    from deeplearning4j_tpu.common.diagnostics import collective_span
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                 for s in states.values()
                 for a in jax.tree_util.tree_leaves(s)
                 if hasattr(a, "shape"))
    out = {}
    with collective_span("state_placement", axis, nbytes,
                         entries=len(states)):
        for k, s in states.items():
            if is_dp_sharded(s):
                out[k] = {DP_SHARDED_KEY: put(s[DP_SHARDED_KEY], shard)}
            else:
                out[k] = put(s, full)
    return out


# -- accounting --------------------------------------------------------------
def update_exchange_bytes(params, n_shards: int, mode=None) -> int:
    """Per-replica wire bytes one applied update exchange moves (ring
    collectives). All three modes move the same total: dense AllReduce
    = 2(N-1)/N * P bytes; sharded ReduceScatter + AllGather = the same
    pair; fsdp's per-layer param AllGather ((N-1)/N * P across the
    step) + grad ReduceScatter ((N-1)/N * P) also sum to it.  The
    ZeRO wins are HBM residency and update-phase HBM traffic, not wire
    bytes — ``mode`` is accepted so callers can be explicit, and the
    per-mode breakdown lives in :func:`exchange_report`."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    if n_shards <= 1:
        return 0
    return int(2 * (n_shards - 1) * total / n_shards)


def exchange_report(params, n_shards: int, mode=None) -> dict:
    """Scaling-observatory accounting for one step's update exchange:
    parameter bytes, per-replica wire bytes (ring-collective model),
    the wire:param ratio, plus a per-mode breakdown — dense reports the
    single all-reduce, sharded/fsdp split it into the grad
    reduce-scatter + param all-gather halves, and fsdp adds the
    per-replica param residency (`bench.py` folds this in next to the
    efficiency curve)."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    mode_s = getattr(mode, "value", mode) or "dense"
    wire = update_exchange_bytes(params, n_shards, mode)
    half = (int((n_shards - 1) * total / n_shards) if n_shards > 1 else 0)
    rep = {
        "mode": mode_s,
        "shards": int(n_shards),
        "param_bytes": int(total),
        "wire_bytes_per_replica": int(wire),
        "wire_to_param_ratio": round(wire / total, 3) if total else 0.0,
    }
    if mode_s == UpdateExchange.DENSE.value:
        rep["all_reduce_bytes"] = int(wire)
    else:
        rep["grad_reduce_scatter_bytes"] = half
        rep["param_all_gather_bytes"] = half
    if mode_s == UpdateExchange.FSDP.value:
        rep["param_resident_bytes_per_replica"] = (
            int(total // n_shards) if n_shards > 1 else int(total))
    return rep


def sharded_state_bytes(states: Dict) -> int:
    """Total bytes of flat sharded updater state (whole-mesh; each
    replica holds 1/N of this)."""
    total = 0
    for s in states.values():
        if is_dp_sharded(s):
            total += sum(int(np.prod(a.shape)) * a.dtype.itemsize
                         for a in
                         jax.tree_util.tree_leaves(s[DP_SHARDED_KEY]))
    return total
