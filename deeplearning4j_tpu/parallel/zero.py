"""ZeRO-1 cross-replica sharded weight update (Xu et al., "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training").

The dense DP step ends in ``AllReduce(grads) -> every replica runs the
full updater on a full copy of the optimizer state``.  This module
replaces that tail with ``ReduceScatter -> each replica updates its 1/N
parameter shard + shard-local updater state -> AllGather of the new
params``: the optimizer state (2x params for Adam-family) lives sharded
along the ``data`` axis instead of replicated, freeing HBM, and the
update-phase HBM traffic drops ~N-fold.

Mechanics: params/grads ravel into one padded flat vector per dtype
(``learning.updaters.dp_ravel``); ``with_sharding_constraint`` pins the
summed flat gradient and the updater state to ``P(data)``, so XLA's
SPMD partitioner lowers the gradient all-reduce to a reduce-scatter and
runs the (purely elementwise) updater math on 1/N of the elements per
replica; constraining the new flat params back to replicated inserts
the all-gather.  Per-element arithmetic is identical to the dense path,
so SGD results stay bitwise equal and stateful updaters agree to float
tolerance.

Full FSDP (ZeRO-3) extends this to parameters and gradients: params
stay resident as the 1/N flat shard (``{FSDP_KEY: {dtype: flat}}``),
the forward all-gathers each layer's flats just-in-time through a
``custom_vjp`` gather whose transpose pins the cotangent back to
``P(axis)`` — so gradients are born reduce-scattered and a full grad
never materializes — and the update tail keeps the new flat params
pinned to the shard (no trailing all-gather). Per-chip residency for
params + grads + updater state drops to ~1/N; the wire total per step
is unchanged (param AllGather + grad ReduceScatter = one AllReduce).

Kill switches: ``DL4J_TPU_SHARDED_UPDATE=0`` (common.environment)
forces the dense tail everywhere, restoring the exact pre-ZeRO
behavior; ``DL4J_TPU_FSDP=0`` demotes fsdp requests to ZeRO-1;
``DL4J_TPU_FSDP_PREFETCH=0`` disables the layer k+1 gather prefetch.
"""
from __future__ import annotations

import enum
import functools
import logging
import time
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.learning.updaters import (DP_SHARDED_KEY,
                                                  ENCODED_KEY, FSDP_KEY,
                                                  TP_KEY, dp_ravel,
                                                  dp_flatten_spec,
                                                  dp_unravel, has_tp,
                                                  is_dp_sharded,
                                                  is_encoded, is_fsdp)
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              flat_sharding, replicated)

log = logging.getLogger("deeplearning4j_tpu")


class UpdateExchange(str, enum.Enum):
    """How replicas exchange the weight update (the successor of the
    reference's threshold-encoding `TrainingMode` stance): ``dense`` =
    AllReduce + fully replicated update, ``sharded`` = ZeRO-1
    ReduceScatter/AllGather (updater state resident 1/N), ``fsdp`` =
    ZeRO-3 (params + grads + state resident 1/N, per-layer just-in-time
    param all-gather), ``auto`` = sharded whenever legal (fsdp is
    opt-in only: it trades gather latency for residency).

    ``encoded`` (ISSUE 20) is the fourth rung — the reference's
    threshold-encoded gradient sharing recast as compressed collectives:
    the sharded exchange with the flat gradient compressed before the
    data-axis collective (sign·tau threshold stream, int8 or 1-bit
    quantization per ``parallel.encoding.EncodingSpec``), per-replica
    error-feedback residuals carried in updater state. Opt-in like
    fsdp: it trades exact dense math for wire bytes."""
    DENSE = "dense"
    SHARDED = "sharded"
    FSDP = "fsdp"
    ENCODED = "encoded"
    AUTO = "auto"


#: attrs nn.conf.constraints.apply_constraints keys off; any of them
#: set on a layer conf means the step tail must see full tensors
#: post-update, which the fsdp tail (params never gathered after the
#: update) cannot provide
_CONSTRAINT_ATTRS = ("constrain_weights", "constrain_bias",
                     "constrain_all", "constrain_params")


def _has_weight_constraints(model) -> bool:
    conf = getattr(model, "conf", None)
    layers = list(getattr(conf, "layers", None) or [])
    for v in (getattr(conf, "vertices", None) or {}).values():
        if getattr(v, "is_layer", False) and v.content is not None:
            layers.append(v.content)
    return any(getattr(layer, a, None)
               for layer in layers for a in _CONSTRAINT_ATTRS)


def resolve_update_exchange(mesh, axis: str = DEFAULT_DATA_AXIS,
                            requested=UpdateExchange.AUTO,
                            model=None) -> UpdateExchange:
    """Resolve ``auto``/validate a request down to DENSE, SHARDED or
    FSDP.

    DENSE whenever no sharded tail can apply: env kill switch off, no
    mesh / dp axis of 1 (nothing to shard across), or the model uses
    norm-based gradient normalization (it needs the full summed
    gradient before any slicing). An explicit FSDP request additionally
    falls back to SHARDED when ``DL4J_TPU_FSDP=0`` or the model carries
    weight constraints (the post-update projection needs full
    tensors); an explicit ENCODED request falls back to SHARDED when
    ``DL4J_TPU_ENCODED_UPDATE=0`` (the kill switch keeps the sharded
    exchange, dropping only the compression)."""
    if isinstance(requested, str):
        try:
            requested = UpdateExchange(requested.lower())
        except ValueError:
            raise ValueError(
                f"unknown update_exchange {requested!r}; expected one "
                f"of {[e.value for e in UpdateExchange]}") from None
    from deeplearning4j_tpu.common.environment import Environment
    env = Environment.get()
    if not env.sharded_update:
        if requested in (UpdateExchange.SHARDED, UpdateExchange.FSDP,
                         UpdateExchange.ENCODED):
            log.info("update_exchange=%s requested but "
                     "DL4J_TPU_SHARDED_UPDATE=0; using dense",
                     requested.value)
        return UpdateExchange.DENSE
    if requested is UpdateExchange.DENSE:
        return UpdateExchange.DENSE
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return UpdateExchange.DENSE
    if model is not None:
        gn = getattr(getattr(model, "conf", None),
                     "gradient_normalization", None)
        if gn is not None and getattr(gn, "name", "NONE") != "NONE":
            log.info("gradient_normalization=%s needs the full summed "
                     "gradient; update exchange stays dense", gn.name)
            return UpdateExchange.DENSE
    if requested is UpdateExchange.ENCODED:
        if not env.encoded_update:
            log.info("update_exchange=encoded requested but "
                     "DL4J_TPU_ENCODED_UPDATE=0; using sharded "
                     "(ZeRO-1, uncompressed)")
            return UpdateExchange.SHARDED
        return UpdateExchange.ENCODED
    if requested is UpdateExchange.FSDP:
        if not env.fsdp:
            log.info("update_exchange=fsdp requested but DL4J_TPU_FSDP=0;"
                     " using sharded (ZeRO-1)")
            return UpdateExchange.SHARDED
        if model is not None and _has_weight_constraints(model):
            log.info("model has weight constraints (post-update "
                     "projection needs full tensors); update exchange "
                     "falls back to sharded (ZeRO-1)")
            return UpdateExchange.SHARDED
        return UpdateExchange.FSDP
    return UpdateExchange.SHARDED


# ---------------------------------------------------------------------------
def apply_update_sharded(updater, grads, params, state, iteration, mesh,
                         axis: str = DEFAULT_DATA_AXIS, *, epoch=0):
    """The ZeRO-1 step tail for one param subtree, traced inside the
    caller's jit.  Returns ``(new_params, new_state)`` with new params
    fully replicated (post-all-gather) and new state in the sharded
    flat layout (``{DP_SHARDED_KEY: {slot: {dtype: flat}}}``; stateless
    updaters pass ``()`` through)."""
    n = mesh.shape[axis]
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def pin(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, sh), tree)

    flat_p, spec = dp_ravel(params, n)
    flat_g, _ = dp_ravel(grads, n, spec)
    # grads arrive as a per-shard sum pending all-reduce; pinning the
    # flat view to P(axis) turns that all-reduce into a reduce-scatter.
    # On a mesh with another non-trivial axis (the 2D (data, model)
    # mesh) the SPMD partitioner miscompiles the ravel's `concatenate`
    # when its output is pinned straight to P(axis) — materialize the
    # flats replicated first, then reshard (an all-reduce + slice
    # instead of the fused reduce-scatter; values identical).
    if any(s > 1 for ax, s in mesh.shape.items() if ax != axis):
        flat_g = pin(flat_g, full)
        flat_p = pin(flat_p, full)
    flat_g = pin(flat_g, shard)
    flat_p = pin(flat_p, shard)
    inner = state[DP_SHARDED_KEY] if is_dp_sharded(state) else state
    inner = pin(inner, shard)
    updates, new_inner = updater.apply(flat_g, inner, iteration, epoch)
    # updater math may run in f32 (Adam bias correction is a strong
    # f32); keep each dtype bucket's own dtype, as the dense tail does
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat, full)           # <- the all-gather
    new_params = dp_unravel(new_flat, spec)
    new_inner = pin(new_inner, shard)
    new_state = ({DP_SHARDED_KEY: new_inner} if is_dp_sharded(state)
                 else new_inner)
    return new_params, new_state


# -- encoded rung (ISSUE 20) -------------------------------------------------
def apply_update_encoded(updater, grads, params, state, iteration, mesh,
                         axis: str = DEFAULT_DATA_AXIS, *, encoding,
                         epoch=0):
    """The encoded (compressed-collective) step tail for one param
    subtree, traced inside the caller's jit: the ZeRO-1 exchange of
    :func:`apply_update_sharded` with the flat gradient compressed
    before the data-axis collective.

    Per applied step, on each replica's 1/N flat shard: add the carried
    error-feedback residual, encode per ``encoding.scheme`` (sign·tau
    threshold stream / int8 / 1-bit — ``parallel.encoding``), carry
    ``corrected - decoded`` as the next residual, adapt tau from the
    observed transmitted fraction (``next_tau_traced``) and clip stale
    residual every ``frequency`` steps (``apply_traced``); the updater
    then consumes the DECODED gradient — what the compressed wire
    format would reconstruct — so the trailing all-gather moves only
    codec payload on a real DCN fabric. Under SPMD the encode runs on
    the summed gradient shard; each replica owns a distinct 1/N slice,
    so residuals are naturally per-replica.

    ``state`` must carry ``ENCODED_KEY`` (``ensure_encoded_state``
    injects it); returns ``(new_params, new_state)`` with params
    replicated post-all-gather, residual/inner state sharded."""
    n = mesh.shape[axis]
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def pin(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, sh), tree)

    flat_p, spec = dp_ravel(params, n)
    flat_g, _ = dp_ravel(grads, n, spec)
    # same 2D-mesh SPMD concatenate workaround as apply_update_sharded
    if any(s > 1 for ax, s in mesh.shape.items() if ax != axis):
        flat_g = pin(flat_g, full)
        flat_p = pin(flat_p, full)
    flat_g = pin(flat_g, shard)
    flat_p = pin(flat_p, shard)
    enc = state[ENCODED_KEY]
    residual = pin(enc["residual"], shard)
    tau, enc_step = enc["tau"], enc["step"]
    from deeplearning4j_tpu.parallel.encoding import encode_flat
    corrected = {k: flat_g[k] + residual[k].astype(flat_g[k].dtype)
                 for k in flat_g}
    decoded, frac_num, elems = {}, [], 0
    for k, c in corrected.items():
        d, f = encode_flat(c, tau, encoding.scheme)
        decoded[k] = d
        frac_num.append(f * c.size)
        elems += int(c.size)
    # size-weighted transmitted fraction across the dtype buckets (the
    # padding zeros count as not-transmitted: a slight underestimate,
    # bounded by n_shards/elems)
    sp = (sum(frac_num) / max(elems, 1) if elems
          else jnp.float32(0.0))
    new_residual = {k: (corrected[k] - decoded[k]).astype(
                        residual[k].dtype) for k in corrected}
    new_tau = encoding.algorithm.next_tau_traced(tau, sp)
    new_residual = encoding.residual_post.apply_traced(
        enc_step, new_tau, new_residual)
    inner = state.get(DP_SHARDED_KEY, ())
    inner = pin(inner, shard)
    updates, new_inner = updater.apply(decoded, inner, iteration, epoch)
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat, full)           # <- the all-gather
    new_params = dp_unravel(new_flat, spec)
    new_residual = pin(new_residual, shard)
    new_state = {ENCODED_KEY: {
        "residual": new_residual,
        "tau": jnp.asarray(new_tau, jnp.float32),
        "step": jnp.asarray(enc_step + 1, jnp.int32),
        "sparsity": jnp.asarray(sp, jnp.float32),
    }}
    if is_dp_sharded(state):
        new_state[DP_SHARDED_KEY] = pin(new_inner, shard)
    return new_params, new_state


# -- FSDP (ZeRO-3) -----------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_flats(flats, mesh, axis):
    """All-gather a layer's flat param shards to replicated.

    The custom vjp exists because ``with_sharding_constraint``'s
    transpose pins the cotangent to the SAME sharding — a plain
    replicated pin would force the gradient to replicate (all-reduce,
    full grad resident). Here the backward pins the cotangent to
    ``P(axis)`` instead, so the partitioner lowers the pending
    cross-replica gradient sum to a reduce-scatter and each replica
    only ever holds its 1/N grad shard."""
    full = replicated(mesh)
    return {k: jax.lax.with_sharding_constraint(v, full)
            for k, v in flats.items()}


def _gather_flats_fwd(flats, mesh, axis):
    return _gather_flats(flats, mesh, axis), None


def _gather_flats_bwd(mesh, axis, _res, ct):
    shard = flat_sharding(mesh, axis)
    return ({k: jax.lax.with_sharding_constraint(v, shard)
             for k, v in ct.items()},)


_gather_flats.defvjp(_gather_flats_fwd, _gather_flats_bwd)


def fsdp_gather(flats, spec, mesh, axis: str = DEFAULT_DATA_AXIS,
                cast_dtype=None):
    """One layer's flat shards -> dense param dict, traced inside the
    caller's jit (the just-in-time all-gather). ``cast_dtype`` applies
    the mixed-precision compute cast per-layer, post-gather."""
    dense = dp_unravel(_gather_flats(flats, mesh, axis), spec)
    if cast_dtype is not None:
        from deeplearning4j_tpu.common.dtypes import cast_floats
        dense = cast_floats(dense, cast_dtype)
    return dense


class FsdpParamView:
    """Trace-time lazy mapping over an fsdp-flat param tree.

    The step builders hand this to ``_forward`` in place of the dense
    param dict; each ``get(key)`` on an fsdp entry emits that layer's
    all-gather at its point of use, so gathers interleave with compute
    in program order instead of front-loading the full param tree.
    With ``prefetch`` the next layer's gather (in ``order``) is also
    emitted when layer k is touched, giving XLA's scheduler the room to
    overlap it with layer k's compute. ``cast`` mirrors
    ``dtypes.cast_floats`` for the compute-dtype path."""

    def __init__(self, params, specs, mesh, axis=DEFAULT_DATA_AXIS,
                 order=None, prefetch=True, cast_dtype=None,
                 tp_specs=None):
        self._params = params
        self._specs = specs
        self._mesh = mesh
        self._axis = axis
        self._order = [k for k in (params if order is None else order)
                       if is_fsdp(params.get(k, {}))]
        self._prefetch = prefetch
        self._cast_dtype = cast_dtype
        self._tp_specs = tp_specs or {}
        self._cache = {}

    def cast(self, dtype):
        return FsdpParamView(self._params, self._specs, self._mesh,
                             self._axis, order=self._order,
                             prefetch=self._prefetch, cast_dtype=dtype,
                             tp_specs=self._tp_specs)

    def _dense(self, key):
        if key not in self._cache:
            sub = self._params[key]
            dense = fsdp_gather(
                sub[FSDP_KEY], self._specs[key],
                self._mesh, self._axis, cast_dtype=self._cast_dtype)
            if has_tp(sub):
                # tp leaves gather over data only (resident -> compute
                # spec); the model-axis sharding stays physical
                sp = self._tp_specs.get(key, {})
                tp = {n: (tp_gather_leaf(a,
                                         _named(self._mesh,
                                                sp[n].compute),
                                         _named(self._mesh,
                                                sp[n].resident))
                          if n in sp else a)
                      for n, a in sub[TP_KEY].items()}
                if self._cast_dtype is not None:
                    from deeplearning4j_tpu.common.dtypes import \
                        cast_floats
                    tp = cast_floats(tp, self._cast_dtype)
                dense = {**dense, **tp}
            self._cache[key] = dense
        return self._cache[key]

    def get(self, key, default=None):
        sub = self._params.get(key, default)
        if not is_fsdp(sub):
            if self._cast_dtype is not None and sub:
                from deeplearning4j_tpu.common.dtypes import cast_floats
                return cast_floats(sub, self._cast_dtype)
            return sub
        dense = self._dense(key)
        if self._prefetch and key in self._order:
            i = self._order.index(key)
            if i + 1 < len(self._order):
                self._dense(self._order[i + 1])
        return dense

    def __getitem__(self, key):
        if key not in self._params:
            raise KeyError(key)
        return self.get(key)

    def __contains__(self, key):
        return key in self._params

    def keys(self):
        return self._params.keys()


def params_to_fsdp(params: Dict, n_shards: int, tp_specs=None):
    """Model params -> per-entry fsdp flat layout. Returns
    ``(flat_params, specs)``; empty/already-flat entries pass through
    (and keep no spec). Entries with ``tp_specs`` names split: those
    leaves ride under TP_KEY as full-shape arrays (model-axis sharded
    via spec placement) and only the rest ravels into the dp flats."""
    tp_specs = tp_specs or {}
    out, specs = {}, {}
    for k, sub in params.items():
        if not sub or is_fsdp(sub):
            out[k] = sub
            continue
        names = tp_specs.get(k, ())
        if names and isinstance(sub, dict):
            tpp = {n: sub[n] for n in names if n in sub}
            rest = {n: a for n, a in sub.items() if n not in names}
        else:
            tpp, rest = {}, sub
        flats, spec = dp_ravel(rest, n_shards)
        out[k] = ({FSDP_KEY: flats, TP_KEY: tpp} if tpp
                  else {FSDP_KEY: flats})
        specs[k] = spec
    return out, specs


def fsdp_spec_shards(specs) -> "int | None":
    """World size a set of fsdp specs was raveled for (None when there
    are no specs).  The elastic re-mesh check: resident flats whose
    spec shard count differs from the mesh about to consume them must
    round-trip through the dense layout first."""
    for spec in (specs or {}).values():
        return int(spec.n_shards)
    return None


def on_2d_mesh(a) -> bool:
    """True when ``a`` is device-resident on a mesh with more than one
    non-trivial axis.  Dense leaves densified off a 2D ``(data, model)``
    residency must round-trip through the host before re-raveling:
    feeding them back through a concatenate -> shard-pin chain hits the
    same XLA SPMD lowering bug :func:`apply_update_sharded` pins
    around."""
    mesh = getattr(getattr(a, "sharding", None), "mesh", None)
    if mesh is None or not hasattr(mesh, "shape"):
        return False
    return sum(1 for s in mesh.shape.values() if s > 1) > 1


def params_to_dense(params: Dict, specs: Dict) -> Dict:
    """Inverse of :func:`params_to_fsdp` (padding dropped). Runs on the
    host at layout-sync boundaries (checkpoint, inference outside the
    jitted step, mesh teardown); the gather wall time lands in the
    ``dl4j_fsdp_gather_seconds`` histogram."""
    if not any(is_fsdp(s) for s in params.values()
               if isinstance(s, dict)):
        return params
    t0 = time.perf_counter()
    out = {}
    for k, sub in params.items():
        if is_fsdp(sub):
            dense = dp_unravel(sub[FSDP_KEY], specs[k])
            if has_tp(sub):
                dense = {**dense, **sub[TP_KEY]}
            out[k] = dense
        else:
            out[k] = sub
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
    from deeplearning4j_tpu.common import telemetry
    if telemetry.enabled():
        telemetry.histogram(
            "dl4j_fsdp_gather_seconds",
            "host-observed wall time of a full fsdp param densify "
            "(all-gather + unravel) at a layout-sync boundary"
        ).observe(time.perf_counter() - t0)
    return out


def place_fsdp_params(mesh, params: Dict,
                      axis: str = DEFAULT_DATA_AXIS,
                      tp_specs=None) -> Dict:
    """Device-put fsdp params on the mesh: flat entries along
    ``P(axis)`` (1/N resident per replica — the ZeRO-3 win), TP_KEY
    leaves at their RESIDENT NamedSharding (model×data under fsdp×tp),
    non-fsdp entries replicated. Sets the
    ``dl4j_fsdp_param_shard_bytes`` residency gauge."""
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)
    n = mesh.shape.get(axis, 1)
    out, flat_bytes = {}, 0
    from deeplearning4j_tpu.common.diagnostics import collective_span
    with collective_span("fsdp_param_placement", axis, 0,
                         entries=len(params)):
        for k, sub in params.items():
            if is_fsdp(sub):
                flats = {dt: jax.device_put(v, shard)
                         for dt, v in sub[FSDP_KEY].items()}
                flat_bytes += sum(int(np.prod(v.shape)) * v.dtype.itemsize
                                  for v in flats.values())
                out[k] = {FSDP_KEY: flats}
                if has_tp(sub):
                    sp = (tp_specs or {}).get(k, {})
                    out[k][TP_KEY] = {
                        n_: jax.device_put(
                            a, _named(mesh, sp[n_].resident)
                            if n_ in sp else full)
                        for n_, a in sub[TP_KEY].items()}
            else:
                out[k] = jax.tree_util.tree_map(
                    lambda a: (jax.device_put(a, full)
                               if hasattr(a, "shape") else a), sub)
    from deeplearning4j_tpu.common import telemetry
    if telemetry.enabled():
        telemetry.gauge(
            "dl4j_fsdp_param_shard_bytes",
            "per-replica resident bytes of the fsdp flat parameter "
            "shards (1/N of the flat param total)"
        ).set(flat_bytes // max(n, 1))
    return out


def apply_update_fsdp(updater, flat_g, flat_p, state, iteration, mesh,
                      axis: str = DEFAULT_DATA_AXIS, *, epoch=0):
    """The ZeRO-3 step tail for one entry's flat shards, traced inside
    the caller's jit. Unlike :func:`apply_update_sharded` the inputs
    are already flat (grads arrive as the reduce-scattered cotangent of
    :func:`_gather_flats`) and the new params stay pinned to
    ``P(axis)`` — there is no trailing all-gather; the next step's
    forward re-gathers per-layer."""
    shard = flat_sharding(mesh, axis)

    def pin(tree):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, shard), tree)

    flat_g = pin(flat_g)
    flat_p = pin(flat_p)
    inner = state[DP_SHARDED_KEY] if is_dp_sharded(state) else state
    inner = pin(inner)
    updates, new_inner = updater.apply(flat_g, inner, iteration, epoch)
    new_flat = {k: (flat_p[k] - updates[k]).astype(flat_p[k].dtype)
                for k in flat_p}
    new_flat = pin(new_flat)     # params stay 1/N resident: no all-gather
    new_inner = pin(new_inner)
    new_state = ({DP_SHARDED_KEY: new_inner} if is_dp_sharded(state)
                 else new_inner)
    return new_flat, new_state


# -- tensor parallelism (2D (data, model) meshes) ----------------------------
# TP leaves keep their FULL logical shape everywhere; the specs below
# (parallel.speclayout.TpLeafSpec) only pin physical placement, so the
# updater/constraint math is byte-for-byte the dense math. The one
# layout-visible rule: tp leaves never ravel into the dp flats — a
# data-axis ravel of a model-sharded leaf would all-gather across the
# model axis inside the step, which 2D mode forbids. They ride under
# TP_KEY instead and get their own elementwise tail (apply_update_tp).

def _named(mesh, spec):
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, spec)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def tp_gather_leaf(x, compute_sh, resident_sh):
    """Pin one tp leaf to its compute sharding for the forward.

    Like :func:`_gather_flats`, the custom vjp exists because a plain
    constraint's transpose pins the cotangent to the SAME sharding;
    here the backward pins it to the RESIDENT sharding instead, so
    under fsdp×tp (resident = ``P(data, model)``) the pending data-axis
    gradient sum lowers to a reduce-scatter and each replica only holds
    its 1/(dp·tp) grad shard. When compute == resident (dense×tp) this
    degenerates to a symmetric pin whose backward all-reduces the grad
    over ``data`` only — never across ``model``."""
    return jax.lax.with_sharding_constraint(x, compute_sh)


def _tp_gather_fwd(x, compute_sh, resident_sh):
    return tp_gather_leaf(x, compute_sh, resident_sh), None


def _tp_gather_bwd(compute_sh, resident_sh, _res, ct):
    return (jax.lax.with_sharding_constraint(ct, resident_sh),)


tp_gather_leaf.defvjp(_tp_gather_fwd, _tp_gather_bwd)


def pin_tp_entry(entry, mesh, specs):
    """Pin an entry's tp leaves for the forward (traced inside the
    caller's jit). Non-spec'd leaves pass through untouched."""
    out = dict(entry)
    for name, ls in specs.items():
        a = out.get(name)
        if hasattr(a, "shape"):
            out[name] = tp_gather_leaf(a, _named(mesh, ls.compute),
                                       _named(mesh, ls.resident))
    return out


def split_tp_entry(entry, specs):
    """One dense entry -> (rest, tp) by spec'd names."""
    tp = {n: entry[n] for n in specs if n in entry}
    rest = {n: a for n, a in entry.items() if n not in specs}
    return rest, tp


def split_tp_state(state):
    """One entry's updater state -> (rest_state, tp_state); inverse is
    :func:`merge_tp_state`. Stateless entries pass ``()`` through."""
    if has_tp(state):
        rest = {k: v for k, v in state.items() if k != TP_KEY}
        return (rest if rest else ()), state[TP_KEY]
    return state, ()


def merge_tp_state(rest, tp):
    if not tp:
        return rest
    out = dict(rest) if isinstance(rest, dict) else {}
    out[TP_KEY] = tp
    return out


def _pin_by_name(tree, mesh, specs, which: str):
    """Pin every leaf of ``tree`` whose innermost dict key is a spec'd
    param name (handles both ``{name: arr}`` and the updater-state
    ``{slot: {name: arr}}`` shapes)."""
    def pin(path, a):
        if not hasattr(a, "shape"):
            return a
        for entry in reversed(path):
            name = getattr(entry, "key", None)
            if name in specs:
                sp = getattr(specs[name], which)
                return jax.lax.with_sharding_constraint(
                    a, _named(mesh, sp))
        return a
    return jax.tree_util.tree_map_with_path(pin, tree)


def apply_update_tp(updater, grads, params, state, iteration, mesh,
                    specs, *, gather_params: bool, epoch=0):
    """The update tail for one entry's tensor-parallel leaves, traced
    inside the caller's jit. Everything keeps full logical shapes; the
    pins keep the (purely elementwise) updater math physically sharded
    at the resident layout — model axis, plus ``data`` under the ZeRO
    layouts — so tp updater state is resident at 1/tp (·1/dp).
    ``gather_params=True`` pins the new params back to the compute
    layout (the ZeRO-1-style trailing data-axis all-gather);
    ``False`` keeps them resident (fsdp — the next forward re-gathers
    through :func:`tp_gather_leaf`)."""
    def pin(tree, which):
        return _pin_by_name(tree, mesh, specs, which)

    grads = pin(grads, "resident")
    params = pin(params, "resident")
    state = pin(state, "resident")
    updates, new_state = updater.apply(grads, state, iteration, epoch)
    new_params = {n: (params[n] - updates[n]).astype(params[n].dtype)
                  for n in params}
    new_params = pin(new_params,
                     "compute" if gather_params else "resident")
    new_state = pin(new_state, "resident")
    return new_params, new_state


def place_tp_params(mesh, params, tp_specs, *, resident: bool = False):
    """Device-put a DENSE-layout param tree on a 2D mesh: tp leaves at
    their compute (or resident) NamedSharding, everything else
    replicated. The dense×tp / sharded×tp placement (fsdp entries go
    through :func:`place_fsdp_params` instead)."""
    full = replicated(mesh)
    which = "resident" if resident else "compute"
    out = {}
    for k, sub in params.items():
        specs = (tp_specs or {}).get(k, {})
        if not specs or not isinstance(sub, dict):
            out[k] = jax.tree_util.tree_map(
                lambda a: (jax.device_put(a, full)
                           if hasattr(a, "shape") else a), sub)
            continue
        ent = {}
        for n, a in sub.items():
            if n in specs and hasattr(a, "shape"):
                ent[n] = jax.device_put(
                    a, _named(mesh, getattr(specs[n], which)))
            elif hasattr(a, "shape"):
                ent[n] = jax.device_put(a, full)
            else:
                ent[n] = a
        out[k] = ent
    return out


# -- layout conversions ------------------------------------------------------
def _flats_match_spec(inner, spec) -> bool:
    """True when every flat's length equals the spec's PADDED length —
    i.e. the state was raveled for the same shard count."""
    for flats in inner.values():
        for dt, flat in flats.items():
            sizes = spec.sizes.get(dt)
            if sizes is None or int(flat.shape[0]) != sizes[1]:
                return False
    return True


def _state_tp_names(state) -> set:
    """Param names the TP_KEY half of a flat state covers (the state is
    self-describing — slots mirror the tp param dict)."""
    names = set()
    for slot_tree in (state.get(TP_KEY, {}) or {}).values():
        if isinstance(slot_tree, dict):
            names |= set(slot_tree)
    return names


def _rest_of_params(params, tp_names):
    if tp_names and isinstance(params, dict):
        return {n: a for n, a in params.items() if n not in tp_names}
    return params


def _residual_is_flat(res, spec) -> bool:
    """Flat residuals are keyed by the spec's dtype names and 1-D;
    dense residuals carry the param treedef (param-name keys)."""
    return (isinstance(res, dict)
            and set(res) == set(spec.sizes)
            and all(getattr(v, "ndim", None) == 1 for v in res.values()))


def to_sharded_state(params, state, n_shards: int, tp_names=()):
    """One subtree's dense updater state -> ZeRO-1 flat layout (the
    ``tp_names`` leaves split out under TP_KEY as full-shape trees —
    they shard over ``model``(×``data``) via specs, never via the
    flats).

    A state that is ALREADY flat is checked against the padded sizes
    for ``n_shards`` AND the tp split: flats raveled for a DIFFERENT
    world size or tp partition (an elastic resume — padding is a
    multiple of the shard count) round-trip through the dense layout
    and re-ravel, so the layout always matches the mesh about to
    consume it (ROADMAP item 4's ``DpFlatSpec`` re-ravel).

    ENCODED_KEY rides along: the error-feedback residual re-ravels for
    ``n_shards`` (dense residuals and flats from a different world
    size both land on the padded flat for this mesh — padding is
    zeros, so the round-trip is bitwise); tau/step/sparsity scalars
    pass through."""
    if not state:
        return state
    tp_names = tuple(tp_names or ())
    if is_encoded(state):
        enc = state[ENCODED_KEY]
        base = {k: v for k, v in state.items() if k != ENCODED_KEY}
        out = to_sharded_state(params, base, n_shards, tp_names)
        out = dict(out) if isinstance(out, dict) else {}
        rest = _rest_of_params(params, tp_names)
        spec = dp_flatten_spec(rest, n_shards)
        res = enc["residual"]
        if not _flats_match_spec({"residual": res}, spec):
            if _residual_is_flat(res, spec):
                # flat for another world size -> dense first (slices
                # the true sizes, dropping that size's padding)
                res = dp_unravel(res, dp_flatten_spec(rest, 1))
            res = dp_ravel(res, n_shards)[0]
        out[ENCODED_KEY] = {**enc, "residual": res}
        return out

    def rest_of(tree):
        if tp_names and isinstance(tree, dict):
            return {n: a for n, a in tree.items() if n not in tp_names}
        return tree

    if is_dp_sharded(state) or has_tp(state):
        spec = dp_flatten_spec(rest_of(params), n_shards)
        if (_flats_match_spec(state.get(DP_SHARDED_KEY, {}), spec)
                and _state_tp_names(state) == set(tp_names)):
            return state
        state = to_dense_state(params, state)
    flats, tp = {}, {}
    for slot, tree in state.items():
        flats[slot] = dp_ravel(rest_of(tree), n_shards)[0]
        if tp_names and isinstance(tree, dict):
            tp_slot = {n: tree[n] for n in tp_names if n in tree}
            if tp_slot:
                tp[slot] = tp_slot
    out = {DP_SHARDED_KEY: flats}
    if tp:
        out[TP_KEY] = tp
    return out


def to_dense_state(params, state):
    """Inverse of :func:`to_sharded_state` (padding dropped; TP_KEY
    leaves — self-describing — merge back into their slots; an
    ENCODED_KEY residual unravels back into the param treedef so the
    checkpoint layout is exact and device-count-portable)."""
    if is_encoded(state):
        enc = state[ENCODED_KEY]
        base = {k: v for k, v in state.items() if k != ENCODED_KEY}
        out = to_dense_state(params, base)
        out = dict(out) if isinstance(out, dict) else {}
        tp_names = _state_tp_names(state)
        rest = _rest_of_params(params, tuple(tp_names))
        res = enc["residual"]
        spec1 = dp_flatten_spec(rest, 1)
        if _residual_is_flat(res, spec1):
            res = dp_unravel(res, spec1)
        out[ENCODED_KEY] = {**enc, "residual": res}
        return out
    if not (is_dp_sharded(state) or has_tp(state)):
        return state
    tp = state.get(TP_KEY, {}) if isinstance(state, dict) else {}
    tp_names = _state_tp_names(state)
    rest_params = ({n: p for n, p in params.items() if n not in tp_names}
                   if tp_names and isinstance(params, dict) else params)
    spec = dp_flatten_spec(rest_params, 1)
    out = {slot: dp_unravel(flats, spec)
           for slot, flats in state.get(DP_SHARDED_KEY, {}).items()}
    for slot, tree in tp.items():
        base = out.get(slot)
        out[slot] = ({**base, **tree} if isinstance(base, dict)
                     else dict(tree))
    return out


def states_to_sharded(params: Dict, states: Dict, n_shards: int,
                      tp_specs=None) -> Dict:
    """Model-level convenience: convert every layer/vertex entry."""
    tp_specs = tp_specs or {}
    return {k: to_sharded_state(params.get(k, {}), s, n_shards,
                                tp_names=tuple(tp_specs.get(k, ())))
            for k, s in states.items()}


def states_to_dense(params: Dict, states: Dict) -> Dict:
    return {k: to_dense_state(params.get(k, {}), s)
            for k, s in states.items()}


def ensure_encoded_state(params, state, n_shards: int, encoding,
                         tp_names=()):
    """One entry's updater state -> encoded flat layout: convert to the
    ZeRO-1 flats for ``n_shards`` and inject the error-feedback state
    (zero residual flats, the algorithm's initial tau, step 0) when
    absent. Entries with no dp-raveled leaves (empty, or fully tp)
    pass through — they never reach :func:`apply_update_encoded`."""
    tp_names = tuple(tp_names or ())
    rest = _rest_of_params(params, tp_names)
    leaves = [a for a in jax.tree_util.tree_leaves(rest)
              if hasattr(a, "shape")]
    if not leaves:
        # nothing to encode, but a fully-tp entry still needs its
        # TP_KEY split for the elementwise tail
        return to_sharded_state(params, state, n_shards, tp_names)
    base = to_sharded_state(params, state, n_shards, tp_names)
    if is_encoded(base):
        return base
    if isinstance(base, dict):
        out = dict(base)
    elif base:
        out = {DP_SHARDED_KEY: base}
    else:
        out = {}
    zeros = dp_ravel(jax.tree_util.tree_map(
        lambda a: jnp.zeros_like(a), rest), n_shards)[0]
    out[ENCODED_KEY] = {
        "residual": zeros,
        "tau": jnp.float32(encoding.initial_tau()),
        "step": jnp.int32(0),
        "sparsity": jnp.float32(0.0),
    }
    return out


def ensure_encoded_states(params: Dict, states: Dict, n_shards: int,
                          encoding, tp_specs=None) -> Dict:
    """Model-level convenience twin of :func:`states_to_sharded`."""
    tp_specs = tp_specs or {}
    return {k: ensure_encoded_state(
                params.get(k, {}), s, n_shards, encoding,
                tp_names=tuple(tp_specs.get(k, ())))
            for k, s in states.items()}


def strip_encoded_state(state):
    """Drop the encoded rung's error-feedback state from one entry (a
    mode change away from ``encoded`` — the residual belongs to the
    compressed exchange and must not leak into dense updater math)."""
    if is_encoded(state):
        base = {k: v for k, v in state.items() if k != ENCODED_KEY}
        return base if base else ()
    return state


def strip_encoded_states(states: Dict) -> Dict:
    return {k: strip_encoded_state(s) for k, s in states.items()}


def place_updater_states(mesh, states: Dict,
                         axis: str = DEFAULT_DATA_AXIS,
                         tp_specs=None) -> Dict:
    """Device-put updater states on the mesh: sharded flat entries along
    ``P(axis)`` (1/N per replica — the whole HBM win), TP_KEY slots at
    their leaves' RESIDENT NamedSharding (1/tp, ·1/dp under the ZeRO
    layouts), ENCODED_KEY residual flats along ``P(axis)`` with the
    tau/step/sparsity scalars replicated, everything else replicated
    (the pre-ZeRO placement)."""
    shard = flat_sharding(mesh, axis)
    full = replicated(mesh)

    def put(tree, sh):
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(a, sh) if hasattr(a, "shape") else a,
            tree)

    def put_tp(tp, sp):
        return {slot: {n: jax.device_put(
                           a, _named(mesh, sp[n].resident)
                           if n in sp else full)
                       for n, a in slot_tree.items()}
                for slot, slot_tree in tp.items()}

    from deeplearning4j_tpu.common.diagnostics import collective_span
    nbytes = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                 for s in states.values()
                 for a in jax.tree_util.tree_leaves(s)
                 if hasattr(a, "shape"))
    out = {}
    with collective_span("state_placement", axis, nbytes,
                         entries=len(states)):
        for k, s in states.items():
            if is_dp_sharded(s) or has_tp(s) or is_encoded(s):
                ent = {}
                if DP_SHARDED_KEY in s:
                    ent[DP_SHARDED_KEY] = put(s[DP_SHARDED_KEY], shard)
                if TP_KEY in s:
                    ent[TP_KEY] = put_tp(s[TP_KEY],
                                         (tp_specs or {}).get(k, {}))
                if ENCODED_KEY in s:
                    enc = s[ENCODED_KEY]
                    ent[ENCODED_KEY] = {
                        "residual": put(enc["residual"], shard),
                        **{kk: put(vv, full) for kk, vv in enc.items()
                           if kk != "residual"},
                    }
                out[k] = ent
            else:
                out[k] = put(s, full)
    return out


# -- accounting --------------------------------------------------------------
def update_exchange_axis_bytes(params, data_shards: int,
                               model_shards: int = 1,
                               tp_specs=None) -> dict:
    """Per-axis, per-replica wire bytes one update exchange moves on a
    2D ``(data, model)`` mesh (ring-collective model).

    The 2D invariant: dp collectives never cross the ``model`` axis —
    tp leaves stay out of the dp flats, so each model-shard group only
    exchanges its OWN 1/tp slice of the tp params over ``data``, and
    the update exchange moves ZERO bytes across ``model`` (activation
    psums in forward/backward are the only model-axis traffic).
    ``cross_axis_bytes`` reports what a naive data-ravel of the tp
    leaves WOULD have moved across ``model`` (the all-gather a flat
    pin of a model-sharded leaf implies) — 0 under this layout; the
    bench regression gate holds it down."""
    from deeplearning4j_tpu.parallel.speclayout import tp_param_bytes
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    tp = max(int(model_shards), 1)
    tpb = tp_param_bytes(params, tp_specs) if tp > 1 else 0
    exchanged = (total - tpb) + tpb // tp
    nd = max(int(data_shards), 1)
    data = (int(2 * (nd - 1) * exchanged / nd) if nd > 1 else 0)
    naive = (int((tp - 1) * tpb / tp) if tp > 1 else 0)
    return {"data": data, "model": 0, "pipe": 0,
            "cross_axis_bytes": 0,
            "naive_ravel_cross_axis_bytes": naive,
            "tp_param_bytes": int(tpb)}


def update_exchange_bytes(params, n_shards: int, mode=None) -> int:
    """Per-replica wire bytes one applied update exchange moves (ring
    collectives). All three modes move the same total: dense AllReduce
    = 2(N-1)/N * P bytes; sharded ReduceScatter + AllGather = the same
    pair; fsdp's per-layer param AllGather ((N-1)/N * P across the
    step) + grad ReduceScatter ((N-1)/N * P) also sum to it.  The
    ZeRO wins are HBM residency and update-phase HBM traffic, not wire
    bytes — ``mode`` is accepted so callers can be explicit, and the
    per-mode breakdown lives in :func:`exchange_report`."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    if n_shards <= 1:
        return 0
    return int(2 * (n_shards - 1) * total / n_shards)


def _dp_raveled_elems(params, tp_specs=None) -> int:
    """Element count of the leaves the dp flat ravel covers (tp leaves
    excluded — they stay on the elementwise tail)."""
    tp_specs = tp_specs or {}
    total = 0
    if not isinstance(params, dict):
        return sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(params)
                   if hasattr(a, "shape"))
    for k, sub in params.items():
        names = set(tp_specs.get(k, ()))
        if names and isinstance(sub, dict):
            sub = {n: a for n, a in sub.items() if n not in names}
        total += sum(int(np.prod(a.shape))
                     for a in jax.tree_util.tree_leaves(sub)
                     if hasattr(a, "shape"))
    return total


def encoded_exchange_bytes(params, n_shards: int, encoding=None,
                           sparsity=None, tp_specs=None) -> int:
    """Per-replica wire bytes the ENCODED exchange moves per applied
    step: the ring model (``2(N-1)/N``) applied to the codec's
    serialized payload (``parallel.encoding.encoded_payload_bytes``)
    instead of the dense parameter bytes. ``sparsity`` is the observed
    transmitted fraction (threshold scheme); when ``None`` the spec's
    planning sparsity is used. TP leaves are excluded — they ride
    their own uncompressed elementwise tail."""
    from deeplearning4j_tpu.parallel.encoding import (
        encoded_payload_bytes, resolve_encoding)
    spec = resolve_encoding(encoding)
    elems = _dp_raveled_elems(params, tp_specs)
    if n_shards <= 1 or elems == 0:
        return 0
    frac = (spec.planning_sparsity() if sparsity is None
            else float(sparsity))
    payload = encoded_payload_bytes(elems, spec.scheme, frac)
    return int(2 * (n_shards - 1) * payload / n_shards)


def exchange_report(params, n_shards: int, mode=None,
                    model_shards: int = 1, tp_specs=None,
                    pipe_shards: int = 1,
                    stage_param_bytes=None, encoding=None,
                    observed_sparsity=None) -> dict:
    """Scaling-observatory accounting for one step's update exchange:
    parameter bytes, per-replica wire bytes (ring-collective model),
    the wire:param ratio, plus a per-mode breakdown — dense reports the
    single all-reduce, sharded/fsdp split it into the grad
    reduce-scatter + param all-gather halves, and fsdp adds the
    per-replica param residency (`bench.py` folds this in next to the
    efficiency curve). With ``model_shards > 1`` the report adds the
    per-axis block from :func:`update_exchange_axis_bytes` and the tp
    residency (2D modes). With ``pipe_shards > 1`` a ``pipeline``
    block joins per-stage parameter bytes into the accounting — stage
    flats stay local to their pipe group, so the dp update exchange
    moves zero bytes across ``pipe`` (microbatch activation/cotangent
    handoffs, reported by the trainer as ``pipe_wire_bytes``, are the
    only pipe-axis traffic).

    For ``mode="encoded"`` the report compares the codec wire against
    the dense counterfactual: ``encoded_wire_bytes`` (ring model over
    the serialized payload, plus the uncompressed tp elementwise
    exchange when tp > 1) becomes ``wire_bytes_per_replica``,
    ``dense_wire_bytes`` keeps what the same step would have moved
    uncompressed, and ``compression_ratio`` is their quotient —
    strictly > 1 for every scheme (``encoding=`` /
    ``observed_sparsity=`` refine the estimate)."""
    total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree_util.tree_leaves(params)
                if hasattr(a, "shape"))
    mode_s = getattr(mode, "value", mode) or "dense"
    tp = max(int(model_shards), 1)
    axis_bytes = update_exchange_axis_bytes(params, n_shards, tp,
                                            tp_specs)
    wire = (axis_bytes["data"] if tp > 1
            else update_exchange_bytes(params, n_shards, mode))
    half = int(wire // 2)
    rep = {
        "mode": mode_s,
        "shards": int(n_shards),
        "param_bytes": int(total),
        "wire_bytes_per_replica": int(wire),
        "wire_to_param_ratio": round(wire / total, 3) if total else 0.0,
    }
    if mode_s == UpdateExchange.DENSE.value:
        rep["all_reduce_bytes"] = int(wire)
    else:
        rep["grad_reduce_scatter_bytes"] = half
        rep["param_all_gather_bytes"] = half
    if mode_s == UpdateExchange.ENCODED.value:
        from deeplearning4j_tpu.parallel.encoding import resolve_encoding
        enc_spec = resolve_encoding(encoding)
        frac = (enc_spec.planning_sparsity() if observed_sparsity is None
                else float(observed_sparsity))
        enc_wire = encoded_exchange_bytes(
            params, n_shards, enc_spec, sparsity=frac,
            tp_specs=tp_specs if tp > 1 else None)
        if tp > 1:
            # the tp elementwise tail exchanges its 1/tp slice dense
            tpb = axis_bytes["tp_param_bytes"]
            tp_wire = (int(2 * (n_shards - 1) * (tpb // tp) / n_shards)
                       if n_shards > 1 else 0)
        else:
            tp_wire = 0
        rep["dense_wire_bytes"] = int(wire)
        rep["encoded_wire_bytes"] = int(enc_wire + tp_wire)
        rep["wire_bytes_per_replica"] = rep["encoded_wire_bytes"]
        rep["wire_to_param_ratio"] = (
            round(rep["encoded_wire_bytes"] / total, 5) if total else 0.0)
        rep["compression_ratio"] = round(
            wire / max(rep["encoded_wire_bytes"], 1), 3)
        rep["encoding_scheme"] = enc_spec.scheme
        rep["encoding_sparsity"] = float(frac)
        enc_half = rep["encoded_wire_bytes"] // 2
        rep["grad_reduce_scatter_bytes"] = enc_half
        rep["param_all_gather_bytes"] = enc_half
    if mode_s == UpdateExchange.FSDP.value:
        rep["param_resident_bytes_per_replica"] = (
            int(total // n_shards) if n_shards > 1 else int(total))
    if tp > 1:
        rep["model_shards"] = tp
        rep["axis_bytes"] = axis_bytes
        rep["tp_resident_bytes_per_replica"] = (
            axis_bytes["tp_param_bytes"] // tp)
    pp = max(int(pipe_shards), 1)
    if pp > 1:
        stage_bytes = [int(b) for b in (stage_param_bytes or [])]
        rep["pipe_shards"] = pp
        rep["pipeline"] = {
            "stages": pp,
            "stage_param_bytes": stage_bytes,
            # dp flats are per pipe group; the update exchange never
            # crosses the pipe axis
            "cross_pipe_bytes": 0,
        }
    return rep


def sharded_state_bytes(states: Dict) -> int:
    """Total bytes of flat sharded updater state (whole-mesh; each
    replica holds 1/N of this)."""
    total = 0
    for s in states.values():
        if is_dp_sharded(s):
            total += sum(int(np.prod(a.shape)) * a.dtype.itemsize
                         for a in
                         jax.tree_util.tree_leaves(s[DP_SHARDED_KEY]))
    return total
