"""Threshold gradient encoding (compression) — functional port of the
reference's gradient-sharing update compression.

Reference parity (SURVEY.md P2, J11):
``org.deeplearning4j.optimize.solvers.accumulation.encoding.*`` —
`EncodingHandler` quantizes each gradient to sign(g)*tau for |g| > tau,
keeps the remainder as a local *residual* added back before the next
encode, and `ThresholdAlgorithm` adapts tau (Fixed / Adaptive /
TargetSparsity); `ResidualPostProcessor` clips stale residuals.

TPU-first status: BASELINE.json's north star explicitly replaces the
encoded-update exchange with a dense XLA AllReduce over ICI — on TPU the
dense collective is compiled into the step and is bandwidth-optimal, so
encoding is OFF by default. The semantics are preserved here as a pure
gradient transform (quantized + residual carry) usable as an optional
DCN-side compression mode: all ops are dense and jit-friendly (a sparse
int-index wire format would fight XLA's static shapes for no win
in-graph).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def encode_threshold(g: jnp.ndarray, tau) -> Tuple[jnp.ndarray,
                                                   jnp.ndarray]:
    """Quantize ``g`` to {-tau, 0, +tau} elementwise (the reference's
    native `encodeThreshold` op); returns (quantized, residual)."""
    q = jnp.where(jnp.abs(g) >= tau, jnp.sign(g) * tau, 0.0).astype(g.dtype)
    return q, g - q


def decode_threshold(q: jnp.ndarray) -> jnp.ndarray:
    """Identity in the dense representation (reference `decodeThreshold`
    turns the sparse int stream back into a dense array)."""
    return q


def sparsity(q: jnp.ndarray) -> jnp.ndarray:
    """Fraction of non-zero (transmitted) elements."""
    return jnp.mean((q != 0).astype(jnp.float32))


class ThresholdAlgorithm:
    """tau policy. Subclasses return the next tau given the last step's
    observed sparsity (reference: encoding.threshold.ThresholdAlgorithm)."""

    def initial(self) -> float:
        raise NotImplementedError

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        raise NotImplementedError


@dataclass
class FixedThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: FixedThresholdAlgorithm — constant tau."""
    threshold: float = 1e-3

    def initial(self) -> float:
        return self.threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        return tau


@dataclass
class AdaptiveThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: AdaptiveThresholdAlgorithm — keep the transmitted
    fraction inside [min_sparsity_target, max_sparsity_target] by
    scaling tau by `decay_rate` steps."""
    initial_threshold: float = 1e-3
    min_target: float = 1e-4
    max_target: float = 1e-2
    decay_rate: float = 1.02

    def initial(self) -> float:
        return self.initial_threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        if last_sparsity > self.max_target:    # sending too much -> raise
            return tau * self.decay_rate
        if last_sparsity < self.min_target:    # sending too little -> lower
            return tau / self.decay_rate
        return tau


@dataclass
class TargetSparsityThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: TargetSparsityThresholdAlgorithm — steer toward one
    target transmitted fraction."""
    initial_threshold: float = 1e-3
    target: float = 1e-3
    decay_rate: float = 1.05

    def initial(self) -> float:
        return self.initial_threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        if last_sparsity > self.target:
            return tau * self.decay_rate
        if last_sparsity < self.target:
            return tau / self.decay_rate
        return tau


@dataclass
class ResidualClippingPostProcessor:
    """Reference: encoding.residual.ResidualClippingPostProcessor —
    every `frequency` steps, clip residuals to +/- max_multiple*tau so
    stale residual cannot blow up."""
    max_multiple: float = 5.0
    frequency: int = 5

    def apply(self, step: int, tau: float, residual):
        if self.frequency <= 0 or step % self.frequency != 0:
            return residual
        lim = self.max_multiple * tau
        return jax.tree_util.tree_map(
            lambda r: jnp.clip(r, -lim, lim), residual)


class EncodingHandler:
    """Stateful encode pipeline (reference:
    accumulation.encoding.EncodingHandler): residual-corrected threshold
    quantization with adaptive tau.

    ``encode(grads)`` -> quantized grads tree; residual and tau update
    internally. The quantized tree is what a DCN-side compressed
    all-reduce would exchange; callers then apply it like a gradient.
    """

    def __init__(self, algorithm: Optional[ThresholdAlgorithm] = None,
                 residual_post: Optional[ResidualClippingPostProcessor]
                 = None):
        self.algorithm = algorithm or AdaptiveThresholdAlgorithm()
        self.residual_post = residual_post or ResidualClippingPostProcessor()
        self.tau = self.algorithm.initial()
        self.residual = None
        self.step = 0
        self.last_sparsity = 0.0

    def encode(self, grads):
        if self.residual is None:
            self.residual = jax.tree_util.tree_map(jnp.zeros_like, grads)
        corrected = jax.tree_util.tree_map(lambda g, r: g + r, grads,
                                           self.residual)
        pairs = jax.tree_util.tree_map(
            lambda g: encode_threshold(g, self.tau), corrected)
        quantized = jax.tree_util.tree_map(
            lambda p: p[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        self.residual = jax.tree_util.tree_map(
            lambda p: p[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        leaves = jax.tree_util.tree_leaves(quantized)
        if leaves:
            total = sum(l.size for l in leaves)
            # size-weighted mean of per-leaf sparsity() — one device
            # sync for the whole tree, not one per leaf
            frac = sum(sparsity(l) * l.size for l in leaves)
            self.last_sparsity = float(frac) / max(total, 1)
            from deeplearning4j_tpu.common import telemetry
            telemetry.gauge(
                "dl4j_dp_encoding_sparsity",
                "fraction of gradient elements the threshold encoder "
                "would transmit (reference: EncodingHandler wire "
                "density; drives the adaptive tau)").set(
                    self.last_sparsity)
        self.tau = self.algorithm.next_tau(self.tau, self.last_sparsity)
        self.residual = self.residual_post.apply(self.step, self.tau,
                                                 self.residual)
        self.step += 1
        return quantized
