"""Threshold gradient encoding (compression) — functional port of the
reference's gradient-sharing update compression.

Reference parity (SURVEY.md P2, J11):
``org.deeplearning4j.optimize.solvers.accumulation.encoding.*`` —
`EncodingHandler` quantizes each gradient to sign(g)*tau for |g| > tau,
keeps the remainder as a local *residual* added back before the next
encode, and `ThresholdAlgorithm` adapts tau (Fixed / Adaptive /
TargetSparsity); `ResidualPostProcessor` clips stale residuals.

TPU-first status: BASELINE.json's north star explicitly replaces the
encoded-update exchange with a dense XLA AllReduce over ICI — on TPU the
dense collective is compiled into the step and is bandwidth-optimal, so
encoding is OFF by default. The semantics are preserved here as a pure
gradient transform (quantized + residual carry) usable as an optional
DCN-side compression mode: all ops are dense and jit-friendly (a sparse
int-index wire format would fight XLA's static shapes for no win
in-graph).

ISSUE 20 revives the module as the engine of the fourth
``UpdateExchange`` rung (``parallel.zero.UpdateExchange.ENCODED``):
the traced variants below (``next_tau_traced``, ``apply_traced``,
``encode_flat``) run INSIDE the jitted step tail on the per-dtype flat
ravel, with per-replica error-feedback residuals carried in updater
state, and ``EncodingSpec`` is the builder-facing config
(``.encoding(...)`` on ``ParallelWrapper`` / ``SharedTrainingMaster``).
The host-side ``EncodingHandler`` remains as the standalone
out-of-graph transform.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: Wire codecs the encoded rung understands.  "threshold" is the
#: reference's sign*tau sparse stream; "int8"/"1bit" are the quantized
#: ReduceScatter/AllGather recasts (ROADMAP item 3).
SCHEMES = ("threshold", "int8", "1bit")


def encode_threshold(g: jnp.ndarray, tau) -> Tuple[jnp.ndarray,
                                                   jnp.ndarray]:
    """Quantize ``g`` to {-tau, 0, +tau} elementwise (the reference's
    native `encodeThreshold` op); returns (quantized, residual)."""
    q = jnp.where(jnp.abs(g) >= tau, jnp.sign(g) * tau, 0.0).astype(g.dtype)
    return q, g - q


def decode_threshold(q: jnp.ndarray) -> jnp.ndarray:
    """Identity in the dense representation (reference `decodeThreshold`
    turns the sparse int stream back into a dense array)."""
    return q


def sparsity(q: jnp.ndarray) -> jnp.ndarray:
    """Fraction of non-zero (transmitted) elements."""
    return jnp.mean((q != 0).astype(jnp.float32))


def encode_int8(c: jnp.ndarray) -> jnp.ndarray:
    """Symmetric per-bucket int8 quantization: round to 127 levels of
    max|c|, return the dequantized (decoded) value.  Under SPMD the max
    is over the local flat shard, so each replica carries its own scale
    — the scale rides the wire as one f32 beside the int8 payload."""
    scale = jnp.maximum(jnp.max(jnp.abs(c)), jnp.finfo(jnp.float32).tiny)
    scale = (scale / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(c.astype(jnp.float32) / scale), -127.0, 127.0)
    return (q * scale).astype(c.dtype)


def encode_1bit(c: jnp.ndarray) -> jnp.ndarray:
    """1-bit sign quantization with the scale that minimizes L2 error
    for a sign codebook (mean|c|); decoded value is sign(c)*mean|c|."""
    scale = jnp.mean(jnp.abs(c).astype(jnp.float32))
    return (jnp.sign(c).astype(jnp.float32) * scale).astype(c.dtype)


def encode_flat(c: jnp.ndarray, tau, scheme: str
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Traced encode of one residual-corrected flat: returns
    ``(decoded, transmitted_fraction)``.  The residual is
    ``c - decoded`` in every scheme (error feedback)."""
    if scheme == "threshold":
        q, _ = encode_threshold(c, tau)
        return q, sparsity(q)
    if scheme == "int8":
        return encode_int8(c), jnp.float32(1.0)
    if scheme == "1bit":
        return encode_1bit(c), jnp.float32(1.0)
    raise ValueError(f"unknown encoding scheme {scheme!r}; "
                     f"expected one of {SCHEMES}")


def encoded_payload_bytes(n_elems: int, scheme: str,
                          sparsity_frac: float = 1.0) -> int:
    """Bytes one replica puts on the wire for an ``n_elems`` gradient
    payload under ``scheme`` (the codec's serialized size, NOT the ring
    multiple — callers apply ``2(N-1)/N``):

    - ``threshold``: the reference's sparse int stream — one int32
      index per transmitted element (sign folded into the index as in
      the reference codec), value implicit ±tau, plus the tau scalar;
    - ``int8``: one byte per element plus the f32 scale;
    - ``1bit``: one bit per element plus the f32 scale.
    """
    if scheme == "threshold":
        return int(math.ceil(max(0.0, min(1.0, sparsity_frac))
                             * n_elems)) * 4 + 4
    if scheme == "int8":
        return int(n_elems) + 4
    if scheme == "1bit":
        return (int(n_elems) + 7) // 8 + 4
    raise ValueError(f"unknown encoding scheme {scheme!r}; "
                     f"expected one of {SCHEMES}")


class ThresholdAlgorithm:
    """tau policy. Subclasses return the next tau given the last step's
    observed sparsity (reference: encoding.threshold.ThresholdAlgorithm)."""

    def initial(self) -> float:
        raise NotImplementedError

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        raise NotImplementedError

    def next_tau_traced(self, tau, last_sparsity):
        """jnp.where twin of ``next_tau`` for use inside the jitted
        step tail (the host variant branches on concrete values)."""
        raise NotImplementedError


@dataclass
class FixedThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: FixedThresholdAlgorithm — constant tau."""
    threshold: float = 1e-3

    def initial(self) -> float:
        return self.threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        return tau

    def next_tau_traced(self, tau, last_sparsity):
        return tau


@dataclass
class AdaptiveThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: AdaptiveThresholdAlgorithm — keep the transmitted
    fraction inside [min_sparsity_target, max_sparsity_target] by
    scaling tau by `decay_rate` steps."""
    initial_threshold: float = 1e-3
    min_target: float = 1e-4
    max_target: float = 1e-2
    decay_rate: float = 1.02

    def initial(self) -> float:
        return self.initial_threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        if last_sparsity > self.max_target:    # sending too much -> raise
            return tau * self.decay_rate
        if last_sparsity < self.min_target:    # sending too little -> lower
            return tau / self.decay_rate
        return tau

    def next_tau_traced(self, tau, last_sparsity):
        return jnp.where(
            last_sparsity > self.max_target, tau * self.decay_rate,
            jnp.where(last_sparsity < self.min_target,
                      tau / self.decay_rate, tau))


@dataclass
class TargetSparsityThresholdAlgorithm(ThresholdAlgorithm):
    """Reference: TargetSparsityThresholdAlgorithm — steer toward one
    target transmitted fraction."""
    initial_threshold: float = 1e-3
    target: float = 1e-3
    decay_rate: float = 1.05

    def initial(self) -> float:
        return self.initial_threshold

    def next_tau(self, tau: float, last_sparsity: float) -> float:
        if last_sparsity > self.target:
            return tau * self.decay_rate
        if last_sparsity < self.target:
            return tau / self.decay_rate
        return tau

    def next_tau_traced(self, tau, last_sparsity):
        return jnp.where(
            last_sparsity > self.target, tau * self.decay_rate,
            jnp.where(last_sparsity < self.target,
                      tau / self.decay_rate, tau))


@dataclass
class ResidualClippingPostProcessor:
    """Reference: encoding.residual.ResidualClippingPostProcessor —
    every `frequency` steps, clip residuals to +/- max_multiple*tau so
    stale residual cannot blow up."""
    max_multiple: float = 5.0
    frequency: int = 5

    def apply(self, step: int, tau: float, residual):
        if self.frequency <= 0 or step % self.frequency != 0:
            return residual
        lim = self.max_multiple * tau
        return jax.tree_util.tree_map(
            lambda r: jnp.clip(r, -lim, lim), residual)

    def apply_traced(self, step, tau, residual):
        """Traced twin: ``step`` / ``tau`` are tracers, the clip fires
        via jnp.where every ``frequency`` applied updates."""
        if self.frequency <= 0:
            return residual
        lim = self.max_multiple * tau
        do = (step % self.frequency) == 0
        return jax.tree_util.tree_map(
            lambda r: jnp.where(do, jnp.clip(r, -lim, lim), r), residual)


@dataclass(frozen=True)
class EncodingSpec:
    """Config of the encoded update-exchange rung — what
    ``ParallelWrapper.Builder.encoding(...)`` /
    ``SharedTrainingConfiguration`` hand to the step tail.  All fields
    are static (baked into the trace); the dynamic quantities (tau,
    residual, observed sparsity) live in updater state under
    ``learning.updaters.ENCODED_KEY``.
    """
    scheme: str = "threshold"
    algorithm: ThresholdAlgorithm = field(
        default_factory=AdaptiveThresholdAlgorithm)
    residual_post: ResidualClippingPostProcessor = field(
        default_factory=ResidualClippingPostProcessor)

    def __post_init__(self):
        if self.scheme not in SCHEMES:
            raise ValueError(f"unknown encoding scheme "
                             f"{self.scheme!r}; expected one of "
                             f"{SCHEMES}")

    def initial_tau(self) -> float:
        return float(self.algorithm.initial())

    def signature(self) -> tuple:
        """Hashable identity for compile caches (the spec itself is
        eq-comparable but its algorithm objects are not hashable)."""
        return (self.scheme,
                type(self.algorithm).__name__,
                tuple(sorted(vars(self.algorithm).items())),
                type(self.residual_post).__name__,
                tuple(sorted(vars(self.residual_post).items())))

    def planning_sparsity(self) -> float:
        """Expected transmitted fraction before any step has run —
        used for the analytic wire-bytes estimate the live gauge then
        refines."""
        if self.scheme != "threshold":
            return 1.0
        if isinstance(self.algorithm, AdaptiveThresholdAlgorithm):
            return self.algorithm.max_target
        if isinstance(self.algorithm, TargetSparsityThresholdAlgorithm):
            return self.algorithm.target
        return 1e-2


def resolve_encoding(encoding=None) -> "EncodingSpec":
    """Normalize the builder-facing ``encoding=`` knob: ``None`` ->
    default spec, a scheme string -> spec with default algorithm, an
    ``EncodingSpec`` passes through."""
    if encoding is None:
        import os
        return EncodingSpec(scheme=os.environ.get(
            "DL4J_TPU_ENCODED_SCHEME", "threshold"))
    if isinstance(encoding, str):
        return EncodingSpec(scheme=encoding)
    if isinstance(encoding, EncodingSpec):
        return encoding
    raise TypeError("encoding= expects None, a scheme string "
                    f"{SCHEMES}, or an EncodingSpec; got "
                    f"{type(encoding).__name__}")


class EncodingHandler:
    """Stateful encode pipeline (reference:
    accumulation.encoding.EncodingHandler): residual-corrected threshold
    quantization with adaptive tau.

    ``encode(grads)`` -> quantized grads tree; residual and tau update
    internally. The quantized tree is what a DCN-side compressed
    all-reduce would exchange; callers then apply it like a gradient.
    """

    def __init__(self, algorithm: Optional[ThresholdAlgorithm] = None,
                 residual_post: Optional[ResidualClippingPostProcessor]
                 = None):
        self.algorithm = algorithm or AdaptiveThresholdAlgorithm()
        self.residual_post = residual_post or ResidualClippingPostProcessor()
        self.tau = self.algorithm.initial()
        self.residual = None
        self.step = 0
        self.last_sparsity = 0.0

    def encode(self, grads):
        if self.residual is None:
            self.residual = jax.tree_util.tree_map(jnp.zeros_like, grads)
        corrected = jax.tree_util.tree_map(lambda g, r: g + r, grads,
                                           self.residual)
        pairs = jax.tree_util.tree_map(
            lambda g: encode_threshold(g, self.tau), corrected)
        quantized = jax.tree_util.tree_map(
            lambda p: p[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        self.residual = jax.tree_util.tree_map(
            lambda p: p[1], pairs, is_leaf=lambda x: isinstance(x, tuple))
        leaves = jax.tree_util.tree_leaves(quantized)
        if leaves:
            total = sum(l.size for l in leaves)
            # size-weighted mean of per-leaf sparsity() — one device
            # sync for the whole tree, not one per leaf
            frac = sum(sparsity(l) * l.size for l in leaves)
            self.last_sparsity = float(frac) / max(total, 1)
            from deeplearning4j_tpu.common import telemetry
            telemetry.gauge(
                "dl4j_dp_encoding_sparsity",
                "fraction of gradient elements the encoder transmits "
                "(live per-step encoded-rung wire density; drives the "
                "adaptive tau)").set(self.last_sparsity)
        self.tau = self.algorithm.next_tau(self.tau, self.last_sparsity)
        self.residual = self.residual_post.apply(self.step, self.tau,
                                                 self.residual)
        self.step += 1
        return quantized
