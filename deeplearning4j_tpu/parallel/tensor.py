"""Tensor (model) parallelism — megatron-style layer sharding.

The reference has NO tensor parallelism (SURVEY.md §2.6 P7: ABSENT —
its in-node strategy is whole-model replicas per device,
`org.deeplearning4j.parallelism.ParallelWrapper`). This module is the
TPU-native extension: weight matrices are split across a mesh ``model``
axis and XLA collectives (psum / reduce_scatter / all_gather over ICI)
stitch the math back together.

Two classic layouts (Megatron-LM):

- **column parallel**: ``W: [d_in, d_out/tp]`` — input replicated,
  output feature-sharded. No communication in forward; the backward
  pass psums dX (shard_map autodiff inserts it from the in_specs).
- **row parallel**: ``W: [d_in/tp, d_out]`` — input feature-sharded,
  output needs a psum (ICI all-reduce). Bias added once, after the sum.

A transformer block does column→row for both the QKV/out-proj pair
(heads shard over ``model``) and the MLP up/down pair, so each block
costs exactly two all-reduces forward — the canonical TP recipe.

**Megatron sequence parallelism** (``sequence_parallel=True``): the
residual stream stays sharded along *time* over the SAME ``model``
axis in the norm/residual regions; each all-reduce is replaced by an
all_gather (entering a TP region) + reduce_scatter (leaving it) pair —
same bytes on the wire, but activation memory per chip drops to
``t/tp``. This is SP in the Megatron sense; ring/Ulysses CP over a
dedicated ``seq`` axis lives in :mod:`.sequence`.

All functions here are *manual-collective* primitives meant to run
inside ``jax.shard_map`` (the pipeline runtime wraps everything in one
shard_map over the full mesh). ``axis`` is the mesh axis name.

For the TRAINING path this recipe is promoted to a GSPMD lowering in
:mod:`.speclayout`: ``SpecLayout`` infers the same column/row
partition per parameter and the jitted step tails pin the leaves with
``with_sharding_constraint`` on a 2D ``(data, model)`` mesh, so XLA
inserts the collectives itself and the modes compose with the
ZeRO-1/ZeRO-3 update exchanges
(``ParallelWrapper.Builder.tensor_parallel``). This module stays the
explicit-collective reference (and the shard_map dryrun the 2D suite
checks the lowering against, tests/test_2d_parallel.py).

Layout-axis ownership (PR-12 convention): this module owns the
``model``-axis *math* (column/row sharded matmuls); :mod:`.speclayout`
owns the per-parameter ``model``/``data`` specs; :mod:`.pipeline` owns
the ``pipe`` axis — a stage partition of whole entries, orthogonal to
both, so ``pipe`` never appears in a spec or a shard_map here
(``ParallelWrapper.Builder.pipeline_stages`` composes all three into
one ``(data, model, pipe)`` mesh).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import dot_product_attention
from .mesh import axis_size

MODEL_AXIS = "model"


# ---------------------------------------------------------------------------
# parallel dense primitives (inside shard_map)
# ---------------------------------------------------------------------------
def column_parallel_dense(x, w, b=None):
    """x replicated over tp, w/b local output-shards -> sharded output."""
    y = x @ w
    if b is not None:
        y = y + b
    return y


def row_parallel_dense(x, w, b=None, axis: str = MODEL_AXIS):
    """x feature-sharded, w local input-shard -> full (replicated) output.

    The psum is the TP all-reduce (rides ICI when ``model`` is laid out
    on an ICI dimension of the physical mesh)."""
    y = lax.psum(x @ w, axis)
    if b is not None:
        y = y + b
    return y


def row_parallel_dense_scatter(x, w, b=None, axis: str = MODEL_AXIS,
                               seq_dim: int = 1):
    """Row-parallel dense that leaves the output *sequence*-sharded:
    reduce_scatter over ``axis`` along ``seq_dim`` instead of psum.
    The exit collective of a TP region under Megatron-SP."""
    y = lax.psum_scatter(x @ w, axis, scatter_dimension=seq_dim,
                         tiled=True)
    if b is not None:
        y = y + b
    return y


def sp_all_gather(x, axis: str = MODEL_AXIS, seq_dim: int = 1):
    """Gather the time dimension from the model axis (enter TP region)."""
    return lax.all_gather(x, axis, axis=seq_dim, tiled=True)


# ---------------------------------------------------------------------------
# TP transformer pieces
# ---------------------------------------------------------------------------
def tp_mlp(x, params, axis: str = MODEL_AXIS,
           activation: Callable = jax.nn.gelu,
           sequence_parallel: bool = False):
    """Column→row parallel 2-layer MLP.

    params: ``Wi [d, ff/tp]``, ``bi [ff/tp]``, ``Wo [ff/tp, d]``,
    ``bo [d]`` (bo must be identical on all tp shards).
    With ``sequence_parallel`` x is [b, t/tp, d] in and out.
    """
    if sequence_parallel:
        x = sp_all_gather(x, axis)
    h = activation(column_parallel_dense(x, params["Wi"], params["bi"]))
    if sequence_parallel:
        return row_parallel_dense_scatter(h, params["Wo"], params["bo"],
                                          axis)
    return row_parallel_dense(h, params["Wo"], params["bo"], axis)


def tp_self_attention(x, params, n_heads_local: int,
                      axis: str = MODEL_AXIS, mask=None,
                      sequence_parallel: bool = False):
    """Multi-head self-attention with heads sharded over ``axis``.

    params: ``Wq/Wk/Wv [d, h_local*dh]``, ``Wo [h_local*dh, d]``,
    ``bo [d]`` (replicated). QKV projections are column-parallel (no
    comm), attention runs on local heads, out-proj is row-parallel.
    x: [b, t, d] (or [b, t/tp, d] under sequence_parallel).
    """
    if sequence_parallel:
        x = sp_all_gather(x, axis)
    b, t, d = x.shape
    dh = params["Wq"].shape[-1] // n_heads_local

    def heads(a):
        return a.reshape(b, t, n_heads_local, dh).transpose(0, 2, 1, 3)

    q = heads(x @ params["Wq"])
    k = heads(x @ params["Wk"])
    v = heads(x @ params["Wv"])
    o = dot_product_attention(q, k, v, mask)
    o = o.transpose(0, 2, 1, 3).reshape(b, t, n_heads_local * dh)
    if sequence_parallel:
        return row_parallel_dense_scatter(o, params["Wo"], params["bo"],
                                          axis)
    return row_parallel_dense(o, params["Wo"], params["bo"], axis)


def layer_norm(x, g, b, eps: float = 1e-5):
    m = jnp.mean(x, -1, keepdims=True)
    v = jnp.mean(jnp.square(x - m), -1, keepdims=True)
    return (x - m) * lax.rsqrt(v + eps) * g + b


def tp_transformer_block(x, params, n_heads_local: int,
                         axis: str = MODEL_AXIS, mask=None,
                         activation: Callable = jax.nn.gelu,
                         sequence_parallel: bool = False,
                         mlp_fn: Optional[Callable] = None):
    """Pre-LN transformer block, TP (optionally Megatron-SP) sharded.

    ``mlp_fn(h) -> h`` overrides the dense MLP (the MoE hook). Under
    sequence_parallel the norms/residuals run on [b, t/tp, d] shards —
    exactly the memory saving Megatron-SP exists for.
    """
    h = layer_norm(x, params["ln1_g"], params["ln1_b"])
    x = x + tp_self_attention(h, params["attn"], n_heads_local, axis,
                              mask, sequence_parallel=sequence_parallel)
    h = layer_norm(x, params["ln2_g"], params["ln2_b"])
    if mlp_fn is not None:
        return x + mlp_fn(h)
    return x + tp_mlp(h, params["mlp"], axis, activation,
                      sequence_parallel=sequence_parallel)


# ---------------------------------------------------------------------------
# param init (local shards built from a global spec, deterministic)
# ---------------------------------------------------------------------------
def init_tp_block_params(key, d_model: int, n_heads: int, d_ff: int,
                         tp: int, tp_rank, dtype=jnp.float32):
    """Build ONE tp-shard of a block's params. Each shard slices the
    same globally-initialized weights, so (tp=k) == (tp=1) numerically.

    ``tp_rank`` may be a traced value (lax.axis_index) — slicing uses
    dynamic_slice so this works inside shard_map."""
    ks = jax.random.split(key, 4)
    dh = d_model // n_heads

    def col_shard(k, d_in, d_out):  # [d_in, d_out] -> local [d_in, d_out/tp]
        w = jax.random.normal(k, (d_in, d_out), dtype) * (d_in ** -0.5)
        return lax.dynamic_slice_in_dim(
            w, tp_rank * (d_out // tp), d_out // tp, axis=1)

    def row_shard(k, d_in, d_out):  # local [d_in/tp, d_out]
        w = jax.random.normal(k, (d_in, d_out), dtype) * (d_in ** -0.5)
        return lax.dynamic_slice_in_dim(
            w, tp_rank * (d_in // tp), d_in // tp, axis=0)

    return {
        "ln1_g": jnp.ones((d_model,), dtype),
        "ln1_b": jnp.zeros((d_model,), dtype),
        "ln2_g": jnp.ones((d_model,), dtype),
        "ln2_b": jnp.zeros((d_model,), dtype),
        "attn": {
            "Wq": col_shard(ks[0], d_model, n_heads * dh),
            "Wk": col_shard(jax.random.fold_in(ks[0], 1), d_model,
                            n_heads * dh),
            "Wv": col_shard(jax.random.fold_in(ks[0], 2), d_model,
                            n_heads * dh),
            "Wo": row_shard(ks[1], n_heads * dh, d_model),
            "bo": jnp.zeros((d_model,), dtype),
        },
        "mlp": {
            "Wi": col_shard(ks[2], d_model, d_ff),
            "bi": jnp.zeros((d_ff // tp,), dtype),
            "Wo": row_shard(ks[3], d_ff, d_model),
            "bo": jnp.zeros((d_model,), dtype),
        },
    }


# ---------------------------------------------------------------------------
# GSPMD PartitionSpec rules (the pjit/auto-sharding path)
# ---------------------------------------------------------------------------
def megatron_specs(axis: str = MODEL_AXIS):
    """PartitionSpecs for a tp block's params under GSPMD auto
    partitioning (annotate params with NamedSharding(mesh, spec) and
    jit — XLA inserts the same collectives the manual path spells
    out). Keys mirror :func:`init_tp_block_params`."""
    from jax.sharding import PartitionSpec as P
    col = P(None, axis)
    row = P(axis, None)
    rep = P()
    return {
        "ln1_g": rep, "ln1_b": rep, "ln2_g": rep, "ln2_b": rep,
        "attn": {"Wq": col, "Wk": col, "Wv": col, "Wo": row, "bo": rep},
        "mlp": {"Wi": col, "bi": P(axis), "Wo": row, "bo": rep},
    }
