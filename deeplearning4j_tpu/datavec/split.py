"""Input splits (reference: ``org.datavec.api.split.*``, SURVEY.md V1):
where records come from, decoupled from how they are parsed."""
from __future__ import annotations

import glob as _glob
import os
import random
from typing import List, Optional, Sequence


class InputSplit:
    def locations(self) -> List[str]:
        raise NotImplementedError

    def length(self) -> int:
        return len(self.locations())


class FileSplit(InputSplit):
    """Recursive directory (or single-file) split with optional
    extension filter and shuffle (reference: api.split.FileSplit)."""

    def __init__(self, root: str,
                 allowed_extensions: Optional[Sequence[str]] = None,
                 random_seed: Optional[int] = None):
        self.root = str(root)
        self.allowed = (tuple(e.lower().lstrip(".")
                              for e in allowed_extensions)
                        if allowed_extensions else None)
        self.seed = random_seed
        self._locs: Optional[List[str]] = None

    def locations(self) -> List[str]:
        if self._locs is None:
            if os.path.isfile(self.root):
                files = [self.root]
            else:
                files = sorted(
                    p for p in _glob.glob(
                        os.path.join(self.root, "**", "*"),
                        recursive=True)
                    if os.path.isfile(p))
            if self.allowed is not None:
                files = [f for f in files
                         if f.rsplit(".", 1)[-1].lower() in self.allowed]
            if self.seed is not None:
                rng = random.Random(self.seed)
                rng.shuffle(files)
            self._locs = files
        return self._locs


class ListStringSplit(InputSplit):
    """In-memory list of 'lines' (reference: ListStringSplit)."""

    def __init__(self, data: Sequence):
        self.data = list(data)

    def locations(self):
        return self.data


class NumberedFileInputSplit(InputSplit):
    """Pattern like ``file_%d.csv`` over [min_idx, max_idx]
    (reference: NumberedFileInputSplit)."""

    def __init__(self, base_string: str, min_idx: int, max_idx: int):
        if "%d" not in base_string:
            raise ValueError("pattern must contain %d")
        self.base = base_string
        self.lo, self.hi = int(min_idx), int(max_idx)

    def locations(self):
        return [self.base % i for i in range(self.lo, self.hi + 1)]
