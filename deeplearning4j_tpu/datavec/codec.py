"""Video/frame-sequence modality (SURVEY.md V4: `datavec-data-codec`
— `CodecRecordReader` yielding per-frame sequences).

The reference decodes containers via JavaCPP-ffmpeg; this image has
no codec libraries, so the native-decode path is gated. Supported
here: ``.npy``/``.npz`` frame stacks ([t, h, w, c]) — the
decoded-frames interchange format — with the same sequence-record
contract downstream transforms consume.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .records import SequenceRecordReader
from .writable import NDArrayWritable


class CodecRecordReader(SequenceRecordReader):
    """One sequence record per file; each step is one frame
    (reference: CodecRecordReader with startFrame/numFrames/rate)."""

    def __init__(self, start_frame: int = 0, num_frames: int = -1,
                 rate: int = 1):
        self.start_frame = start_frame
        self.num_frames = num_frames
        self.rate = rate
        self.split = None

    def initialize(self, split):
        self.split = split
        self.reset()
        return self

    def _frames(self, loc) -> np.ndarray:
        loc = str(loc)
        if loc.endswith(".npy"):
            return np.load(loc)
        if loc.endswith(".npz"):
            z = np.load(loc)
            return z[list(z.files)[0]]
        raise NotImplementedError(
            f"codec decode for '{loc}': only .npy/.npz frame stacks "
            "are supported in this build (no ffmpeg in the image); "
            "pre-extract frames to numpy")

    def _make_iter(self):
        for loc in self.split.locations():
            f = self._frames(loc)
            end = (self.start_frame + self.num_frames * self.rate
                   if self.num_frames > 0 else len(f))
            sel = f[self.start_frame:end:self.rate]
            yield [[NDArrayWritable(fr)] for fr in sel]
