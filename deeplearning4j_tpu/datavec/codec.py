"""Video/frame-sequence modality (SURVEY.md V4: `datavec-data-codec`
— `CodecRecordReader` yielding per-frame sequences).

The reference decodes containers via JavaCPP-ffmpeg; this image has no
ffmpeg, so decode is done in-repo: a pure-python RIFF parser handles
``.avi`` containers with uncompressed (DIB/BGR) or MJPEG streams,
Pillow handles multi-frame ``.gif``/``.tiff``, and ``.npy``/``.npz``
frame stacks ([t, h, w, c]) remain the interchange format. Same
sequence-record contract downstream transforms consume.
"""
from __future__ import annotations

import io
import struct
from typing import List

import numpy as np

from .records import SequenceRecordReader
from .writable import NDArrayWritable


def _read_avi_frames(path: str) -> np.ndarray:
    """Minimal RIFF/AVI demuxer for the two codec-free stream types:
    biCompression==0 (raw bottom-up BGR) and MJPG (per-frame JPEG,
    decoded with Pillow). Returns [t, h, w, 3] uint8 RGB."""
    data = open(path, "rb").read()
    if data[:4] != b"RIFF" or data[8:12] != b"AVI ":
        raise ValueError(f"{path}: not an AVI (RIFF) file")

    frames: List[bytes] = []
    hdr = {}                # w, h, bits, comp of the VIDEO stream
    last_strh_type = [None]

    def walk(buf, off, end):
        while off + 8 <= end:
            fourcc = buf[off:off + 4]
            size = struct.unpack("<I", buf[off + 4:off + 8])[0]
            body = off + 8
            if fourcc in (b"RIFF", b"LIST"):
                walk(buf, body + 4, body + size)   # skip list type
            elif fourcc == b"strh" and size >= 4:
                last_strh_type[0] = buf[body:body + 4]
            elif fourcc == b"strf" and not hdr and size >= 40 and \
                    last_strh_type[0] == b"vids":
                # only the video stream's BITMAPINFOHEADER (an audio
                # stream's 40-byte WAVEFORMATEXTENSIBLE must not win)
                (_, w, h, _, bits, comp) = struct.unpack(
                    "<IiiHHI", buf[body:body + 20])
                hdr.update(w=w, h=h, bits=bits, comp=comp)
            elif fourcc[2:4] in (b"db", b"dc") and size > 0:
                frames.append(buf[body:body + size])
            off = body + size + (size & 1)         # chunks pad to even

    walk(data, 12, len(data))
    if not frames:
        raise ValueError(f"{path}: no video frames found")
    if not hdr:
        raise ValueError(f"{path}: no video stream header (strf) "
                         f"found — damaged AVI?")
    comp = hdr["comp"]
    mjpg = struct.unpack("<I", b"MJPG")[0]
    out = []
    if comp == 0:                           # raw DIB: bottom-up BGR(A)
        w, h = hdr["w"], abs(hdr["h"])
        bits = hdr["bits"]
        if bits not in (24, 32):
            raise NotImplementedError(
                f"{path}: raw AVI with biBitCount={bits} "
                f"(24/32 supported)")
        bpp = bits // 8
        flip = hdr["h"] > 0                 # positive height=bottom-up
        row = (w * bpp + 3) & ~3            # rows pad to 4 bytes
        for fb in frames:
            a = np.frombuffer(fb[:row * h], np.uint8)
            a = a.reshape(h, row)[:, :w * bpp].reshape(h, w, bpp)
            a = a[::-1] if flip else a
            out.append(a[..., 2::-1].copy())  # BGR(A) -> RGB
    elif comp == mjpg or comp == struct.unpack("<I", b"mjpg")[0]:
        try:
            from PIL import Image
        except Exception as e:              # pragma: no cover
            raise NotImplementedError(
                "MJPEG AVI needs Pillow for JPEG decode") from e
        for fb in frames:
            img = Image.open(io.BytesIO(fb)).convert("RGB")
            out.append(np.asarray(img))
    else:
        fourcc = struct.pack("<I", comp)
        raise NotImplementedError(
            f"{path}: AVI codec {fourcc!r} unsupported (raw DIB and "
            f"MJPG only in this build; no ffmpeg)")
    return np.stack(out)


def _read_pil_frames(path: str) -> np.ndarray:
    """Multi-frame GIF/TIFF via Pillow."""
    from PIL import Image, ImageSequence
    img = Image.open(path)
    return np.stack([np.asarray(f.convert("RGB"))
                     for f in ImageSequence.Iterator(img)])


class CodecRecordReader(SequenceRecordReader):
    """One sequence record per file; each step is one frame
    (reference: CodecRecordReader with startFrame/numFrames/rate)."""

    def __init__(self, start_frame: int = 0, num_frames: int = -1,
                 rate: int = 1):
        self.start_frame = start_frame
        self.num_frames = num_frames
        self.rate = rate
        self.split = None

    def initialize(self, split):
        self.split = split
        self.reset()
        return self

    def _frames(self, loc) -> np.ndarray:
        loc = str(loc)
        if loc.endswith(".npy"):
            return np.load(loc)
        if loc.endswith(".npz"):
            z = np.load(loc)
            return z[list(z.files)[0]]
        if loc.lower().endswith(".avi"):
            return _read_avi_frames(loc)
        if loc.lower().endswith((".gif", ".tif", ".tiff")):
            return _read_pil_frames(loc)
        raise NotImplementedError(
            f"codec decode for '{loc}': supported containers are "
            ".avi (raw/MJPEG), .gif/.tiff, and .npy/.npz frame "
            "stacks (no ffmpeg in this build)")

    def _make_iter(self):
        for loc in self.split.locations():
            f = self._frames(loc)
            end = (self.start_frame + self.num_frames * self.rate
                   if self.num_frames > 0 else len(f))
            sel = f[self.start_frame:end:self.rate]
            yield [[NDArrayWritable(fr)] for fr in sel]
