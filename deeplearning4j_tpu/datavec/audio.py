"""Audio modality (SURVEY.md V4: `datavec-data-audio` —
`WavFileRecordReader`, spectrogram/MFCC-style features).

Pure-numpy DSP (the reference wraps JavaCPP-ffmpeg; zero extra deps
here): WAV decode via the stdlib ``wave`` module, STFT power
spectrograms, log-mel filterbanks.
"""
from __future__ import annotations

import wave
from typing import Optional, Sequence

import numpy as np

from .records import RecordReader
from .writable import NDArrayWritable


def read_wav(path) -> tuple:
    """-> (samples float32 [-1,1] shape [n] or [n, ch], sample_rate)."""
    with wave.open(str(path), "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(n)
    if width == 2:
        a = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 1:
        a = (np.frombuffer(raw, np.uint8).astype(np.float32)
             - 128.0) / 128.0
    elif width == 4:
        a = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if ch > 1:
        a = a.reshape(-1, ch)
    return a, sr


def stft_power(x: np.ndarray, frame_length: int = 512,
               hop: int = 256) -> np.ndarray:
    """Power spectrogram [frames, frame_length//2+1] (Hann window)."""
    x = np.asarray(x, np.float32)
    if x.ndim > 1:
        x = x.mean(-1)                      # downmix
    if len(x) < frame_length:
        x = np.pad(x, (0, frame_length - len(x)))
    n_frames = 1 + (len(x) - frame_length) // hop
    win = np.hanning(frame_length).astype(np.float32)
    frames = np.stack([x[i * hop:i * hop + frame_length] * win
                       for i in range(n_frames)])
    return np.abs(np.fft.rfft(frames, axis=-1)) ** 2


def log_mel(power: np.ndarray, sample_rate: int, n_mels: int = 40,
            fmin: float = 0.0, fmax: Optional[float] = None
            ) -> np.ndarray:
    """Log-mel filterbank features [frames, n_mels]."""
    n_fft = (power.shape[-1] - 1) * 2
    fmax = fmax or sample_rate / 2

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    mels = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), n_mels + 2)
    freqs = mel_to_hz(mels)
    bins = np.floor((n_fft + 1) * freqs / sample_rate).astype(int)
    fb = np.zeros((n_mels, power.shape[-1]), np.float32)
    for m in range(1, n_mels + 1):
        l, c, r = bins[m - 1], bins[m], bins[m + 1]
        for k in range(l, c):
            if c > l:
                fb[m - 1, k] = (k - l) / (c - l)
        for k in range(c, r):
            if r > c:
                fb[m - 1, k] = (r - k) / (r - c)
    return np.log(power @ fb.T + 1e-10)


class WavFileRecordReader(RecordReader):
    """One record per WAV file: a single NDArrayWritable of features
    (reference: WavFileRecordReader / NativeAudioRecordReader)."""

    def __init__(self, features: str = "waveform",
                 frame_length: int = 512, hop: int = 256,
                 n_mels: int = 40):
        if features not in ("waveform", "spectrogram", "logmel"):
            raise ValueError(features)
        self.features = features
        self.frame_length = frame_length
        self.hop = hop
        self.n_mels = n_mels
        self.split = None

    def initialize(self, split):
        self.split = split
        self.reset()
        return self

    def _make_iter(self):
        for loc in self.split.locations():
            x, sr = read_wav(loc)
            if self.features != "waveform":
                p = stft_power(x, self.frame_length, self.hop)
                if self.features == "logmel":
                    p = log_mel(p, sr, self.n_mels)
                x = p
            yield [NDArrayWritable(np.asarray(x, np.float32))]
