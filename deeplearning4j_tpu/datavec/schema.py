"""Column schema (reference: ``org.datavec.api.transform.schema.Schema``,
SURVEY.md V2): typed column metadata that TransformProcess threads
through every operation so output types are known statically."""
from __future__ import annotations

import enum
from typing import List, Optional, Sequence


class ColumnType(enum.Enum):
    INTEGER = "Integer"
    LONG = "Long"
    DOUBLE = "Double"
    FLOAT = "Float"
    CATEGORICAL = "Categorical"
    STRING = "String"
    BOOLEAN = "Boolean"
    TIME = "Time"
    NDARRAY = "NDArray"


class ColumnMetaData:
    def __init__(self, name: str, ctype: ColumnType,
                 state_names: Optional[Sequence[str]] = None):
        self.name = name
        self.ctype = ctype
        self.state_names = list(state_names) if state_names else None

    def __repr__(self):
        extra = f", states={self.state_names}" if self.state_names else ""
        return f"ColumnMetaData({self.name!r}, {self.ctype.name}{extra})"


class Schema:
    """Immutable column list; build via ``Schema.Builder()``."""

    def __init__(self, columns: List[ColumnMetaData]):
        self.columns = list(columns)
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")

    # -- queries ---------------------------------------------------------
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def num_columns(self) -> int:
        return len(self.columns)

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(f"no column '{name}'; have {self.column_names()}")

    def column(self, name: str) -> ColumnMetaData:
        return self.columns[self.index_of(name)]

    def type_of(self, name: str) -> ColumnType:
        return self.column(name).ctype

    def __repr__(self):
        return "Schema(\n  " + "\n  ".join(map(repr, self.columns)) + \
            "\n)"

    class Builder:
        def __init__(self):
            self._cols: List[ColumnMetaData] = []

        def add_column_integer(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.INTEGER))
            return self

        def add_column_long(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.LONG))
            return self

        def add_column_double(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.DOUBLE))
            return self

        def add_column_float(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.FLOAT))
            return self

        def add_column_string(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.STRING))
            return self

        def add_column_boolean(self, *names):
            for n in names:
                self._cols.append(ColumnMetaData(n, ColumnType.BOOLEAN))
            return self

        def add_column_categorical(self, name, state_names):
            self._cols.append(ColumnMetaData(
                name, ColumnType.CATEGORICAL, state_names))
            return self

        def add_column_ndarray(self, name):
            self._cols.append(ColumnMetaData(name, ColumnType.NDARRAY))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)
