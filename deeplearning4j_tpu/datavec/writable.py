"""Writable value types (reference: ``org.datavec.api.writable.*``,
SURVEY.md V1).

The reference's Writables are Hadoop-style boxed values flowing through
record readers and transforms. Here they are thin typed boxes over
Python/numpy scalars — the type tags matter (schema validation,
transform dispatch), the boxing is cheap, and ``.to_python()`` /
``Writable.of()`` convert at the numpy boundary.
"""
from __future__ import annotations

import numpy as np


class Writable:
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def to_python(self):
        return self.value

    def to_double(self) -> float:
        return float(self.value)

    def to_int(self) -> int:
        return int(self.value)

    def __eq__(self, other):
        return (type(self) is type(other) and
                self.value == other.value)

    def __hash__(self):
        return hash((type(self).__name__, self.value))

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    @staticmethod
    def of(v) -> "Writable":
        """Best-effort boxing of a Python/numpy value."""
        if isinstance(v, Writable):
            return v
        if isinstance(v, (bool, np.bool_)):
            return BooleanWritable(bool(v))
        if isinstance(v, (int, np.integer)):
            return IntWritable(int(v))
        if isinstance(v, (float, np.floating)):
            return DoubleWritable(float(v))
        if isinstance(v, np.ndarray):
            return NDArrayWritable(v)
        if v is None:
            return NullWritable()
        return Text(str(v))


class IntWritable(Writable):
    def __init__(self, value: int):
        super().__init__(int(value))


class LongWritable(IntWritable):
    pass


class DoubleWritable(Writable):
    def __init__(self, value: float):
        super().__init__(float(value))


class FloatWritable(DoubleWritable):
    pass


class BooleanWritable(Writable):
    def __init__(self, value: bool):
        super().__init__(bool(value))

    def to_double(self):
        return 1.0 if self.value else 0.0


class Text(Writable):
    def __init__(self, value: str):
        super().__init__(str(value))

    def to_double(self):
        return float(self.value)

    def to_int(self):
        return int(float(self.value))


class NullWritable(Writable):
    def __init__(self):
        super().__init__(None)

    def to_double(self):
        return float("nan")


class NDArrayWritable(Writable):
    """Tensor-valued column (reference: image/sequence features)."""

    def __init__(self, value):
        super().__init__(np.asarray(value))

    def __eq__(self, other):
        return (type(self) is type(other) and
                np.array_equal(self.value, other.value))

    def __hash__(self):
        return id(self)
