"""Group-by reduction and two-table joins (SURVEY.md V2).

Reference parity: ``org.datavec.api.transform.reduce.Reducer`` (group
records by key column(s), aggregate every other column with a per-column
``ReduceOp``) and ``org.datavec.api.transform.join.Join``
(Inner/LeftOuter/RightOuter/FullOuter joins of two schema'd record
sets). The reference executes these on Spark (`datavec-spark`) or
locally (`datavec-local`); here the local executor covers both roles —
cluster-scale ETL belongs to the host data pipeline, not the TPU.
"""
from __future__ import annotations

import enum
import math
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.datavec.schema import (ColumnMetaData, ColumnType,
                                               Schema)


class ReduceOp(enum.Enum):
    """Reference: org.datavec.api.transform.ops.AggregableReductionUtils
    op set."""
    MIN = "min"
    MAX = "max"
    SUM = "sum"
    MEAN = "mean"
    STDEV = "stdev"
    COUNT = "count"
    COUNT_UNIQUE = "count_unique"
    FIRST = "first"
    LAST = "last"
    RANGE = "range"


_NUMERIC = (ColumnType.INTEGER, ColumnType.LONG, ColumnType.DOUBLE,
            ColumnType.FLOAT)


def _reduce_values(op: ReduceOp, values: list):
    if op is ReduceOp.COUNT:
        return len(values)
    if op is ReduceOp.COUNT_UNIQUE:
        return len(set(values))
    if op is ReduceOp.FIRST:
        return values[0]
    if op is ReduceOp.LAST:
        return values[-1]
    # MIN/MAX/SUM keep exact int arithmetic on integer columns (the
    # declared output type is the input type; float() would lose
    # precision above 2^53)
    if op is ReduceOp.MIN:
        return min(values)
    if op is ReduceOp.MAX:
        return max(values)
    if op is ReduceOp.SUM:
        return sum(values)
    nums = [float(v) for v in values]
    if op is ReduceOp.MEAN:
        return sum(nums) / len(nums)
    if op is ReduceOp.RANGE:
        return max(nums) - min(nums)
    if op is ReduceOp.STDEV:
        m = sum(nums) / len(nums)
        if len(nums) < 2:
            return 0.0
        return math.sqrt(sum((x - m) ** 2 for x in nums) /
                         (len(nums) - 1))
    raise ValueError(op)


_TYPE_AGNOSTIC = (ReduceOp.COUNT, ReduceOp.COUNT_UNIQUE,
                  ReduceOp.FIRST, ReduceOp.LAST)


def _out_type(op: ReduceOp, in_type: ColumnType,
              column: str) -> ColumnType:
    if op in (ReduceOp.COUNT, ReduceOp.COUNT_UNIQUE):
        return ColumnType.LONG
    if op in (ReduceOp.FIRST, ReduceOp.LAST):
        return in_type
    if in_type not in _NUMERIC:
        raise ValueError(
            f"ReduceOp.{op.name} on non-numeric column '{column}' "
            f"({in_type.name}); tag it with first/last/count_columns")
    if op in (ReduceOp.MEAN, ReduceOp.STDEV, ReduceOp.RANGE):
        return ColumnType.DOUBLE
    return in_type


class Reducer:
    """Group-by aggregation (reference: transform.reduce.Reducer).

    Reducer.Builder(default_op).key_columns("k")
        .sum_columns("a").mean_columns("b").build()
    """

    def __init__(self, keys: List[str], default_op: ReduceOp,
                 column_ops: Dict[str, ReduceOp]):
        self.keys = keys
        self.default_op = default_op
        self.column_ops = column_ops

    class Builder:
        def __init__(self, default_op: ReduceOp = ReduceOp.SUM):
            self._default = default_op
            self._keys: List[str] = []
            self._ops: Dict[str, ReduceOp] = {}

        def key_columns(self, *names: str) -> "Reducer.Builder":
            self._keys.extend(names)
            return self

        def _tag(self, op: ReduceOp, names) -> "Reducer.Builder":
            for n in names:
                self._ops[n] = op
            return self

        def min_columns(self, *n):
            return self._tag(ReduceOp.MIN, n)

        def max_columns(self, *n):
            return self._tag(ReduceOp.MAX, n)

        def sum_columns(self, *n):
            return self._tag(ReduceOp.SUM, n)

        def mean_columns(self, *n):
            return self._tag(ReduceOp.MEAN, n)

        def stdev_columns(self, *n):
            return self._tag(ReduceOp.STDEV, n)

        def count_columns(self, *n):
            return self._tag(ReduceOp.COUNT, n)

        def count_unique_columns(self, *n):
            return self._tag(ReduceOp.COUNT_UNIQUE, n)

        def first_columns(self, *n):
            return self._tag(ReduceOp.FIRST, n)

        def last_columns(self, *n):
            return self._tag(ReduceOp.LAST, n)

        def range_columns(self, *n):
            return self._tag(ReduceOp.RANGE, n)

        def build(self) -> "Reducer":
            if not self._keys:
                raise ValueError("Reducer needs key columns")
            return Reducer(self._keys, self._default, dict(self._ops))

    # ------------------------------------------------------------------
    def transform_schema(self, schema: Schema) -> Schema:
        cols = []
        for name in schema.column_names():
            if name in self.keys:
                cols.append(ColumnMetaData(name, schema.type_of(name)))
            else:
                op = self.column_ops.get(name, self.default_op)
                cols.append(ColumnMetaData(
                    f"{op.value}({name})",
                    _out_type(op, schema.type_of(name), name)))
        return Schema(cols)

    def execute(self, schema: Schema,
                records: Sequence[Sequence]) -> List[List]:
        self.transform_schema(schema)   # validates op/column-type combos
        names = schema.column_names()
        key_idx = [schema.index_of(k) for k in self.keys]
        groups: Dict[tuple, List[Sequence]] = {}
        order: List[tuple] = []
        for r in records:
            k = tuple(r[i] for i in key_idx)
            if k not in groups:
                groups[k] = []
                order.append(k)
            groups[k].append(r)
        out = []
        for k in order:
            rows = groups[k]
            rec = []
            for i, name in enumerate(names):
                if name in self.keys:
                    rec.append(rows[0][i])
                else:
                    op = self.column_ops.get(name, self.default_op)
                    rec.append(_reduce_values(op, [r[i] for r in rows]))
            out.append(rec)
        return out


class JoinType(enum.Enum):
    INNER = "inner"
    LEFT_OUTER = "left_outer"
    RIGHT_OUTER = "right_outer"
    FULL_OUTER = "full_outer"


class Join:
    """Two-table join on key columns (reference: transform.join.Join).

    Join.Builder(JoinType.INNER).set_join_columns("k")
        .set_schemas(left, right).build()
    then ``join.execute(left_records, right_records)``.
    """

    def __init__(self, join_type: JoinType, keys: List[str],
                 left: Schema, right: Schema):
        self.join_type = join_type
        self.keys = keys
        self.left = left
        self.right = right

    class Builder:
        def __init__(self, join_type: JoinType = JoinType.INNER):
            self._type = join_type
            self._keys: List[str] = []
            self._left: Optional[Schema] = None
            self._right: Optional[Schema] = None

        def set_join_columns(self, *names: str) -> "Join.Builder":
            self._keys.extend(names)
            return self

        def set_schemas(self, left: Schema,
                        right: Schema) -> "Join.Builder":
            self._left, self._right = left, right
            return self

        def build(self) -> "Join":
            if not self._keys or self._left is None:
                raise ValueError("Join needs key columns and schemas")
            return Join(self._type, self._keys, self._left, self._right)

    # ------------------------------------------------------------------
    def output_schema(self) -> Schema:
        cols = [ColumnMetaData(n, self.left.type_of(n))
                for n in self.left.column_names()]
        for n in self.right.column_names():
            if n not in self.keys:
                cols.append(ColumnMetaData(n, self.right.type_of(n)))
        return Schema(cols)

    def execute(self, left_records: Sequence[Sequence],
                right_records: Sequence[Sequence]) -> List[List]:
        lk = [self.left.index_of(k) for k in self.keys]
        rk = [self.right.index_of(k) for k in self.keys]
        r_other = [i for i, n in enumerate(self.right.column_names())
                   if n not in self.keys]
        l_width = self.left.num_columns()
        r_width = len(r_other)

        rindex: Dict[tuple, List[Sequence]] = {}
        for r in right_records:
            rindex.setdefault(tuple(r[i] for i in rk), []).append(r)

        out: List[List] = []
        matched_right: set = set()
        for l in left_records:
            k = tuple(l[i] for i in lk)
            matches = rindex.get(k)
            if matches:
                matched_right.add(k)
                for r in matches:
                    out.append(list(l) + [r[i] for i in r_other])
            elif self.join_type in (JoinType.LEFT_OUTER,
                                    JoinType.FULL_OUTER):
                out.append(list(l) + [None] * r_width)
        if self.join_type in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
            for k, rows in rindex.items():
                if k in matched_right:
                    continue
                for r in rows:
                    # key values land in their left-schema positions
                    left_part = [None] * l_width
                    for kn, kv in zip(self.keys, k):
                        left_part[self.left.index_of(kn)] = kv
                    out.append(left_part + [r[i] for i in r_other])
        return out
