"""Image loading + augmentation (reference: ``datavec-data-image`` —
``NativeImageLoader`` (JavaCPP-OpenCV), ``ImageRecordReader``
(label-from-path), ``ImageTransform`` augmentations; SURVEY.md V3 —
the ImageNet input path for ResNet-50).

Decode uses Pillow when available (PNG/JPEG/...); `.npy`/`.ppm` load
without it. Augmentations are pure-numpy HWC float32 transforms
composable via :class:`PipelineImageTransform` — host-side work that
overlaps device compute through the async prefetch iterator
(datasets.iterators.AsyncDataSetIterator).
"""
from __future__ import annotations

import os
import random as _random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datavec.records import RecordReader
from deeplearning4j_tpu.datavec.split import InputSplit
from deeplearning4j_tpu.datavec.writable import (IntWritable,
                                                 NDArrayWritable)

try:
    from PIL import Image as _PILImage
    _HAS_PIL = True
except Exception:                                  # pragma: no cover
    _HAS_PIL = False


class ImageLoader:
    """Decode + resize to HWC float32 (reference: NativeImageLoader;
    NHWC here — XLA:TPU's native conv layout, the reference's NCHW
    exists only at import boundaries).

    INTENTIONAL divergence (ADVICE.md r5), BY DEFAULT: file inputs
    decoded via Pillow resize with Pillow's antialiased BILINEAR (plus
    JPEG draft mode), while ndarray/`.npy` inputs resize through the
    half-pixel numpy ``_resize_bilinear`` below — the same logical
    image can yield slightly different pixels depending on input form.
    The PIL path is the default because it is the throughput path
    (GIL-released SIMD resize, 147 -> >1k img/s on the ETL bench) and
    antialiased downscale is the *better* eval-time convention.

    ``exact_resize=True`` removes the divergence: PIL decodes at the
    image's native size (no draft-mode DCT scaling, no Pillow resize)
    and the array goes through the SAME half-pixel numpy
    ``_resize_bilinear`` as ndarray/``.npy`` inputs, so a file-fed and
    an array-fed pipeline produce bit-identical pixels — at the numpy
    path's (slower, non-antialiased) throughput."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 exact_resize: bool = False):
        self.h, self.w, self.c = int(height), int(width), int(channels)
        self.exact_resize = bool(exact_resize)

    def load(self, path_or_array) -> np.ndarray:
        a = self._decode(path_or_array)
        a = self._to_channels(a)
        if a.shape[:2] != (self.h, self.w):
            a = _resize_bilinear(a, self.h, self.w)
        return a.astype(np.float32)

    def _decode(self, src) -> np.ndarray:
        if isinstance(src, np.ndarray):
            return src
        path = str(src)
        if path.endswith(".npy"):
            return np.load(path)
        if _HAS_PIL:
            with _PILImage.open(path) as im:
                # JPEG draft mode: decode directly at the nearest
                # 1/2 / 1/4 / 1/8 DCT scale >= target — the decoder
                # skips most of the IDCT work on big downscales
                # (skipped under exact_resize: the scaled decode feeds
                # different pixels into the resize than an array path
                # that starts from the full-size image)
                if im.format == "JPEG" and not self.exact_resize:
                    im.draft("RGB" if self.c == 3 else "L",
                             (self.w, self.h))
                im = im.convert("RGB" if self.c == 3 else "L")
                if self.exact_resize:
                    # native-size decode; load() routes the array
                    # through _resize_bilinear like any ndarray input
                    return np.asarray(im)
                if im.size != (self.w, self.h):
                    # Pillow's C resize (GIL-released, SIMD): feeder
                    # THREADS scale, unlike the numpy fallback below —
                    # measured 147 -> >1k img/s on the ETL bench
                    im = im.resize((self.w, self.h),
                                   _PILImage.BILINEAR)
                return np.asarray(im)
        raise RuntimeError(f"cannot decode {path}: Pillow unavailable "
                           "(use .npy inputs)")

    def _to_channels(self, a: np.ndarray) -> np.ndarray:
        if a.ndim == 2:
            a = a[:, :, None]
        if a.shape[2] != self.c:
            if self.c == 1:
                a = a.mean(axis=2, keepdims=True)
            elif self.c == 3 and a.shape[2] == 1:
                a = np.repeat(a, 3, axis=2)
            else:
                raise ValueError(f"cannot map {a.shape[2]} channels "
                                 f"to {self.c}")
        return a


def _resize_bilinear(a: np.ndarray, h: int, w: int) -> np.ndarray:
    """Pure-numpy bilinear resize, HWC."""
    H, W = a.shape[:2]
    ys = (np.arange(h) + 0.5) * H / h - 0.5
    xs = (np.arange(w) + 0.5) * W / w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    a = a.astype(np.float32)
    top = a[y0][:, x0] * (1 - wx) + a[y0][:, x1] * wx
    bot = a[y1][:, x0] * (1 - wx) + a[y1][:, x1] * wx
    return top * (1 - wy) + bot * wy


# -- transforms --------------------------------------------------------------
class ImageTransform:
    """HWC float32 -> HWC float32 (reference: ImageTransform chain)."""

    def __init__(self, random_seed: Optional[int] = None):
        self.rng = _random.Random(random_seed)

    def transform(self, img: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def __call__(self, img):
        return self.transform(img)


class ResizeImageTransform(ImageTransform):
    def __init__(self, height: int, width: int, **kw):
        super().__init__(**kw)
        self.h, self.w = height, width

    def transform(self, img):
        return _resize_bilinear(img, self.h, self.w)


class FlipImageTransform(ImageTransform):
    """mode: 0 = vertical, 1 = horizontal, -1 = both, None = random
    choice each call (reference: FlipImageTransform/OpenCV flip)."""

    def __init__(self, mode: Optional[int] = 1, **kw):
        super().__init__(**kw)
        self.mode = mode

    def transform(self, img):
        m = self.mode
        if m is None:
            m = self.rng.choice([0, 1, -1])
        if m in (1, -1):
            img = img[:, ::-1]
        if m in (0, -1):
            img = img[::-1]
        return np.ascontiguousarray(img)


class RandomCropTransform(ImageTransform):
    def __init__(self, height: int, width: int, **kw):
        super().__init__(**kw)
        self.h, self.w = height, width

    def transform(self, img):
        H, W = img.shape[:2]
        if H < self.h or W < self.w:
            img = _resize_bilinear(img, max(H, self.h), max(W, self.w))
            H, W = img.shape[:2]
        y = self.rng.randint(0, H - self.h) if H > self.h else 0
        x = self.rng.randint(0, W - self.w) if W > self.w else 0
        return img[y:y + self.h, x:x + self.w]


class CropImageTransform(ImageTransform):
    """Center crop by margins (reference: CropImageTransform)."""

    def __init__(self, crop_top: int, crop_left: int, crop_bottom: int,
                 crop_right: int, **kw):
        super().__init__(**kw)
        self.t, self.l = crop_top, crop_left
        self.b, self.r = crop_bottom, crop_right

    def transform(self, img):
        H, W = img.shape[:2]
        return img[self.t:H - self.b or None,
                   self.l:W - self.r or None]


class RotateImageTransform(ImageTransform):
    """Rotate by angle degrees (bilinear, reflect-free zero fill)."""

    def __init__(self, angle: float, **kw):
        super().__init__(**kw)
        self.angle = angle

    def transform(self, img):
        th = np.deg2rad(self.angle)
        H, W = img.shape[:2]
        cy, cx = (H - 1) / 2, (W - 1) / 2
        yy, xx = np.meshgrid(np.arange(H), np.arange(W),
                             indexing="ij")
        ys = cy + (yy - cy) * np.cos(th) - (xx - cx) * np.sin(th)
        xs = cx + (yy - cy) * np.sin(th) + (xx - cx) * np.cos(th)
        y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
        y1 = np.clip(y0 + 1, 0, H - 1)
        x1 = np.clip(x0 + 1, 0, W - 1)
        wy = np.clip(ys - y0, 0, 1)[..., None]
        wx = np.clip(xs - x0, 0, 1)[..., None]
        out = (img[y0, x0] * (1 - wy) * (1 - wx) +
               img[y1, x0] * wy * (1 - wx) +
               img[y0, x1] * (1 - wy) * wx +
               img[y1, x1] * wy * wx)
        inside = ((ys >= 0) & (ys <= H - 1) &
                  (xs >= 0) & (xs <= W - 1))[..., None]
        return np.where(inside, out, 0.0).astype(np.float32)


class ColorConversionTransform(ImageTransform):
    """Grayscale conversion kept channel-shaped."""

    def transform(self, img):
        if img.shape[2] == 1:
            return img
        g = (0.299 * img[..., 0] + 0.587 * img[..., 1] +
             0.114 * img[..., 2])
        return np.repeat(g[..., None], img.shape[2], axis=2)


class BrightnessContrastTransform(ImageTransform):
    def __init__(self, alpha: float = 1.0, beta: float = 0.0, **kw):
        super().__init__(**kw)
        self.alpha, self.beta = alpha, beta

    def transform(self, img):
        return img * self.alpha + self.beta


class PipelineImageTransform(ImageTransform):
    """Chain with optional per-stage probabilities (reference:
    PipelineImageTransform)."""

    def __init__(self, transforms: Sequence[ImageTransform],
                 probabilities: Optional[Sequence[float]] = None,
                 shuffle: bool = False, **kw):
        super().__init__(**kw)
        self.transforms = list(transforms)
        self.probs = list(probabilities) if probabilities else None

    def transform(self, img):
        for i, t in enumerate(self.transforms):
            if self.probs is None or \
                    self.rng.random() < self.probs[i]:
                img = t.transform(img)
        return img


# -- reader -------------------------------------------------------------------
class ParentPathLabelGenerator:
    """Label = name of the parent directory (reference:
    io.labels.ParentPathLabelGenerator)."""

    def label_for(self, path: str) -> str:
        return os.path.basename(os.path.dirname(str(path)))


class ImageRecordReader(RecordReader):
    """[NDArrayWritable(image), IntWritable(label)] per file
    (reference: ImageRecordReader)."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 label_generator=None,
                 image_transform: Optional[ImageTransform] = None):
        self.loader = ImageLoader(height, width, channels)
        self.label_gen = label_generator
        self.image_transform = image_transform
        self.labels: List[str] = []

    def initialize(self, split: InputSplit):
        self.split = split
        if self.label_gen is not None:
            self.labels = sorted({self.label_gen.label_for(p)
                                  for p in split.locations()})
        self.reset()
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def _make_iter(self):
        for loc in self.split.locations():
            img = self.loader.load(loc)
            if self.image_transform is not None:
                img = self.image_transform.transform(img)
            rec = [NDArrayWritable(img)]
            if self.label_gen is not None:
                rec.append(IntWritable(self.labels.index(
                    self.label_gen.label_for(loc))))
            yield rec
