"""TransformProcess (reference:
``org.datavec.api.transform.TransformProcess`` + ``transform.*`` op
classes, SURVEY.md V2): a schema-typed DAG of record operations built
once, executed per record (streaming) or over a whole collection
(`LocalTransformExecutor` — reference ``datavec-local``).

Each step is (schema_fn, record_fn): schema_fn threads column metadata
(so the final schema is known before any data flows), record_fn maps a
record (or filters it by returning None).
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datavec.schema import (ColumnMetaData, ColumnType,
                                               Schema)
from deeplearning4j_tpu.datavec.writable import (DoubleWritable,
                                                 IntWritable, Text,
                                                 Writable)

MathOp = {
    "Add": lambda a, b: a + b,
    "Subtract": lambda a, b: a - b,
    "Multiply": lambda a, b: a * b,
    "Divide": lambda a, b: a / b,
    "Modulus": lambda a, b: a % b,
    "ReverseSubtract": lambda a, b: b - a,
    "ReverseDivide": lambda a, b: b / a,
    "ScalarMin": min,
    "ScalarMax": max,
}

MathFunction = {
    "ABS": abs, "CEIL": math.ceil, "FLOOR": math.floor,
    "EXP": math.exp, "LOG": math.log, "LOG2": lambda v: math.log2(v),
    "SQRT": math.sqrt, "SIN": math.sin, "COS": math.cos,
    "TAN": math.tan, "SIGNUM": lambda v: (v > 0) - (v < 0),
}


class TransformProcess:
    """Built via ``TransformProcess.Builder(initial_schema)``."""

    def __init__(self, initial_schema: Schema, steps):
        self.initial_schema = initial_schema
        self.steps = steps          # list of (name, schema_fn, rec_fn)
        s = initial_schema
        for _, schema_fn, _ in steps:
            s = schema_fn(s)
        self.final_schema = s

    def get_final_schema(self) -> Schema:
        return self.final_schema

    def execute_record(self, record: Sequence[Writable]):
        """Run one record through every step; None = filtered out."""
        rec = list(record)
        schema = self.initial_schema
        for _, schema_fn, rec_fn in self.steps:
            rec = rec_fn(schema, rec)
            schema = schema_fn(schema)
            if rec is None:
                return None
        return rec

    def execute(self, records) -> List[List[Writable]]:
        """Collection execution (reference: LocalTransformExecutor
        .execute)."""
        out = []
        for r in records:
            t = self.execute_record(r)
            if t is not None:
                out.append(t)
        return out

    class Builder:
        def __init__(self, initial_schema: Schema):
            self.schema0 = initial_schema
            self.steps = []

        def _add(self, name, schema_fn, rec_fn):
            self.steps.append((name, schema_fn, rec_fn))
            return self

        # -- column structure ops ---------------------------------------
        def remove_columns(self, *names):
            names = set(names)

            def sf(s):
                return Schema([c for c in s.columns
                               if c.name not in names])

            def rf(s, r):
                keep = [i for i, c in enumerate(s.columns)
                        if c.name not in names]
                return [r[i] for i in keep]

            return self._add(f"remove{sorted(names)}", sf, rf)

        def remove_all_columns_except_for(self, *names):
            keep_names = set(names)

            def sf(s):
                return Schema([c for c in s.columns
                               if c.name in keep_names])

            def rf(s, r):
                keep = [i for i, c in enumerate(s.columns)
                        if c.name in keep_names]
                return [r[i] for i in keep]

            return self._add(f"keep{sorted(keep_names)}", sf, rf)

        def rename_column(self, old: str, new: str):
            def sf(s):
                return Schema([ColumnMetaData(new, c.ctype,
                                              c.state_names)
                               if c.name == old else c
                               for c in s.columns])

            return self._add(f"rename {old}->{new}", sf,
                             lambda s, r: list(r))

        def reorder_columns(self, *names):
            def sf(s):
                return Schema([s.column(n) for n in names])

            def rf(s, r):
                return [r[s.index_of(n)] for n in names]

            return self._add(f"reorder{list(names)}", sf, rf)

        def duplicate_column(self, src: str, new: str):
            def sf(s):
                c = s.column(src)
                return Schema(s.columns +
                              [ColumnMetaData(new, c.ctype,
                                              c.state_names)])

            def rf(s, r):
                return list(r) + [r[s.index_of(src)]]

            return self._add(f"dup {src}->{new}", sf, rf)

        # -- type conversions -------------------------------------------
        def string_to_categorical(self, name: str, state_names):
            states = list(state_names)

            def sf(s):
                return Schema([ColumnMetaData(name,
                                              ColumnType.CATEGORICAL,
                                              states)
                               if c.name == name else c
                               for c in s.columns])

            def rf(s, r):
                i = s.index_of(name)
                v = str(r[i].to_python())
                if v not in states:
                    raise ValueError(f"value '{v}' not in categorical "
                                     f"states {states} for '{name}'")
                return r[:i] + [Text(v)] + r[i + 1:]

            return self._add(f"toCategorical {name}", sf, rf)

        def categorical_to_integer(self, *names):
            todo = set(names)

            def sf(s):
                return Schema([ColumnMetaData(c.name, ColumnType.INTEGER)
                               if c.name in todo else c
                               for c in s.columns])

            def rf(s, r):
                r = list(r)
                for n in todo:
                    i = s.index_of(n)
                    states = s.column(n).state_names
                    r[i] = IntWritable(states.index(
                        str(r[i].to_python())))
                return r

            return self._add(f"cat->int {sorted(todo)}", sf, rf)

        def categorical_to_one_hot(self, *names):
            todo = list(names)

            def sf(s):
                cols = []
                for c in s.columns:
                    if c.name in todo:
                        cols.extend(ColumnMetaData(
                            f"{c.name}[{st}]", ColumnType.INTEGER)
                            for st in c.state_names)
                    else:
                        cols.append(c)
                return Schema(cols)

            def rf(s, r):
                out = []
                for c, v in zip(s.columns, r):
                    if c.name in todo:
                        val = str(v.to_python())
                        out.extend(IntWritable(1 if st == val else 0)
                                   for st in c.state_names)
                    else:
                        out.append(v)
                return out

            return self._add(f"oneHot {todo}", sf, rf)

        def convert_to_double(self, *names):
            todo = set(names)

            def sf(s):
                return Schema([ColumnMetaData(c.name, ColumnType.DOUBLE)
                               if c.name in todo else c
                               for c in s.columns])

            def rf(s, r):
                return [DoubleWritable(v.to_double())
                        if c.name in todo else v
                        for c, v in zip(s.columns, r)]

            return self._add(f"toDouble {sorted(todo)}", sf, rf)

        def convert_to_integer(self, *names):
            todo = set(names)

            def sf(s):
                return Schema([ColumnMetaData(c.name, ColumnType.INTEGER)
                               if c.name in todo else c
                               for c in s.columns])

            def rf(s, r):
                return [IntWritable(v.to_int())
                        if c.name in todo else v
                        for c, v in zip(s.columns, r)]

            return self._add(f"toInt {sorted(todo)}", sf, rf)

        def convert_to_string(self, *names):
            todo = set(names)

            def sf(s):
                return Schema([ColumnMetaData(c.name, ColumnType.STRING)
                               if c.name in todo else c
                               for c in s.columns])

            def rf(s, r):
                return [Text(str(v.to_python()))
                        if c.name in todo else v
                        for c, v in zip(s.columns, r)]

            return self._add(f"toString {sorted(todo)}", sf, rf)

        # -- math ---------------------------------------------------------
        def double_math_op(self, name: str, op: str, scalar: float):
            f = MathOp[op]

            def rf(s, r):
                i = s.index_of(name)
                return (r[:i] +
                        [DoubleWritable(f(r[i].to_double(), scalar))] +
                        r[i + 1:])

            return self._add(f"{op}({name},{scalar})",
                             lambda s: s, rf)

        def double_math_function(self, name: str, fn: str):
            f = MathFunction[fn]

            def rf(s, r):
                i = s.index_of(name)
                return (r[:i] +
                        [DoubleWritable(f(r[i].to_double()))] +
                        r[i + 1:])

            return self._add(f"{fn}({name})", lambda s: s, rf)

        def integer_math_op(self, name: str, op: str, scalar: int):
            f = MathOp[op]

            def rf(s, r):
                i = s.index_of(name)
                return (r[:i] +
                        [IntWritable(int(f(r[i].to_int(), scalar)))] +
                        r[i + 1:])

            return self._add(f"{op}({name},{scalar})",
                             lambda s: s, rf)

        # -- filters ------------------------------------------------------
        def filter(self, predicate: Callable[[Schema, list], bool]):
            """Drop records where predicate(schema, record) is True
            (reference: FilterOp semantics — condition true = remove)."""

            def rf(s, r):
                return None if predicate(s, r) else r

            return self._add("filter", lambda s: s, rf)

        def filter_invalid_values(self, *names):
            todo = set(names)

            def bad(s, r):
                for n in todo:
                    v = r[s.index_of(n)]
                    try:
                        d = v.to_double()
                    except (TypeError, ValueError):
                        return True
                    if d != d:          # NaN
                        return True
                return False

            return self.filter(bad)

        def conditional_replace_value_transform(
                self, name: str, new_value,
                condition: Callable[[Writable], bool]):
            def rf(s, r):
                i = s.index_of(name)
                if condition(r[i]):
                    return (r[:i] + [Writable.of(new_value)] +
                            r[i + 1:])
                return r

            return self._add(f"condReplace {name}", lambda s: s, rf)

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema0, self.steps)


class LocalTransformExecutor:
    """Reference: ``org.datavec.local.transforms
    .LocalTransformExecutor.execute``."""

    @staticmethod
    def execute(records, tp: TransformProcess):
        return tp.execute(records)

    @staticmethod
    def execute_to_numpy(records, tp: TransformProcess) -> np.ndarray:
        rows = LocalTransformExecutor.execute(records, tp)
        return np.array([[w.to_double() for w in r] for r in rows])


# ---------------------------------------------------------------------------
# Sequence operations (reference: TransformProcess.convertToSequence /
# trimSequence / offsetSequence, and reduce.Reducer over windows —
# SURVEY.md V2 "sequence" ops)
# ---------------------------------------------------------------------------
def convert_to_sequence(schema, records, key_column: str,
                        sort_column=None):
    """Group flat records into per-key sequences (reference:
    convertToSequence(keyColumn, comparator)); each sequence is sorted
    by ``sort_column`` when given, else kept in input order. Returns
    (keys, sequences) with keys in first-appearance order."""
    ki = schema.index_of(key_column)
    si = schema.index_of(sort_column) if sort_column else None
    groups, order = {}, []
    for r in records:
        k = r[ki]
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(list(r))
    seqs = []
    for k in order:
        rows = groups[k]
        if si is not None:
            rows = sorted(rows, key=lambda r: r[si])
        seqs.append(rows)
    return order, seqs


def trim_sequence(sequences, max_length: int, from_start: bool = True):
    """Cap sequence length (reference: trimSequence): keep the first
    (``from_start``) or last ``max_length`` steps."""
    if max_length <= 0:
        return [[] for _ in sequences]
    if from_start:
        return [s[:max_length] for s in sequences]
    return [s[-max_length:] for s in sequences]


def offset_sequence(sequences, offset: int):
    """Shift steps off the front (positive) or back (negative)
    (reference: offsetSequence with OperationType.TrimSequence)."""
    if offset >= 0:
        return [s[offset:] for s in sequences]
    return [s[:offset] for s in sequences]


def reduce_sequence_by_window(schema, sequence, window: int,
                              reducer, stride=None,
                              include_partial: bool = True):
    """Tumbling/strided windows over one sequence, each reduced to one
    record by a :class:`deeplearning4j_tpu.datavec.reduce_join.Reducer`
    (reference: reduceSequenceByWindow(reducer, TimeWindowFunction)).
    The trailing partial window is kept by default
    (``include_partial=False`` drops it). Returns the reduced
    sequence."""
    stride = stride or window
    out = []
    s = 0
    while s < len(sequence):
        win = sequence[s:s + window]
        if len(win) < window and not include_partial:
            break
        # Reducer.execute validates op/column-type combos and reduces
        # per column; one window == one group (keys constant within it)
        out.extend(reducer.execute(schema, win))
        s += stride
    return out
