"""Text vectorizers (SURVEY.md V4: `datavec-data-nlp` —
`BagOfWordsVectorizer`, `TfidfVectorizer`)."""
from __future__ import annotations

import math
from collections import Counter
from typing import Dict, Iterable, List, Optional

import numpy as np


class BagOfWordsVectorizer:
    """Count vectors over a fitted vocabulary (reference: same name;
    tokenization delegates to the nlp tokenizer factory)."""

    def __init__(self, tokenizer_factory=None,
                 min_word_frequency: int = 1,
                 max_vocab: Optional[int] = None):
        if tokenizer_factory is None:
            from ..nlp.tokenization import DefaultTokenizerFactory
            tokenizer_factory = DefaultTokenizerFactory()
        self.tf = tokenizer_factory
        self.min_word_frequency = min_word_frequency
        self.max_vocab = max_vocab
        self.vocab: Dict[str, int] = {}

    def _tokens(self, text: str) -> List[str]:
        return self.tf.create(text).get_tokens()

    def fit(self, corpus: Iterable[str]) -> "BagOfWordsVectorizer":
        c = Counter()
        for doc in corpus:
            c.update(self._tokens(doc))
        items = [(w, n) for w, n in c.most_common()
                 if n >= self.min_word_frequency]
        if self.max_vocab:
            items = items[:self.max_vocab]
        self.vocab = {w: i for i, (w, _) in enumerate(items)}
        return self

    def transform(self, text: str) -> np.ndarray:
        v = np.zeros(len(self.vocab), np.float32)
        for t in self._tokens(text):
            i = self.vocab.get(t)
            if i is not None:
                v[i] += 1.0
        return v

    def fit_transform(self, corpus) -> np.ndarray:
        corpus = list(corpus)
        self.fit(corpus)
        return np.stack([self.transform(d) for d in corpus])


class TfidfVectorizer(BagOfWordsVectorizer):
    """TF-IDF with smoothed idf = ln((1+N)/(1+df)) + 1 (reference:
    TfidfVectorizer over lucene; same weighting family)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.idf: Optional[np.ndarray] = None

    def fit(self, corpus: Iterable[str]) -> "TfidfVectorizer":
        corpus = list(corpus)
        super().fit(corpus)
        df = np.zeros(len(self.vocab), np.float64)
        for doc in corpus:
            for i in {self.vocab[t] for t in self._tokens(doc)
                      if t in self.vocab}:
                df[i] += 1
        n = len(corpus)
        self.idf = (np.log((1.0 + n) / (1.0 + df)) + 1.0) \
            .astype(np.float32)
        return self

    def transform(self, text: str) -> np.ndarray:
        counts = super().transform(text)
        total = counts.sum()
        tf = counts / total if total else counts
        return tf * self.idf
