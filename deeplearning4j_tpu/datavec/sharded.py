"""Distributed ETL seam: deterministic per-process sharding of a
RecordReader/TransformProcess pipeline.

Reference role: ``datavec-spark``'s distributed transform execution +
``dl4j-spark``'s ``RDD<DataSet>`` partitioning (SURVEY.md V2/P4).  On
a TPU pod there is no Spark: every host process reads the SAME input
(shared filesystem, the pod norm), takes a deterministic contiguous
shard of it, and feeds :class:`SharedTrainingMaster`'s global-batch
assembly (``jax.make_array_from_process_local_data``).  The shard
boundaries depend only on (record count, process count), so a
restarted or re-run job sees identical partitions — the property
Spark gets from deterministic RDD lineage.
"""
from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

log = logging.getLogger("deeplearning4j_tpu")


class ShardedDataSetIterator(DataSetIterator):
    """Per-process shard of a record pipeline, as a DataSetIterator.

    - ``reader``: any :class:`RecordReader` (CSV/line/collection/...),
      already ``initialize``d; records are read ONCE at construction
      (host-side ETL, the datavec-local model) and optionally pushed
      through a ``TransformProcess``.
    - The N usable records are split into ``process_count`` equal
      contiguous blocks of ``N // process_count`` (the ragged global
      tail is dropped AND LOGGED — every process must yield the same
      number of batches or the in-step collectives deadlock).
    - Within the block, complete ``batch_size`` batches are yielded;
      the ragged local tail is likewise dropped and logged.
    - ``label_index`` + ``n_labels`` → one-hot classification labels
      (reference: RecordReaderDataSetIterator semantics);
      ``label_index`` alone → regression target column(s).

    ``process_index``/``process_count`` default to the live
    ``jax.distributed`` world, so the SAME user code runs single- and
    multi-process.
    """

    def __init__(self, reader, batch_size: int, *,
                 label_index: Optional[int] = None,
                 n_labels: Optional[int] = None,
                 transform_process=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 dtype=np.float32):
        super().__init__()
        import jax
        self.batch_size = int(batch_size)
        self.label_index = label_index
        self.n_labels = n_labels
        self.dtype = dtype
        pc = (process_count if process_count is not None
              else jax.process_count())
        pi = (process_index if process_index is not None
              else jax.process_index())
        if not 0 <= pi < pc:
            raise ValueError(f"process_index {pi} outside world of "
                             f"{pc} processes")
        rows = [list(r) for r in reader]
        if transform_process is not None:
            rows = transform_process.execute(rows)
        mat = np.array(
            [[w.to_double() if hasattr(w, "to_double") else float(w)
              for w in r] for r in rows], dtype=dtype)
        n_total = len(mat)
        per_proc = n_total // pc
        if per_proc == 0:
            raise ValueError(
                f"{n_total} records cannot shard over {pc} processes")
        dropped_global = n_total - per_proc * pc
        if dropped_global:
            log.warning(
                "ShardedDataSetIterator: dropping %d ragged tail "
                "record(s) of %d so all %d processes hold equal "
                "shards", dropped_global, n_total, pc)
        shard = mat[pi * per_proc:(pi + 1) * per_proc]
        n_batches = per_proc // self.batch_size
        dropped_local = per_proc - n_batches * self.batch_size
        if dropped_local:
            log.warning(
                "ShardedDataSetIterator: dropping %d record(s) of the "
                "local shard (%d) below batch size %d", dropped_local,
                per_proc, self.batch_size)
        if n_batches == 0:
            raise ValueError(
                f"local shard of {per_proc} records < batch size "
                f"{self.batch_size}")
        self._shard = shard[:n_batches * self.batch_size]
        self._n_batches = n_batches
        self._cursor = 0
        self.process_index = pi
        self.process_count = pc

    # -- record matrix -> DataSet --------------------------------------
    def _to_dataset(self, block: np.ndarray) -> DataSet:
        li = self.label_index
        if li is None:
            return self._apply_pre(DataSet(block, block))  # unsupervised
        li = li % block.shape[1]
        feats = np.concatenate([block[:, :li], block[:, li + 1:]],
                               axis=1)
        if self.n_labels is not None:
            labels = np.eye(self.n_labels, dtype=self.dtype)[
                block[:, li].astype(np.int64)]
        else:
            labels = block[:, li:li + 1]
        return self._apply_pre(DataSet(feats, labels))

    # -- DataSetIterator contract --------------------------------------
    def reset(self):
        self._cursor = 0

    def has_next(self) -> bool:
        return self._cursor < self._n_batches

    def next(self) -> DataSet:  # noqa: A003
        if not self.has_next():
            raise StopIteration
        b = self.batch_size
        block = self._shard[self._cursor * b:(self._cursor + 1) * b]
        self._cursor += 1
        return self._to_dataset(block)

    def batch(self) -> int:
        return self.batch_size

    def total_examples(self) -> int:
        return self._n_batches * self.batch_size
