"""Record readers (reference: ``org.datavec.api.records.reader.impl.*``,
SURVEY.md V1): InputSplit -> iterable records of Writables.

A record is ``List[Writable]``; a sequence record is
``List[List[Writable]]`` (time-major), exactly the reference contract
consumed by ``RecordReaderDataSetIterator``.
"""
from __future__ import annotations

import csv as _csv
import io
import os
from typing import Iterator, List, Optional, Sequence

from deeplearning4j_tpu.datavec.split import InputSplit, ListStringSplit
from deeplearning4j_tpu.datavec.writable import Text, Writable

Record = List[Writable]
SequenceRecord = List[List[Writable]]


class RecordReader:
    """Iterator over records (reference: records.reader.RecordReader)."""

    def initialize(self, split: InputSplit) -> "RecordReader":
        self.split = split
        self.reset()
        return self

    def reset(self):
        self._iter = self._make_iter()

    def has_next(self) -> bool:
        if not hasattr(self, "_peek"):
            try:
                self._peek = next(self._iter)
            except StopIteration:
                return False
        return True

    def next(self) -> Record:
        if not self.has_next():
            raise StopIteration
        rec = self._peek
        del self._peek
        return rec

    def __iter__(self) -> Iterator[Record]:
        self.reset()
        while self.has_next():
            yield self.next()

    def _make_iter(self) -> Iterator[Record]:
        raise NotImplementedError


class LineRecordReader(RecordReader):
    """One record per line of each file (reference: LineRecordReader).
    With ListStringSplit, each element IS a line."""

    def _lines(self):
        for loc in self.split.locations():
            if isinstance(self.split, ListStringSplit) or \
                    not (isinstance(loc, str) and os.path.isfile(loc)):
                yield str(loc)
            else:
                with open(loc, "r") as f:
                    for line in f:
                        yield line.rstrip("\n")

    def _make_iter(self):
        for line in self._lines():
            yield [Text(line)]


class CSVRecordReader(LineRecordReader):
    """Comma (or custom) delimited lines -> one Writable per field
    (reference: CSVRecordReader; skip_num_lines for headers)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ",",
                 quote: str = '"'):
        self.skip = int(skip_num_lines)
        self.delimiter = delimiter
        self.quote = quote

    def _make_iter(self):
        n = 0
        for line in self._lines():
            n += 1
            if n <= self.skip:
                continue
            row = next(_csv.reader(io.StringIO(line),
                                   delimiter=self.delimiter,
                                   quotechar=self.quote))
            yield [Text(f) for f in row]

    def numeric_matrix(self, split=None):
        """Bulk-parse the whole split as a float32 [rows, cols] matrix
        via the native CSV parser (C++ fast path, SURVEY.md V1's
        high-rate ingest; falls back to Python parsing). Use for
        all-numeric files — the record iterator handles mixed types."""
        import numpy as _np
        from deeplearning4j_tpu.native import parse_csv_floats
        if split is not None:
            self.initialize(split)
        locs = []
        try:
            locs = list(self.split.locations())
        except Exception:
            pass
        import os as _os
        if len(locs) == 1 and self.skip == 0 \
                and _os.path.isfile(locs[0]):
            # single plain file: hand raw bytes straight to the C
            # parser — no per-line Python iteration, no join copy
            with open(locs[0], "rb") as f:
                data = f.read()
            return _np.asarray(parse_csv_floats(data, self.delimiter))
        text = "\n".join(l for i, l in enumerate(self._lines())
                         if i >= self.skip)
        return _np.asarray(parse_csv_floats(text, self.delimiter))


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: CollectionRecordReader)."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [[Writable.of(v) for v in r] for r in records]
        self.split = None
        self.reset()

    def initialize(self, split=None):
        self.reset()
        return self

    def _make_iter(self):
        return iter(self.records)


# -- sequences --------------------------------------------------------------
class SequenceRecordReader(RecordReader):
    def next_sequence(self) -> SequenceRecord:
        return self.next()


class CSVSequenceRecordReader(SequenceRecordReader):
    """One file per sequence; each line is a timestep (reference:
    CSVSequenceRecordReader)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip = int(skip_num_lines)
        self.delimiter = delimiter

    def _make_iter(self):
        for loc in self.split.locations():
            with open(loc, "r") as f:
                lines = [ln.rstrip("\n") for ln in f][self.skip:]
            yield [[Text(x) for x in
                    next(_csv.reader(io.StringIO(ln),
                                     delimiter=self.delimiter))]
                   for ln in lines if ln]


class CollectionSequenceRecordReader(SequenceRecordReader):
    """In-memory sequences (reference:
    CollectionSequenceRecordReader)."""

    def __init__(self, sequences: Sequence[Sequence[Sequence]]):
        self.sequences = [[[Writable.of(v) for v in step]
                           for step in seq] for seq in sequences]
        self.split = None
        self.reset()

    def initialize(self, split=None):
        self.reset()
        return self

    def _make_iter(self):
        return iter(self.sequences)


class TransformProcessRecordReader(RecordReader):
    """Applies a TransformProcess on the fly (reference:
    TransformProcessRecordReader). Records filtered out by the process
    are skipped."""

    def __init__(self, reader: RecordReader, transform_process):
        self.reader = reader
        self.tp = transform_process

    def initialize(self, split: InputSplit):
        self.reader.initialize(split)
        self.reset()
        return self

    def reset(self):
        if hasattr(self.reader, "_iter"):
            self.reader.reset()
        self._iter = self._make_iter()

    def _make_iter(self):
        for rec in self.reader:
            out = self.tp.execute_record(rec)
            if out is not None:
                yield out
