"""Unified kernel-selection ladder for the hand-written Pallas kernels.

Before this module every fused kernel carried its own ad-hoc gate —
``DL4J_TPU_FLASH_ATTENTION`` in attention_pallas, ``DL4J_TPU_FUSED_BN_BWD``
in bn_pallas, and now ``DL4J_TPU_FUSED_CONV`` for the conv-epilogue
family — each re-implementing the same three rungs in slightly
different shapes.  The ladder is the cuDNN-helper dispatch discipline
(SURVEY.md D9: the helper seam decides, the layer never does):

  1. **structural gate** — dominates everything.  A site the kernel
     cannot express (dense additive bias, unaligned channels, wrong
     dtype/rank, inference-mode BN asked for a batch-stats pass) is
     demoted to the dense lowering no matter what the env says; the
     demotion reason is logged and counted.
  2. **force / kill override** — the tri-state env var (``=1`` force
     on anywhere, ``=0`` kill switch, unset auto), with the
     ``Environment.extra`` key taking precedence over the process env
     so tests and embedding apps can flip gates without touching
     ``os.environ``.
  3. **measured auto-heuristic** — kernel-specific, supplied by the
     caller as a thunk returning ``(fused, reason)``; thresholds are
     backed by bench rounds (FLASH_MIN_SEQ by BENCH_notes_r03, the
     conv-family on-TPU default by BENCH_notes_r06).

Every decision increments ``dl4j_kernel_select_total{kernel,decision}``
so a profile that shows a dense conv where a fused one was expected is
answerable from telemetry instead of print-debugging trace code.
Decisions happen at trace time (inside ``jit`` tracing), so the counter
counts compiled-program dispatch choices, not per-step executions.
"""
from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Union

from deeplearning4j_tpu.common import telemetry

log = logging.getLogger(__name__)

#: kernel family -> (Environment.extra key, env var) for the tri-state
#: force/kill override.  The conv epilogue and the BN forward
#: reduction ride the same DL4J_TPU_FUSED_CONV gate: they are one
#: family (the epilogue writes what the stats pass reads).
GATES = {
    "conv_epilogue": ("fused_conv", "DL4J_TPU_FUSED_CONV"),
    "bn_fwd": ("fused_conv", "DL4J_TPU_FUSED_CONV"),
    "bn_bwd": ("fused_bn_bwd", "DL4J_TPU_FUSED_BN_BWD"),
    "attention": ("flash_attention", "DL4J_TPU_FLASH_ATTENTION"),
    "paged_attention": ("paged_attention", "DL4J_TPU_PAGED_ATTENTION"),
}

_select_total = telemetry.counter(
    "dl4j_kernel_select_total",
    "kernel-dispatch ladder decisions by kernel family and rung "
    "(structural / forced / killed / auto_fused / auto_dense)")


@dataclass(frozen=True)
class Selection:
    """One dispatch decision: which lowering a site gets and why."""

    kernel: str          #: kernel family (a GATES key)
    fused: bool          #: True = hand kernel, False = dense lowering
    decision: str        #: ladder rung that decided (counter label)
    reason: str          #: human-readable justification

    def __bool__(self) -> bool:  # ``if select(...):`` reads naturally
        return self.fused


def gate_override(kernel: str) -> Optional[bool]:
    """The tri-state force/kill override for a kernel family:
    True (force on) / False (kill switch) / None (auto heuristic).
    ``Environment.extra[<key>]`` overrides the env var."""
    from deeplearning4j_tpu.common.environment import Environment
    extra_key, env_var = GATES[kernel]
    flag = Environment.get().extra.get(extra_key)
    if flag is None:
        flag = os.environ.get(env_var)
    if flag is None or str(flag) == "":
        return None
    return str(flag) in ("1", "true", "True", "yes")


_UNSET = object()


def select(kernel: str, *,
           structural: Optional[str] = None,
           auto: Union[Tuple[bool, str],
                       Callable[[], Tuple[bool, str]]] = (False, "auto"),
           override=_UNSET,
           use_env_override: bool = True,
           record: bool = True) -> Selection:
    """Run the ladder for one dispatch site.

    ``structural`` — a demotion reason when the site fails the
    kernel's structural gate, or None when it is admissible.
    ``auto`` — the measured heuristic: either a ``(fused, reason)``
    pair or a thunk returning one (thunks keep device probes like
    free-HBM lookups off the structural/override fast paths).
    ``override``/``use_env_override`` exist for tests — by default the
    live ``gate_override(kernel)`` tri-state is consulted.
    """
    env_var = GATES[kernel][1]
    if structural is not None:
        sel = Selection(kernel, False, "structural", structural)
    else:
        if override is _UNSET:
            override = gate_override(kernel) if use_env_override else None
        if override is False:
            sel = Selection(kernel, False, "killed",
                            f"{env_var}=0 kill switch")
        elif override is True:
            sel = Selection(kernel, True, "forced",
                            f"{env_var}=1 forced")
        else:
            fused, reason = auto() if callable(auto) else auto
            sel = Selection(kernel, bool(fused),
                            "auto_fused" if fused else "auto_dense",
                            reason)
    if record:
        _select_total.inc(kernel=kernel, decision=sel.decision)
        # layer-attribution join: selection happens at trace time,
        # inside the layer's attribution scope — record which layer's
        # trace made this decision (lazy import: layerprof imports
        # telemetry, keep this module light at import time)
        from deeplearning4j_tpu.common import layerprof
        layerprof.note_selection(sel)
        log.debug("kernel_select %s -> %s (%s: %s)", kernel,
                  "fused" if sel.fused else "dense", sel.decision,
                  sel.reason)
    return sel


def decisions(kernel: str) -> dict:
    """Counter readback for tests/diagnostics: decision -> count."""
    return {d: _select_total.value(kernel=kernel, decision=d)
            for d in ("structural", "forced", "killed", "auto_fused",
                      "auto_dense")}
