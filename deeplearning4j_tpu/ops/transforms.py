"""Transforms: stateless elementwise/similarity functions.

Reference parity: ``org.nd4j.linalg.ops.transforms.Transforms`` (SURVEY.md
J2/J8 neighborhood). Everything lowers to single XLA HLO ops and fuses.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap


def _u(x):
    return jnp.asarray(_unwrap(x))


def _wrap(fn):
    def f(x, *args, **kwargs):
        return INDArray(fn(_u(x), *args, **kwargs))
    return f


abs = _wrap(jnp.abs)  # noqa: A001
exp = _wrap(jnp.exp)
log = _wrap(jnp.log)
sqrt = _wrap(jnp.sqrt)
floor = _wrap(jnp.floor)
ceil = _wrap(jnp.ceil)
round = _wrap(jnp.round)  # noqa: A001
sign = _wrap(jnp.sign)
sin = _wrap(jnp.sin)
cos = _wrap(jnp.cos)
tanh = _wrap(jnp.tanh)
sigmoid = _wrap(jax.nn.sigmoid)
softplus = _wrap(jax.nn.softplus)
softsign = _wrap(jax.nn.soft_sign)
elu = _wrap(jax.nn.elu)
gelu = _wrap(jax.nn.gelu)
relu = _wrap(jax.nn.relu)
relu6 = _wrap(jax.nn.relu6)
hard_sigmoid = _wrap(jax.nn.hard_sigmoid)
hard_tanh = _wrap(lambda x: jnp.clip(x, -1.0, 1.0))
swish = _wrap(jax.nn.swish)
mish = _wrap(jax.nn.mish)
log_sigmoid = _wrap(jax.nn.log_sigmoid)
erf = _wrap(jax.scipy.special.erf)


def leaky_relu(x, alpha: float = 0.01) -> INDArray:
    return INDArray(jax.nn.leaky_relu(_u(x), alpha))


def pow(x, p) -> INDArray:  # noqa: A001
    return INDArray(jnp.power(_u(x), _u(p)))


def max(x, y) -> INDArray:  # noqa: A001
    return INDArray(jnp.maximum(_u(x), _u(y)))


def min(x, y) -> INDArray:  # noqa: A001
    return INDArray(jnp.minimum(_u(x), _u(y)))


def clip(x, lo, hi) -> INDArray:
    return INDArray(jnp.clip(_u(x), lo, hi))


def softmax(x, axis: int = -1) -> INDArray:
    return INDArray(jax.nn.softmax(_u(x), axis=axis))


def log_softmax(x, axis: int = -1) -> INDArray:
    return INDArray(jax.nn.log_softmax(_u(x), axis=axis))


def unit_vec(x) -> INDArray:
    v = _u(x)
    n = jnp.linalg.norm(v)
    return INDArray(jnp.where(n > 0, v / n, v))


def cosine_sim(a, b) -> float:
    va, vb = _u(a).reshape(-1), _u(b).reshape(-1)
    return float(jnp.vdot(va, vb) /
                 (jnp.linalg.norm(va) * jnp.linalg.norm(vb)))


def cosine_distance(a, b) -> float:
    return 1.0 - cosine_sim(a, b)


def euclidean_distance(a, b) -> float:
    return float(jnp.linalg.norm(_u(a).reshape(-1) - _u(b).reshape(-1)))


def manhattan_distance(a, b) -> float:
    return float(jnp.sum(jnp.abs(_u(a).reshape(-1) - _u(b).reshape(-1))))


def hamming_distance(a, b) -> float:
    return float(jnp.mean((_u(a).reshape(-1) != _u(b).reshape(-1))
                          .astype(jnp.float32)))


def dot(a, b) -> float:
    return float(jnp.vdot(_u(a), _u(b)))


def cross(a, b) -> INDArray:
    return INDArray(jnp.cross(_u(a), _u(b)))


def atan2(y, x) -> INDArray:
    return INDArray(jnp.arctan2(_u(y), _u(x)))


def is_nan(x) -> INDArray:
    return INDArray(jnp.isnan(_u(x)))


def is_inf(x) -> INDArray:
    return INDArray(jnp.isinf(_u(x)))
