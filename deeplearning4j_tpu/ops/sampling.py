"""Token sampling — the one implementation behind every decode loop.

Greedy / temperature / top-k next-token selection factored out of the
benchmark decoders so the generative serving engine
(:mod:`serving.generative`) and ``benchmarks/bench_charrnn.py`` sample
through identical math. Everything here is jit-friendly: pure
functions of ``(logits, key, temperature, top_k)`` with no Python
branching on traced values, so one compiled decode step serves greedy
and stochastic sequences side by side in the same batch.

Conventions:

- ``logits`` is ``[batch, vocab]`` (a single decode step's last-token
  logits). ``temperature`` and ``top_k`` are per-row arrays (or
  scalars broadcast to the batch), so heterogeneous requests batch
  together without retracing.
- ``temperature == 0`` means greedy (argmax) for that row — resolved
  with ``jnp.where``, not Python ``if``, so it is trace-stable.
- ``top_k == 0`` means "no top-k filter" (full distribution).
- The PRNG key is threaded explicitly; callers split per step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: additive score for filtered logits — matches ops.attention.NEG_INF
#: (finite, so masked-everything rows degrade to uniform, not NaN)
NEG_INF = -1e9


def top_k_filter(logits, top_k):
    """Keep each row's ``top_k`` largest logits, push the rest to
    ``NEG_INF``. ``top_k`` is a per-row int array (0 = keep all).
    Shape-stable: always sorts, always where-selects."""
    logits = jnp.asarray(logits)
    vocab = logits.shape[-1]
    k = jnp.asarray(top_k, jnp.int32)
    k = jnp.broadcast_to(k, logits.shape[:-1])
    # threshold = k-th largest value per row (k clamped into [1, vocab])
    kc = jnp.clip(k, 1, vocab)
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    thresh = jnp.take_along_axis(sorted_desc, kc[..., None] - 1,
                                 axis=-1)
    filtered = jnp.where(logits >= thresh, logits, NEG_INF)
    return jnp.where(k[..., None] > 0, filtered, logits)


def sample_logits(logits, key, temperature=1.0, top_k=0):
    """Next-token ids ``[batch]`` from ``[batch, vocab]`` logits.

    Per-row ``temperature`` (0 = greedy argmax) and ``top_k``
    (0 = unfiltered). One fused program: greedy rows ride the same
    compiled step as sampled rows via ``jnp.where`` — the property the
    continuous decode batch depends on (no per-request retrace)."""
    logits = jnp.asarray(logits)
    temp = jnp.asarray(temperature, logits.dtype)
    temp = jnp.broadcast_to(temp, logits.shape[:-1])
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # stochastic path: temperature-scale (guard the 0 rows — their
    # result is discarded by the where), top-k filter, Gumbel trick
    safe_temp = jnp.where(temp > 0, temp, 1.0)
    scaled = logits / safe_temp[..., None]
    scaled = top_k_filter(scaled, top_k)
    sampled_ids = jax.random.categorical(key, scaled,
                                         axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0, sampled_ids, greedy_ids)


def greedy(logits):
    """Pure argmax ids ``[batch]`` — the deterministic reference the
    conformance gate compares paged decode against."""
    return jnp.argmax(jnp.asarray(logits), axis=-1).astype(jnp.int32)
