"""Op execution funnel: profiling hooks + NaN/Inf panic.

Reference parity: ``org.nd4j.linalg.api.ops.executioner.DefaultOpExecutioner``
with its ``profilingConfigurableHookIn/Out`` pair, ``OpProfiler`` /
``ProfilerConfig`` / ``PerformanceTracker`` (SURVEY.md J3/J13, section 5.1),
and the ``checkForNAN``/``checkForINF`` panic that throws at the offending op.

TPU-first: there is no dispatch to native kernels here — every op is a jax
callable that XLA compiles and fuses. The executioner exists as the
*observability* seam: op-level timing (eager only; inside jit XLA fuses and
the JAX profiler is the tool), call counting, and NaN/Inf scanning.
"""
from __future__ import annotations

import time
from collections import defaultdict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.common.environment import Environment


class ND4JOpProfilerException(RuntimeError):
    """Raised when NaN/Inf panic trips (reference: same-named exception)."""


@dataclass
class ProfilerConfig:
    check_for_nan: bool = False
    check_for_inf: bool = False
    native_statistics: bool = False
    check_elapsed_time: bool = True

    @staticmethod
    def from_environment() -> "ProfilerConfig":
        env = Environment.get()
        return ProfilerConfig(check_for_nan=env.check_for_nan,
                              check_for_inf=env.check_for_inf)


@dataclass
class _OpStats:
    invocations: int = 0
    total_ns: int = 0


class OpProfiler:
    """Per-op invocation counts + wall time (eager path only)."""

    _instance: "OpProfiler | None" = None

    def __init__(self):
        self.stats: dict[str, _OpStats] = defaultdict(_OpStats)
        self.config = ProfilerConfig.from_environment()

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def reset(self):
        self.stats.clear()

    def time_spent(self, op_name: str) -> float:
        return self.stats[op_name].total_ns / 1e9

    def print_out_dashboard(self) -> str:
        lines = ["Op profiler dashboard:"]
        for name, s in sorted(self.stats.items(),
                              key=lambda kv: -kv[1].total_ns):
            lines.append(f"  {name:<32} x{s.invocations:<8} "
                         f"{s.total_ns / 1e6:.3f} ms")
        out = "\n".join(lines)
        print(out)
        return out


def _is_concrete(x) -> bool:
    return not isinstance(x, jax.core.Tracer)


class OpExecutioner:
    """Static funnel every facade op goes through.

    ``exec(name, fn, *args)`` runs ``fn(*args)`` and, when enabled, records
    timing and scans float outputs for NaN/Inf. Inside a jit trace all hooks
    degrade to no-ops (XLA owns the schedule there); use
    ``jax.debug_nans``/``jax.profiler`` for in-graph equivalents.
    """

    @staticmethod
    def exec(name: str, fn, *args, **kwargs):
        prof = OpProfiler.get_instance()
        env = Environment.get()
        timing = env.profiling
        t0 = time.perf_counter_ns() if timing else 0
        out = fn(*args, **kwargs)
        if timing:
            # async dispatch: wait for the device, or we time the enqueue
            try:
                jax.block_until_ready(out)
            except Exception:
                pass  # tracers can't block; in-trace timing is XLA's job
            s = prof.stats[name]
            s.invocations += 1
            s.total_ns += time.perf_counter_ns() - t0
        # live-merge Environment toggles so Nd4j.getEnvironment()-style
        # flag flips work after the singleton exists
        cfg = prof.config
        check_nan = cfg.check_for_nan or env.check_for_nan
        check_inf = cfg.check_for_inf or env.check_for_inf
        if check_nan or check_inf:
            OpExecutioner._panic_scan(
                name, out, ProfilerConfig(check_for_nan=check_nan,
                                          check_for_inf=check_inf))
        return out

    @staticmethod
    def _panic_scan(name, out, cfg: ProfilerConfig):
        leaves = jax.tree_util.tree_leaves(out)
        for leaf in leaves:
            if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                    leaf.dtype, jnp.floating):
                continue
            if not _is_concrete(leaf):
                continue  # in-trace: leave to jax.debug_nans
            if cfg.check_for_nan and bool(jnp.isnan(leaf).any()):
                raise ND4JOpProfilerException(
                    f"NaN value detected in output of op [{name}]")
            if cfg.check_for_inf and bool(jnp.isinf(leaf).any()):
                raise ND4JOpProfilerException(
                    f"Inf value detected in output of op [{name}]")
