from deeplearning4j_tpu.ops.executioner import OpExecutioner, OpProfiler, ProfilerConfig  # noqa: F401
