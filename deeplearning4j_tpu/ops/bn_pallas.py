"""Fused batch-norm backward — Pallas TPU kernel.

Reference parity: ``CudnnBatchNormalizationHelper.backprop`` (SURVEY.md
D9/N8 — the helper seam exists precisely to hand-tune where the
stock lowering falls short).  The XLA autodiff of the BN normalize
splits the backward into separate reduction and elementwise fusions
that each re-read the activation and its cotangent from HBM; on a
ResNet-50 step the profiler attributes ~21 ms to those re-reads
(BENCH_notes_r02.md).  The ResNet-50 train step sits at ~94% of the
HBM roofline, so bytes ARE the step time.

This kernel pair caps BN-backward traffic at the provable minimum of
two passes:

  pass 1 (reduce):  read x, dy  → Σdy, Σdy·x̂  (= dβ, dγ)
  pass 2 (dx):      read x, dy  → dx = A·dy + D·x + E

with A/D/E per-channel f32 coefficients folded OUTSIDE the kernel
from the sums (the algebra: dx = γr(dy − Σdy/M − x̂·Σdyx̂/M) plus the
running-stat cotangent terms, rearranged into one FMA form so the
inner loop is two mul-adds per element).

Enabled behind ``DL4J_TPU_FUSED_BN_BWD=1`` (Environment
``extra["fused_bn_bwd"]``).  Off-TPU the kernels run in Pallas
interpret mode, so the f64 gradient checks exercise the SAME code
path the chip runs.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def fused_bn_bwd_enabled() -> bool:
    """Default ON on TPU (the kernel is gradient-checked and the
    ~21 ms HBM re-read saving — BENCH_notes_r02 — is otherwise dead);
    off elsewhere, where the dense XLA lowering wins and interpret
    mode would crawl. DL4J_TPU_FUSED_BN_BWD=0 is the kill switch,
    =1 forces it on anywhere (Environment ``extra["fused_bn_bwd"]``
    overrides the env var).  Since the ISSUE-13 unification the
    decision runs through the shared ``ops/kernel_select.py`` ladder
    (family ``bn_bwd``) and is counted in
    ``dl4j_kernel_select_total``."""
    from deeplearning4j_tpu.ops import kernel_select

    def _auto():
        platform = jax.devices()[0].platform
        if platform == "tpu":
            return True, "auto: tpu — fused backward pays (r02)"
        return False, f"auto: platform '{platform}' is not tpu"

    return kernel_select.select("bn_bwd", auto=_auto).fused


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _block_rows(M: int, C: int) -> int:
    """~512KB f32 working set per operand block, sublane-aligned."""
    bm = max(8, min(4096, (512 * 1024) // (4 * max(C, 128))))
    bm = (bm // 8) * 8
    return min(bm, max(8, ((M + 7) // 8) * 8))


def _reduce_kernel(x_ref, dy_ref, stat_ref, acc_ref, *, M, bm, acc_t):
    i = pl.program_id(0)
    x = x_ref[...].astype(acc_t)
    dy = dy_ref[...].astype(acc_t)
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    valid = (i * bm + rows) < M
    dy = jnp.where(valid, dy, 0)
    xhat = (x - stat_ref[0:1, :]) * stat_ref[1:2, :]
    # mask the PRODUCT too: padded x rows hold garbage (0·NaN = NaN)
    part = jnp.concatenate(
        [jnp.sum(dy, axis=0, keepdims=True),
         jnp.sum(jnp.where(valid, dy * xhat, 0), axis=0,
                 keepdims=True)], axis=0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += part


def _dx_kernel(x_ref, dy_ref, coef_ref, dx_ref, *, acc_t):
    x = x_ref[...].astype(acc_t)
    dy = dy_ref[...].astype(acc_t)
    a = coef_ref[0:1, :]
    d = coef_ref[1:2, :]
    e = coef_ref[2:3, :]
    dx_ref[...] = (a * dy + d * x + e).astype(dx_ref.dtype)


def _bn_bwd_sums(x2d, dy2d, mean, rstd, acc_t):
    """Pass 1: Σdy and Σdy·x̂ per channel, one read of x and dy."""
    M, C = x2d.shape
    bm = _block_rows(M, C)
    grid = (pl.cdiv(M, bm),)
    stat = jnp.stack([mean, rstd]).astype(acc_t)      # [2, C]
    acc = pl.pallas_call(
        partial(_reduce_kernel, M=M, bm=bm, acc_t=acc_t),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((2, C), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((2, C), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, C), acc_t),
        interpret=_interpret(),
    )(x2d, dy2d, stat)
    return acc[0], acc[1]


def _bn_bwd_dx(x2d, dy2d, a, d, e, acc_t):
    """Pass 2: dx = A·dy + D·x + E (pure per-channel FMA)."""
    M, C = x2d.shape
    bm = _block_rows(M, C)
    grid = (pl.cdiv(M, bm),)
    coef = jnp.stack([a, d, e]).astype(acc_t)         # [3, C]
    return pl.pallas_call(
        partial(_dx_kernel, acc_t=acc_t),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((3, C), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), x2d.dtype),
        interpret=_interpret(),
    )(x2d, dy2d, coef)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_train_normalize(x, gamma, beta, eps):
    """Training-mode BN normalize with batch statistics, returning
    ``(y, mean, var)`` — the fused-backward drop-in for the layer's
    inline math (one-pass E[x]/E[x²] statistics, f32 accumulation)."""
    y, mean, var, _ = bn_forward_math(x, gamma, beta, eps)
    return y, mean, var


def bn_forward_math(x, gamma, beta, eps):
    """THE training-mode BN forward — single source of truth shared by
    the inline layer path and the fused-backward custom_vjp.

    Statistics policy: for bf16/f16 activations, one-pass E[x]/E[x²]
    with f32 accumulation (one fused HBM read; the f32 accumulator's
    ~16 extra mantissa bits make the cancellation benign — the
    cuDNN/TF fused-BN formulation).  For f32+ activations that margin
    does not exist, so the accurate two-pass mean-then-var form is
    used.  When the ``bn_fwd`` kernel-select ladder admits the site
    (DL4J_TPU_FUSED_CONV family), the statistics and the normalize
    each run as ONE Pallas pass (ops/conv_pallas.py) — this is how the
    forward reduction kernel composes with the fused backward: the
    same custom_vjp, hand kernels on both sides.  Returns
    (y, mean, var, rstd)."""
    from deeplearning4j_tpu.ops import conv_pallas
    axes = tuple(range(x.ndim - 1))
    acc_t = jnp.promote_types(x.dtype, jnp.float32)
    fwd_sel = conv_pallas.select_bn_forward(x.shape, x.dtype,
                                           training=True)
    if fwd_sel.fused:
        mean, var = conv_pallas.channel_stats(x)
    elif x.dtype in (jnp.bfloat16, jnp.float16):
        xf = x.astype(acc_t)
        n = x.size // x.shape[-1]
        mean = jnp.sum(xf, axis=axes) / n
        var = jnp.maximum(
            jnp.sum(jax.lax.square(xf), axis=axes) / n
            - jax.lax.square(mean), 0.0)
    else:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    rstd = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(acc_t) * rstd
    bias = beta.astype(acc_t) - mean * scale
    if fwd_sel.fused:
        y = conv_pallas.scale_shift_act(x, scale, bias, "identity")
    else:
        # x·scale + bias: one fused multiply-add over the tensor
        # instead of subtract/divide chains
        y = x * scale.astype(x.dtype) + bias.astype(x.dtype)
    return y, mean, var, rstd


def _bn_fwd(x, gamma, beta, eps):
    y, mean, var, rstd = bn_forward_math(x, gamma, beta, eps)
    return (y, mean, var), (x, gamma, mean, rstd)


def _bn_bwd(eps, res, cts):
    # kernel-site annotation: non-dl4j prefix so the tag nests inside
    # the enclosing layer's dl4j.<layer> attribution scope (custom_vjp
    # backward rules inherit the primal trace's scope in HLO metadata;
    # this marks the hand kernel itself)
    with jax.named_scope("pallas.bn_bwd"):
        return _bn_bwd_raw(eps, res, cts)


def _bn_bwd_raw(eps, res, cts):
    dy, dmean_ct, dvar_ct = cts
    x, gamma, mean, rstd = res
    acc_t = jnp.promote_types(x.dtype, jnp.float32)
    C = x.shape[-1]
    M = x.size // C
    x2d = x.reshape(M, C)
    dy2d = dy.reshape(M, C)

    sdy, sdyx = _bn_bwd_sums(x2d, dy2d, mean.astype(acc_t),
                             rstd.astype(acc_t), acc_t)
    g = gamma.astype(acc_t)
    r = rstd.astype(acc_t)
    mu = mean.astype(acc_t)
    inv_m = 1.0 / M
    # dx = γr·dy − γr·Σdy/M − γr²·x̂-coefficient... rearranged into
    # dx = A·dy + D·x + E with the mean/var cotangent terms folded in
    a_coef = g * r
    d_coef = -g * r * r * (sdyx * inv_m) \
        + 2.0 * dvar_ct.astype(acc_t) * inv_m
    e_coef = (-a_coef * (sdy * inv_m)
              + dmean_ct.astype(acc_t) * inv_m
              - d_coef * mu)
    dx = _bn_bwd_dx(x2d, dy2d, a_coef, d_coef, e_coef,
                    acc_t).reshape(x.shape)
    dgamma = sdyx.astype(gamma.dtype)
    dbeta = sdy.astype(gamma.dtype)
    return dx, dgamma, dbeta


bn_train_normalize.defvjp(_bn_fwd, _bn_bwd)
