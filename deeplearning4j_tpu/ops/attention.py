"""Shared attention math — the single implementation behind both the
graph-op registry (``dot_product_attention`` /
``multi_head_dot_product_attention``, reference nd4j op names) and the
NN attention layers (SURVEY.md D4). One einsum/softmax/einsum chain
that XLA fuses onto the MXU; heads are a tensor dimension, never a
Python loop.

Mask semantics (matching the reference's masked attention): masks are
key masks broadcastable to [..., t_q, t_k]; 0 = masked. Masked keys
get score -inf before softmax; rows whose keys are ALL masked produce
zeros (not uniform garbage).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def dot_product_attention(q, k, v, mask=None, scale=None,
                          dropout_rng=None, dropout_rate=0.0,
                          bias=None):
    """Scaled dot-product attention on [..., t, d] tensors.

    ``dropout_rng``/``dropout_rate``: attention-probability dropout
    (applied to the post-softmax weights, TF/HF BERT style).
    ``bias``: additive pre-softmax score bias (the exporter-style
    (1-mask)*-1e4 convention the fused imported path carries).

    Backend dispatch: bias-free, dropout-free sites (every nn
    attention layer and the fused key-mask imported path) route
    through the Pallas flash kernel when the sequence-length/
    HBM-headroom heuristic or DL4J_TPU_FLASH_ATTENTION selects it —
    see ops/attention_pallas.py; everything else runs the einsum
    chain below."""
    if bias is None and (dropout_rng is None or dropout_rate == 0.0):
        from deeplearning4j_tpu.ops.attention_pallas import \
            maybe_flash_sdpa
        out = maybe_flash_sdpa(q, k, v, scale, mask=mask)
        if out is not None:
            return out
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if bias is not None:
        scores = scores + bias
    if mask is not None:
        scores = jnp.where(mask > 0, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    if mask is not None:
        w = jnp.where(mask > 0, w, 0.0)
    if dropout_rng is not None and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    w.shape)
        w = jnp.where(keep, w / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", w, v)


def split_heads(a, n_heads):
    b, t, _ = a.shape
    return a.reshape(b, t, n_heads, -1).transpose(0, 2, 1, 3)


def merge_heads(a):
    b, h, t, dh = a.shape
    return a.transpose(0, 2, 1, 3).reshape(b, t, h * dh)


def multi_head_attention(params, q_in, kv_in, n_heads, key_mask=None):
    """Projected MHA. params: Wq/Wk/Wv [*, h*dh], Wo [h*dh, n_out];
    optional projection biases bq/bk/bv [h*dh] and bo [n_out]
    (the Keras ``MultiHeadAttention(use_bias=True)`` form).

    q_in: [b, tq, dq]; kv_in: [b, tk, dk]; key_mask: [b, tk] or None.
    """
    def proj(x, w, b):
        y = x @ params[w]
        return y + params[b] if b in params else y

    q = split_heads(proj(q_in, "Wq", "bq"), n_heads)
    k = split_heads(proj(kv_in, "Wk", "bk"), n_heads)
    v = split_heads(proj(kv_in, "Wv", "bv"), n_heads)
    m = key_mask[:, None, None, :] if key_mask is not None else None
    o = dot_product_attention(q, k, v, m)
    out = merge_heads(o) @ params["Wo"]
    return out + params["bo"] if "bo" in params else out
