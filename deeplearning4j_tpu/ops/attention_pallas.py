"""Pallas flash-attention backend for the ``sdpa_core`` op.

The dense einsum attention (ops/attention.py) materializes the
``[b, h, t_q, t_k]`` scores tensor in HBM — at long sequence lengths
those bytes dominate the memory floor and the step time (BENCH_r05:
bytes, not FLOPs, are the lever). This backend routes ``sdpa_core``
sites onto the blocked online-softmax Pallas kernel
(parallel/sequence.py — forward + LSE-recomputing backward, measured
1.55-1.6x faster than XLA dense attention at seq 8k-16k on v5e and
able to run 32k where dense cannot allocate the score matrix at all),
which keeps only O(block_q x block_k) scores in VMEM and never writes
them to HBM.

Adaptation to the ``sdpa_core`` contract:

  * arbitrary ``scale``: the kernel hardcodes the 1/sqrt(d) scaling of
    natively-authored attention, so q is pre-multiplied by
    ``scale * sqrt(d)`` (a single elementwise op; exact for the
    default scale, where the factor is 1.0 and the multiply is
    skipped);
  * key masks: ``mask_mode="key"`` sites (the GraphOptimizer's
    strength-reduced exporter masks) stream a ``[b, t_k]`` key mask
    through the kernel — dense ADDITIVE biases are not streamable and
    fall back to the einsum path;
  * rank: [b, h, t, d] natively, [b, t, d] via a unit heads axis.

Backend selection (``select_attention_backend``): the
``DL4J_TPU_FLASH_ATTENTION`` env var forces the kernel on (``1``) or
off (``0``); unset, the kernel auto-engages on TPU when t_k reaches
``FLASH_MIN_SEQ`` (below ~4k the XLA dense lowering wins outright —
BENCH_notes_r03) OR when the would-be scores tensor alone would eat
more than ``HBM_HEADROOM_FRACTION`` of the device's free HBM.
Off-TPU the kernel runs in Pallas interpret mode (the bn_pallas.py
pattern), so CPU tests exercise the SAME code path the chip runs.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: below this key length the XLA dense lowering beats the kernel
#: outright on TPU (BENCH_notes_r03); auto-selection starts here
FLASH_MIN_SEQ = 4096
#: auto-select flash below FLASH_MIN_SEQ once the dense scores tensor
#: alone would consume this fraction of the device's free HBM
HBM_HEADROOM_FRACTION = 0.25


def flash_attention_override() -> Optional[bool]:
    """Tri-state DL4J_TPU_FLASH_ATTENTION gate: True (force on) /
    False (kill switch) / None (auto heuristic). Environment
    ``extra["flash_attention"]`` overrides the env var.  Since the
    ISSUE-13 unification this is the ``attention`` family row of the
    shared ``ops/kernel_select.py`` ladder."""
    from deeplearning4j_tpu.ops import kernel_select
    return kernel_select.gate_override("attention")


def _free_hbm_bytes() -> Optional[int]:
    try:
        st = jax.local_devices()[0].memory_stats()
        return int(st["bytes_limit"]) - int(st["bytes_in_use"])
    except Exception:           # CPU backend has no memory_stats
        return None


def as_key_mask(mask, batch: int, t_k: int, rank: int):
    """Reduce a mask broadcastable against [b, (h,) t_q, t_k] scores
    to the [b, t_k] key-mask form the kernel streams, or None when
    the mask varies per query/head (right-aligned numpy broadcasting
    — exactly the dense path's semantics)."""
    if mask.ndim == 0 or mask.ndim > rank:
        return None
    ms = tuple(mask.shape)
    if ms[-1] != t_k:
        return None
    if mask.ndim >= 2 and ms[-2] != 1:
        return None             # per-query mask: not streamable
    lead = 1
    for i, dim in enumerate(ms[:-2] if mask.ndim >= 2 else ()):
        axis_from_right = mask.ndim - i
        if axis_from_right == rank:          # the batch axis
            if dim not in (1, batch):
                return None
            lead = dim
        elif dim != 1:                       # a head/query axis
            return None
    flat = jnp.reshape(mask, (lead, t_k))
    return jnp.broadcast_to(flat, (batch, t_k))


def select_attention_backend(q_shape: Tuple[int, ...],
                             k_shape: Tuple[int, ...], *,
                             mask_ok: bool = True,
                             has_bias: bool = False,
                             platform: Optional[str] = None,
                             free_hbm: Optional[int] = None,
                             override=None,
                             use_env_override: bool = True):
    """Pick ("flash" | "dense", reason) for an sdpa_core site.

    Structural requirements dominate everything (a dense additive
    bias or per-query mask cannot stream through the kernel); then
    the DL4J_TPU_FLASH_ATTENTION override; then the auto heuristic
    (TPU + long sequence, or scores tensor vs free-HBM headroom).
    ``platform``/``free_hbm``/``override`` exist for tests — they
    default to the live device.  The ladder itself lives in
    ``ops/kernel_select.py`` (family ``attention``), so every decision
    lands in ``dl4j_kernel_select_total{kernel="attention"}``."""
    from deeplearning4j_tpu.ops import kernel_select

    structural = None
    if has_bias:
        structural = "additive bias is not streamable"
    elif len(q_shape) not in (3, 4) or len(k_shape) != len(q_shape):
        structural = f"rank {len(q_shape)} not supported"
    elif q_shape[-1] != k_shape[-1]:
        structural = "q/k head-dim mismatch"
    elif not mask_ok:
        structural = "mask is not a key mask"
    if override is None and use_env_override:
        override = flash_attention_override()

    def _auto():
        plat = platform
        if plat is None:
            plat = jax.devices()[0].platform
        if plat != "tpu":
            return False, f"auto: platform '{plat}' is not tpu"
        t_k = k_shape[-2]
        if t_k >= FLASH_MIN_SEQ:
            return True, f"auto: t_k={t_k} >= {FLASH_MIN_SEQ}"
        scores_bytes = 4        # f32 scores
        for d in q_shape[:-1]:
            scores_bytes *= int(d)
        scores_bytes *= int(t_k)
        fh = free_hbm if free_hbm is not None else _free_hbm_bytes()
        if fh is not None and fh > 0 \
                and scores_bytes > HBM_HEADROOM_FRACTION * fh:
            return True, (f"auto: scores tensor {scores_bytes >> 20} MB"
                          f" > {HBM_HEADROOM_FRACTION:.0%} of free HBM"
                          f" ({fh >> 20} MB)")
        return False, f"auto: t_k={t_k} fits the dense lowering"

    sel = kernel_select.select("attention", structural=structural,
                               auto=_auto, override=override,
                               use_env_override=False)
    return ("flash" if sel.fused else "dense"), sel.reason


def flash_sdpa(q, k, v, scale: Optional[float] = None, key_mask=None,
               block_q: int = 1024, block_k: int = 1024,
               interpret: Optional[bool] = None):
    """Run sdpa_core semantics on the Pallas kernel:
    softmax(q k^T * scale, masked) v. q/k/v [b, h, t, d] or
    [b, t, d]; key_mask [b, t_k] (0 = masked) or None. Differentiable
    (the kernel carries its own custom VJP; the scale pre-multiply
    composes). ``interpret=None`` resolves to interpret mode off-TPU,
    so gradient checks exercise the chip's code path."""
    from deeplearning4j_tpu.parallel.sequence import flash_attention
    squeeze_heads = q.ndim == 3
    if squeeze_heads:
        q, k, v = q[:, None], k[:, None], v[:, None]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    factor = float(scale) * math.sqrt(d)
    if abs(factor - 1.0) > 1e-9:
        # the kernel scales scores by 1/sqrt(d); fold the requested
        # scale into q so q'k^T/sqrt(d) == q k^T * scale
        q = q * jnp.asarray(factor, q.dtype)
    if key_mask is not None and key_mask.dtype == jnp.bool_:
        key_mask = key_mask.astype(jnp.float32)
    # kernel-site annotation: a non-dl4j prefix so the kernel tag
    # nests INSIDE the enclosing layer's dl4j.<layer> scope in HLO
    # metadata without stealing the attribution match
    with jax.named_scope("pallas.flash_attention"):
        out = flash_attention(q, k, v, False, block_q, block_k,
                              interpret, key_mask)
    return out[:, 0] if squeeze_heads else out


# ---------------------------------------------------------------------------
# paged decode attention (ISSUE 16): one query token per sequence
# attending over a block-paged KV pool through a per-sequence block
# table — the decode half of the generative serving engine.
# ---------------------------------------------------------------------------

#: masked-score value — matches parallel/sequence.py's NEG_INF so the
#: exp-zeroing trick (exp of masked == exactly 0) carries over
_PAGED_NEG_INF = -1e30


def paged_attention_reference(q, k_pool, v_pool, block_tables,
                              lengths, scale: Optional[float] = None):
    """Dense-gather fallback AND numerical reference for paged decode
    attention.

    ``q`` [b, h, d] (the single new token per sequence); ``k_pool`` /
    ``v_pool`` [num_blocks, block, h, d] (one layer's paged KV);
    ``block_tables`` [b, max_blocks] int32 (scratch-block-0 padded);
    ``lengths`` [b] int32 — valid KV tokens per sequence (>= 1, the
    current token's KV already written). Returns [b, h, d].

    The gather materializes [b, max_blocks*block, h, d] — exactly the
    bytes the Pallas kernel avoids — but runs everywhere and defines
    the semantics the kernel must match bit-for-tolerance."""
    b, h, d = q.shape
    block = k_pool.shape[1]
    t = block_tables.shape[1] * block
    k = jnp.reshape(k_pool[block_tables], (b, t, h, d))
    v = jnp.reshape(v_pool[block_tables], (b, t, h, d))
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    valid = (jnp.arange(t, dtype=jnp.int32)[None, :]
             < lengths[:, None])                      # [b, t]
    s = jnp.where(valid[:, None, :], s, _PAGED_NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(s <= _PAGED_NEG_INF / 2, 0.0, jnp.exp(s - m))
    w = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bht,bthd->bhd", w, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _paged_decode_kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref,
                         out_ref, m_ref, l_ref, acc_ref, *,
                         block_size: int, scale: float):
    """Online-softmax accumulation over one sequence's KV blocks.
    Grid (batch, max_blocks), j innermost; the block table picks the
    KV block each j step streams in (scalar-prefetch index map), so
    only table-listed blocks ever leave HBM."""
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(1)
    n_j = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():                                  # noqa: ANN202
        m_ref[...] = jnp.full_like(m_ref, _PAGED_NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[...].astype(jnp.float32)            # [h, d]
    k = k_ref[...].astype(jnp.float32)            # [block, h, d]
    v = v_ref[...].astype(jnp.float32)
    # per-head scores: contract d, batch over h -> [h, block]
    s = jax.lax.dot_general(
        q, k, (((1,), (2,)), ((0,), (1,)))) * scale
    token_idx = (j * block_size
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1))
    s = jnp.where(token_idx < lens_ref[b], s, _PAGED_NEG_INF)

    m_prev = m_ref[:, 0]                          # [h]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.where(s <= _PAGED_NEG_INF / 2, 0.0,
                  jnp.exp(s - m_new[:, None]))    # [h, block]
    l_new = corr * l_ref[:, 0] + jnp.sum(p, axis=1)
    # p @ v batched over h: [h, block] x [block, h, d] -> [h, d]
    pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((0,), (1,))))
    acc_ref[...] = acc_ref[...] * corr[:, None] + pv
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
    l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == n_j - 1)
    def _finish():                                # noqa: ANN202
        denom = jnp.maximum(l_ref[:, 0], 1e-30)
        out_ref[...] = (acc_ref[...] / denom[:, None]
                        ).astype(out_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths,
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Pallas paged decode attention — same contract as
    :func:`paged_attention_reference`, but the KV pool stays in HBM
    and only the blocks each sequence's table names are streamed into
    VMEM (scalar-prefetched index map), one online-softmax fold per
    block. ``interpret=None`` resolves to interpret mode off-TPU so
    CPU conformance tests run the chip's code path."""
    import functools

    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    block = int(k_pool.shape[1])
    max_blocks = int(block_tables.shape[1])
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_tables, lengths
        grid=(b, max_blocks),
        in_specs=[
            pl.BlockSpec((None, h, d),
                         lambda i, j, tables, lens: (i, 0, 0)),
            pl.BlockSpec((None, block, h, d),
                         lambda i, j, tables, lens:
                         (tables[i, j], 0, 0, 0)),
            pl.BlockSpec((None, block, h, d),
                         lambda i, j, tables, lens:
                         (tables[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, h, d),
                               lambda i, j, tables, lens: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),    # running max
            pltpu.VMEM((h, 128), jnp.float32),    # running sum
            pltpu.VMEM((h, d), jnp.float32),      # output accumulator
        ],
    )
    kernel = functools.partial(_paged_decode_kernel,
                               block_size=block, scale=float(scale))
    with jax.named_scope("pallas.paged_decode_attention"):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
            interpret=interpret,
        )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
          q, k_pool, v_pool)


def select_paged_backend(batch: int, max_blocks: int, *,
                         platform: Optional[str] = None,
                         override=None,
                         use_env_override: bool = True):
    """Pick ("paged" | "dense", reason) for a decode-attention site
    through the shared kernel-select ladder (family
    ``paged_attention``, env ``DL4J_TPU_PAGED_ATTENTION``). Auto rung:
    the Pallas kernel on TPU (it exists to keep gathered KV bytes out
    of HBM), the dense gather elsewhere (interpret mode is a
    conformance vehicle, not a fast path)."""
    from deeplearning4j_tpu.ops import kernel_select

    structural = None
    if batch < 1 or max_blocks < 1:
        structural = f"degenerate decode shape b={batch} " \
                     f"blocks={max_blocks}"
    if override is None and use_env_override:
        override = kernel_select.gate_override("paged_attention")

    def _auto():
        plat = platform
        if plat is None:
            plat = jax.devices()[0].platform
        if plat == "tpu":
            return True, "auto: paged kernel on tpu"
        return False, f"auto: platform '{plat}' is not tpu"

    sel = kernel_select.select("paged_attention", structural=structural,
                               auto=_auto, override=override,
                               use_env_override=False)
    return ("paged" if sel.fused else "dense"), sel.reason


def maybe_flash_sdpa(q, k, v, scale: Optional[float] = None,
                     mask=None, bias=None, block_q: int = 1024,
                     block_k: int = 1024):
    """Backend dispatch for an sdpa_core site: the flash result when
    the selection heuristic (or override) takes it, else None — the
    caller falls back to the dense einsum path."""
    km, mask_ok = None, True
    if mask is not None:
        km = as_key_mask(mask, int(q.shape[0]), int(k.shape[-2]),
                         q.ndim)
        mask_ok = km is not None
    backend, _reason = select_attention_backend(
        tuple(q.shape), tuple(k.shape), mask_ok=mask_ok,
        has_bias=bias is not None)
    if backend != "flash":
        return None
    return flash_sdpa(q, k, v, scale, key_mask=km, block_q=block_q,
                      block_k=block_k)
