"""Pallas flash-attention backend for the ``sdpa_core`` op.

The dense einsum attention (ops/attention.py) materializes the
``[b, h, t_q, t_k]`` scores tensor in HBM — at long sequence lengths
those bytes dominate the memory floor and the step time (BENCH_r05:
bytes, not FLOPs, are the lever). This backend routes ``sdpa_core``
sites onto the blocked online-softmax Pallas kernel
(parallel/sequence.py — forward + LSE-recomputing backward, measured
1.55-1.6x faster than XLA dense attention at seq 8k-16k on v5e and
able to run 32k where dense cannot allocate the score matrix at all),
which keeps only O(block_q x block_k) scores in VMEM and never writes
them to HBM.

Adaptation to the ``sdpa_core`` contract:

  * arbitrary ``scale``: the kernel hardcodes the 1/sqrt(d) scaling of
    natively-authored attention, so q is pre-multiplied by
    ``scale * sqrt(d)`` (a single elementwise op; exact for the
    default scale, where the factor is 1.0 and the multiply is
    skipped);
  * key masks: ``mask_mode="key"`` sites (the GraphOptimizer's
    strength-reduced exporter masks) stream a ``[b, t_k]`` key mask
    through the kernel — dense ADDITIVE biases are not streamable and
    fall back to the einsum path;
  * rank: [b, h, t, d] natively, [b, t, d] via a unit heads axis.

Backend selection (``select_attention_backend``): the
``DL4J_TPU_FLASH_ATTENTION`` env var forces the kernel on (``1``) or
off (``0``); unset, the kernel auto-engages on TPU when t_k reaches
``FLASH_MIN_SEQ`` (below ~4k the XLA dense lowering wins outright —
BENCH_notes_r03) OR when the would-be scores tensor alone would eat
more than ``HBM_HEADROOM_FRACTION`` of the device's free HBM.
Off-TPU the kernel runs in Pallas interpret mode (the bn_pallas.py
pattern), so CPU tests exercise the SAME code path the chip runs.
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: below this key length the XLA dense lowering beats the kernel
#: outright on TPU (BENCH_notes_r03); auto-selection starts here
FLASH_MIN_SEQ = 4096
#: auto-select flash below FLASH_MIN_SEQ once the dense scores tensor
#: alone would consume this fraction of the device's free HBM
HBM_HEADROOM_FRACTION = 0.25


def flash_attention_override() -> Optional[bool]:
    """Tri-state DL4J_TPU_FLASH_ATTENTION gate: True (force on) /
    False (kill switch) / None (auto heuristic). Environment
    ``extra["flash_attention"]`` overrides the env var.  Since the
    ISSUE-13 unification this is the ``attention`` family row of the
    shared ``ops/kernel_select.py`` ladder."""
    from deeplearning4j_tpu.ops import kernel_select
    return kernel_select.gate_override("attention")


def _free_hbm_bytes() -> Optional[int]:
    try:
        st = jax.local_devices()[0].memory_stats()
        return int(st["bytes_limit"]) - int(st["bytes_in_use"])
    except Exception:           # CPU backend has no memory_stats
        return None


def as_key_mask(mask, batch: int, t_k: int, rank: int):
    """Reduce a mask broadcastable against [b, (h,) t_q, t_k] scores
    to the [b, t_k] key-mask form the kernel streams, or None when
    the mask varies per query/head (right-aligned numpy broadcasting
    — exactly the dense path's semantics)."""
    if mask.ndim == 0 or mask.ndim > rank:
        return None
    ms = tuple(mask.shape)
    if ms[-1] != t_k:
        return None
    if mask.ndim >= 2 and ms[-2] != 1:
        return None             # per-query mask: not streamable
    lead = 1
    for i, dim in enumerate(ms[:-2] if mask.ndim >= 2 else ()):
        axis_from_right = mask.ndim - i
        if axis_from_right == rank:          # the batch axis
            if dim not in (1, batch):
                return None
            lead = dim
        elif dim != 1:                       # a head/query axis
            return None
    flat = jnp.reshape(mask, (lead, t_k))
    return jnp.broadcast_to(flat, (batch, t_k))


def select_attention_backend(q_shape: Tuple[int, ...],
                             k_shape: Tuple[int, ...], *,
                             mask_ok: bool = True,
                             has_bias: bool = False,
                             platform: Optional[str] = None,
                             free_hbm: Optional[int] = None,
                             override=None,
                             use_env_override: bool = True):
    """Pick ("flash" | "dense", reason) for an sdpa_core site.

    Structural requirements dominate everything (a dense additive
    bias or per-query mask cannot stream through the kernel); then
    the DL4J_TPU_FLASH_ATTENTION override; then the auto heuristic
    (TPU + long sequence, or scores tensor vs free-HBM headroom).
    ``platform``/``free_hbm``/``override`` exist for tests — they
    default to the live device.  The ladder itself lives in
    ``ops/kernel_select.py`` (family ``attention``), so every decision
    lands in ``dl4j_kernel_select_total{kernel="attention"}``."""
    from deeplearning4j_tpu.ops import kernel_select

    structural = None
    if has_bias:
        structural = "additive bias is not streamable"
    elif len(q_shape) not in (3, 4) or len(k_shape) != len(q_shape):
        structural = f"rank {len(q_shape)} not supported"
    elif q_shape[-1] != k_shape[-1]:
        structural = "q/k head-dim mismatch"
    elif not mask_ok:
        structural = "mask is not a key mask"
    if override is None and use_env_override:
        override = flash_attention_override()

    def _auto():
        plat = platform
        if plat is None:
            plat = jax.devices()[0].platform
        if plat != "tpu":
            return False, f"auto: platform '{plat}' is not tpu"
        t_k = k_shape[-2]
        if t_k >= FLASH_MIN_SEQ:
            return True, f"auto: t_k={t_k} >= {FLASH_MIN_SEQ}"
        scores_bytes = 4        # f32 scores
        for d in q_shape[:-1]:
            scores_bytes *= int(d)
        scores_bytes *= int(t_k)
        fh = free_hbm if free_hbm is not None else _free_hbm_bytes()
        if fh is not None and fh > 0 \
                and scores_bytes > HBM_HEADROOM_FRACTION * fh:
            return True, (f"auto: scores tensor {scores_bytes >> 20} MB"
                          f" > {HBM_HEADROOM_FRACTION:.0%} of free HBM"
                          f" ({fh >> 20} MB)")
        return False, f"auto: t_k={t_k} fits the dense lowering"

    sel = kernel_select.select("attention", structural=structural,
                               auto=_auto, override=override,
                               use_env_override=False)
    return ("flash" if sel.fused else "dense"), sel.reason


def flash_sdpa(q, k, v, scale: Optional[float] = None, key_mask=None,
               block_q: int = 1024, block_k: int = 1024,
               interpret: Optional[bool] = None):
    """Run sdpa_core semantics on the Pallas kernel:
    softmax(q k^T * scale, masked) v. q/k/v [b, h, t, d] or
    [b, t, d]; key_mask [b, t_k] (0 = masked) or None. Differentiable
    (the kernel carries its own custom VJP; the scale pre-multiply
    composes). ``interpret=None`` resolves to interpret mode off-TPU,
    so gradient checks exercise the chip's code path."""
    from deeplearning4j_tpu.parallel.sequence import flash_attention
    squeeze_heads = q.ndim == 3
    if squeeze_heads:
        q, k, v = q[:, None], k[:, None], v[:, None]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    factor = float(scale) * math.sqrt(d)
    if abs(factor - 1.0) > 1e-9:
        # the kernel scales scores by 1/sqrt(d); fold the requested
        # scale into q so q'k^T/sqrt(d) == q k^T * scale
        q = q * jnp.asarray(factor, q.dtype)
    if key_mask is not None and key_mask.dtype == jnp.bool_:
        key_mask = key_mask.astype(jnp.float32)
    # kernel-site annotation: a non-dl4j prefix so the kernel tag
    # nests INSIDE the enclosing layer's dl4j.<layer> scope in HLO
    # metadata without stealing the attribution match
    with jax.named_scope("pallas.flash_attention"):
        out = flash_attention(q, k, v, False, block_q, block_k,
                              interpret, key_mask)
    return out[:, 0] if squeeze_heads else out


def maybe_flash_sdpa(q, k, v, scale: Optional[float] = None,
                     mask=None, bias=None, block_q: int = 1024,
                     block_k: int = 1024):
    """Backend dispatch for an sdpa_core site: the flash result when
    the selection heuristic (or override) takes it, else None — the
    caller falls back to the dense einsum path."""
    km, mask_ok = None, True
    if mask is not None:
        km = as_key_mask(mask, int(q.shape[0]), int(k.shape[-2]),
                         q.ndim)
        mask_ok = km is not None
    backend, _reason = select_attention_backend(
        tuple(q.shape), tuple(k.shape), mask_ok=mask_ok,
        has_bias=bias is not None)
    if backend != "flash":
        return None
    return flash_sdpa(q, k, v, scale, key_mask=km, block_q=block_q,
                      block_k=block_k)
