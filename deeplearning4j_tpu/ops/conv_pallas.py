"""Fused conv/BN/ReLU epilogue kernels — the Pallas conv family.

Reference parity: ``CudnnConvolutionHelper`` with
``cudnnConvolutionBiasActivationForward`` (SURVEY.md D9; the cuDNN
playbook of PAPERS.md 1410.0759 fuses the conv epilogue into the
matmul's output tiles).  BENCH_r05 puts the ResNet-50 step at 93.5%
of the HBM roofline but only 29.3% of bf16 peak: bytes, not flops,
are the step time, and the profiler attributes the gap to the conv
path — XLA lowers conv → bias/BN scale-shift → ReLU as separate
elementwise fusions that re-read the conv result from HBM.

Three kernels close those round-trips:

  * **epilogue** — ``y = act(x·scale + shift)`` with per-channel f32
    coefficients, tiled ``[bm, C]`` (the bn_pallas block policy).
    One read, one write; serves conv bias+activation, BN inference
    (scale/shift folded from running stats), and the training-mode
    BN normalize.  Backward is a single fused pass producing
    ``dx = dy·act′·scale`` plus the ``Σdy·act′`` / ``Σdy·act′·x``
    channel reductions (dshift/dscale) — no re-read.
  * **channel stats** — one-pass per-channel ``Σx`` / ``Σx²`` with
    f32 accumulation, so training-mode BN derives mean/var from ONE
    read of the conv output instead of XLA's separate reduction
    fusions; composes with the existing bn_pallas fused backward
    (``bn_forward_math`` routes its statistics here when selected).
  * **matmul epilogue** — pointwise (1×1, stride 1) convs ARE
    matmuls; the MXU matmul kernel applies bias+activation in the
    output tile before it ever reaches HBM (the ResNet-50 bottleneck
    stages are 1×1-dominated).

Dispatch runs through the unified ``ops/kernel_select.py`` ladder
(kernel families ``conv_epilogue`` / ``bn_fwd``, both riding the
``DL4J_TPU_FUSED_CONV`` tri-state gate): structural gates — dtype,
sublane channel alignment, streamable activation (relu/identity),
training vs inference BN — demote to the dense lowering with a
counted reason; unset, the auto heuristic engages on TPU above a
size floor.  Off-TPU the kernels run in Pallas interpret mode, so
the f64 gradient checks exercise the SAME code path the chip runs
(the bn_pallas.py pattern).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.ops import kernel_select
from deeplearning4j_tpu.ops.bn_pallas import _block_rows, _interpret

#: activations the epilogue kernels stream (relu as a max against the
#: zero of the accumulator dtype; identity as a pure FMA)
STREAMABLE_ACTIVATIONS = ("relu", "identity")
#: below this many output elements the kernel-launch bookkeeping beats
#: the saved HBM round-trip and the XLA fusion wins (r06 proxy figure,
#: pending a chip window)
FUSED_CONV_MIN_ELEMENTS = 1 << 16
#: MXU lane width — the pointwise-matmul path requires both contracted
#: and output channels to tile it exactly
MXU_LANE = 128

_fused_steps = telemetry.counter(
    "dl4j_conv_fused_steps_total",
    "fused conv-family kernel sites traced into compiled programs, "
    "by site (conv / conv_matmul / bn_train / bn_infer); counts "
    "dispatches at trace time, not per executed step")


# ---------------------------------------------------------------------------
# selection (structural gate -> override -> auto, via kernel_select)
# ---------------------------------------------------------------------------
def _family_structural(shape, dtype, platform) -> Optional[str]:
    """The structural gate shared by every conv-family kernel: a
    demotion reason, or None when the site is admissible."""
    if len(shape) < 2:
        return f"rank {len(shape)} not supported"
    dt = jnp.dtype(dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        return f"dtype {dt.name} is not floating"
    if dt == jnp.dtype(jnp.float64) and platform == "tpu":
        return "f64 is not supported on tpu"
    c = int(shape[-1])
    if c % 8 != 0:
        return f"channels {c} not sublane-aligned (C % 8 != 0)"
    return None


def _auto_heuristic(n_elements, platform):
    if platform != "tpu":
        return False, f"auto: platform '{platform}' is not tpu"
    if n_elements < FUSED_CONV_MIN_ELEMENTS:
        return False, (f"auto: {n_elements} elements below the fusion "
                       f"floor {FUSED_CONV_MIN_ELEMENTS}")
    return True, (f"auto: tpu, {n_elements} elements >= "
                  f"{FUSED_CONV_MIN_ELEMENTS}")


def select_conv_epilogue(out_shape, dtype, act_name: str, *,
                         has_epilogue: bool = True,
                         platform: Optional[str] = None,
                         override=None, use_env_override: bool = True,
                         record: bool = True) -> kernel_select.Selection:
    """Ladder decision for a conv-epilogue site (conv bias+activation,
    or inference-mode BN's folded scale/shift+activation).
    ``platform``/``override`` exist for tests — they default to the
    live device and the DL4J_TPU_FUSED_CONV tri-state."""
    if platform is None:
        platform = jax.devices()[0].platform
    if not has_epilogue:
        structural = "no epilogue to fuse (no bias, identity activation)"
    elif act_name not in STREAMABLE_ACTIVATIONS:
        structural = f"activation '{act_name}' is not streamable"
    else:
        structural = _family_structural(out_shape, dtype, platform)
    n = 1
    for d in out_shape:
        n *= int(d)
    if override is None and use_env_override:
        override = kernel_select.gate_override("conv_epilogue")
    return kernel_select.select(
        "conv_epilogue", structural=structural,
        auto=lambda: _auto_heuristic(n, platform),
        override=override, use_env_override=False, record=record)


def select_bn_forward(shape, dtype, *, training: bool,
                      platform: Optional[str] = None,
                      override=None, use_env_override: bool = True,
                      record: bool = True) -> kernel_select.Selection:
    """Ladder decision for the training-mode BN forward (one-pass
    channel stats + fused normalize). Inference-mode BN has no
    batch-stats pass — it is an epilogue site — so asking for the
    stats kernel outside training is a structural demotion."""
    if platform is None:
        platform = jax.devices()[0].platform
    if not training:
        structural = ("inference-mode BN folds into the epilogue "
                      "(no batch-stats pass)")
    else:
        structural = _family_structural(shape, dtype, platform)
    n = 1
    for d in shape:
        n *= int(d)
    if override is None and use_env_override:
        override = kernel_select.gate_override("bn_fwd")
    return kernel_select.select(
        "bn_fwd", structural=structural,
        auto=lambda: _auto_heuristic(n, platform),
        override=override, use_env_override=False, record=record)


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------
def _epilogue_kernel(x_ref, coef_ref, y_ref, *, act, acc_t):
    x = x_ref[...].astype(acc_t)
    y = x * coef_ref[0:1, :] + coef_ref[1:2, :]
    if act == "relu":
        y = jnp.maximum(y, 0)
    y_ref[...] = y.astype(y_ref.dtype)


def _epilogue_bwd_kernel(x_ref, dy_ref, coef_ref, dx_ref, acc_ref, *,
                         act, M, bm, acc_t):
    i = pl.program_id(0)
    x = x_ref[...].astype(acc_t)
    dy = dy_ref[...].astype(acc_t)
    a = coef_ref[0:1, :]
    b = coef_ref[1:2, :]
    if act == "relu":
        g = jnp.where((x * a + b) > 0, dy, 0)
    else:
        g = dy
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    valid = (i * bm + rows) < M
    g = jnp.where(valid, g, 0)
    dx_ref[...] = (g * a).astype(dx_ref.dtype)
    # mask the PRODUCT too: padded x rows hold garbage (0·NaN = NaN)
    part = jnp.concatenate(
        [jnp.sum(g, axis=0, keepdims=True),
         jnp.sum(jnp.where(valid, g * x, 0), axis=0, keepdims=True)],
        axis=0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += part


def _stats_kernel(x_ref, acc_ref, *, M, bm, acc_t):
    i = pl.program_id(0)
    x = x_ref[...].astype(acc_t)
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    valid = (i * bm + rows) < M
    part = jnp.concatenate(
        [jnp.sum(jnp.where(valid, x, 0), axis=0, keepdims=True),
         jnp.sum(jnp.where(valid, x * x, 0), axis=0, keepdims=True)],
        axis=0)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += part


def _matmul_epilogue_kernel(x_ref, w_ref, bias_ref, y_ref, *, act,
                            acc_t):
    z = jnp.dot(x_ref[...], w_ref[...], preferred_element_type=acc_t)
    y = z + bias_ref[...].astype(acc_t)
    if act == "relu":
        y = jnp.maximum(y, 0)
    y_ref[...] = y.astype(y_ref.dtype)


# ---------------------------------------------------------------------------
# raw launchers (shared by the custom_vjp forward/backward rules)
# ---------------------------------------------------------------------------
def _acc_type(x):
    return jnp.promote_types(x.dtype, jnp.float32)


def _epilogue_apply(x, scale, shift, act):
    # kernel-site annotation: non-dl4j prefix so the tag nests inside
    # the enclosing layer's dl4j.<layer> attribution scope
    with jax.named_scope("pallas.conv_epilogue"):
        return _epilogue_apply_raw(x, scale, shift, act)


def _epilogue_apply_raw(x, scale, shift, act):
    acc_t = _acc_type(x)
    C = x.shape[-1]
    M = x.size // C
    bm = _block_rows(M, C)
    coef = jnp.stack([jnp.broadcast_to(scale, (C,)).astype(acc_t),
                      jnp.broadcast_to(shift, (C,)).astype(acc_t)])
    y2d = pl.pallas_call(
        partial(_epilogue_kernel, act=act, acc_t=acc_t),
        grid=(pl.cdiv(M, bm),),
        in_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((2, C), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bm, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), x.dtype),
        interpret=_interpret(),
    )(x.reshape(M, C), coef)
    return y2d.reshape(x.shape)


def _epilogue_backward(x, dy, scale, shift, act):
    acc_t = _acc_type(x)
    C = x.shape[-1]
    M = x.size // C
    bm = _block_rows(M, C)
    coef = jnp.stack([jnp.broadcast_to(scale, (C,)).astype(acc_t),
                      jnp.broadcast_to(shift, (C,)).astype(acc_t)])
    dx2d, acc = pl.pallas_call(
        partial(_epilogue_bwd_kernel, act=act, M=M, bm=bm, acc_t=acc_t),
        grid=(pl.cdiv(M, bm),),
        in_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((bm, C), lambda i: (i, 0)),
                  pl.BlockSpec((2, C), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0)),
                   pl.BlockSpec((2, C), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((M, C), x.dtype),
                   jax.ShapeDtypeStruct((2, C), acc_t)],
        interpret=_interpret(),
    )(x.reshape(M, C), dy.reshape(M, C), coef)
    # acc[0] = Σ dy·act′ (dshift), acc[1] = Σ dy·act′·x (dscale)
    return dx2d.reshape(x.shape), acc[1], acc[0]


def _channel_sums(x2d, acc_t):
    M, C = x2d.shape
    bm = _block_rows(M, C)
    return pl.pallas_call(
        partial(_stats_kernel, M=M, bm=bm, acc_t=acc_t),
        grid=(pl.cdiv(M, bm),),
        in_specs=[pl.BlockSpec((bm, C), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2, C), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2, C), acc_t),
        interpret=_interpret(),
    )(x2d)


def _matmul_epilogue(x2d, w2d, bias, act):
    acc_t = _acc_type(x2d)
    M, K = x2d.shape
    N = w2d.shape[-1]
    bm = min(128, max(8, ((M + 7) // 8) * 8))
    bn = MXU_LANE
    bias2d = jnp.broadcast_to(bias, (N,)).reshape(1, N)
    return pl.pallas_call(
        partial(_matmul_epilogue_kernel, act=act, acc_t=acc_t),
        grid=(pl.cdiv(M, bm), pl.cdiv(N, bn)),
        in_specs=[pl.BlockSpec((bm, K), lambda i, j: (i, 0)),
                  pl.BlockSpec((K, bn), lambda i, j: (0, j)),
                  pl.BlockSpec((1, bn), lambda i, j: (0, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), x2d.dtype),
        interpret=_interpret(),
    )(x2d, w2d, bias2d)


# ---------------------------------------------------------------------------
# differentiable building blocks
# ---------------------------------------------------------------------------
@partial(jax.custom_vjp, nondiff_argnums=(3,))
def scale_shift_act(x, scale, shift, act: str):
    """``y = act(x·scale + shift)`` with per-channel (last-axis)
    coefficients, one fused read/write pass.  The epilogue shared by
    conv bias+activation, inference-mode BN, and the training-mode BN
    normalize.  Backward is the matching one-pass kernel:
    ``dx = dy·act′·scale`` plus the dscale/dshift reductions."""
    return _epilogue_apply(x, scale, shift, act)


def _ssa_fwd(x, scale, shift, act):
    return _epilogue_apply(x, scale, shift, act), (x, scale, shift)


def _ssa_bwd(act, res, dy):
    x, scale, shift = res
    dx, dscale, dshift = _epilogue_backward(x, dy, scale, shift, act)
    return (dx, dscale.astype(scale.dtype), dshift.astype(shift.dtype))


scale_shift_act.defvjp(_ssa_fwd, _ssa_bwd)


@jax.custom_vjp
def channel_stats(x):
    """Per-channel ``(mean, var)`` over every leading axis in ONE pass
    — Σx and Σx² accumulate in the same read (f32 accumulation for
    sub-f32 inputs), so training-mode BN stops re-reading the conv
    output for its statistics.  Differentiable: the backward is the
    per-channel FMA ``dx = x·(2·dvar/M) + (dmean − 2·mean·dvar)/M``,
    lowered through the same epilogue kernel."""
    return _channel_stats_impl(x)


def _channel_stats_impl(x):
    acc_t = _acc_type(x)
    C = x.shape[-1]
    M = x.size // C
    acc = _channel_sums(x.reshape(M, C), acc_t)
    mean = acc[0] / M
    var = jnp.maximum(acc[1] / M - jax.lax.square(mean), 0.0)
    return mean, var


def _cs_fwd(x):
    mean, var = _channel_stats_impl(x)
    return (mean, var), (x, mean)


def _cs_bwd(res, cts):
    dmean, dvar = cts
    x, mean = res
    acc_t = _acc_type(x)
    inv_m = 1.0 / (x.size // x.shape[-1])
    dv = dvar.astype(acc_t)
    scale = 2.0 * dv * inv_m
    shift = (dmean.astype(acc_t) - 2.0 * mean * dv) * inv_m
    return (_epilogue_apply(x, scale, shift, "identity"),)


channel_stats.defvjp(_cs_fwd, _cs_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def matmul_bias_act(x2d, w2d, bias, act: str):
    """``y = act(x @ w + bias)`` with the epilogue applied in the MXU
    output tile before it reaches HBM — the pointwise-conv lowering.
    Backward recovers the relu mask from the saved OUTPUT (``y > 0``
    ⟺ pre-activation > 0 when scale ≡ 1), so the pre-activation is
    never written to HBM."""
    return _matmul_epilogue(x2d, w2d, bias, act)


def _mba_fwd(x2d, w2d, bias, act):
    y = _matmul_epilogue(x2d, w2d, bias, act)
    return y, (x2d, w2d, bias, y)


def _mba_bwd(act, res, dy):
    x2d, w2d, bias, y = res
    acc_t = _acc_type(x2d)
    g = jnp.where(y > 0, dy, 0) if act == "relu" else dy
    dx = jnp.dot(g, w2d.T,
                 preferred_element_type=acc_t).astype(x2d.dtype)
    dw = jnp.dot(x2d.T, g,
                 preferred_element_type=acc_t).astype(w2d.dtype)
    db = jnp.sum(g.astype(acc_t), axis=0).astype(bias.dtype)
    return dx, dw, db


matmul_bias_act.defvjp(_mba_fwd, _mba_bwd)


# ---------------------------------------------------------------------------
# layer entry points
# ---------------------------------------------------------------------------
def _is_pointwise(w_shape, window_strides, rhs_dilation, padding):
    spatial = w_shape[:-2]
    if any(int(k) != 1 for k in spatial):
        return False
    if any(int(s) != 1 for s in window_strides):
        return False
    if any(int(d) != 1 for d in rhs_dilation):
        return False
    if isinstance(padding, str):
        return True              # SAME == VALID == no pad for 1×…×1
    return all(int(lo) == 0 and int(hi) == 0 for lo, hi in padding)


def conv_forward(x, w, *, window_strides, padding, rhs_dilation,
                 dimension_numbers, bias=None, activation=None):
    """THE conv-family call site: ``conv_general_dilated`` plus its
    bias/activation epilogue, with the epilogue emitted inside Pallas
    output tiles when the ``conv_epilogue`` ladder admits the site —
    otherwise the exact dense lowering the layers always used.
    Conv1D/2D/3D all route here (channels-last dimension numbers), so
    the dispatch logic lives in one place instead of per-rank copies."""
    from deeplearning4j_tpu.activations import Activation
    act = activation if activation is not None else Activation.IDENTITY
    act_name = act.value
    n_out = int(w.shape[-1])
    out_shape = tuple(x.shape[:-1]) + (n_out,)

    def dense():
        z = jax.lax.conv_general_dilated(
            x, w, window_strides=window_strides, padding=padding,
            rhs_dilation=rhs_dilation,
            dimension_numbers=dimension_numbers)
        if bias is not None:
            z = z + bias
        return act(z)

    has_epilogue = bias is not None or act_name != "identity"
    sel = select_conv_epilogue(out_shape, x.dtype, act_name,
                               has_epilogue=has_epilogue)
    if not sel.fused:
        return dense()
    acc_t = _acc_type(x)
    shift = bias if bias is not None else jnp.zeros((n_out,), acc_t)
    c_in = int(w.shape[-2])
    if _is_pointwise(w.shape, window_strides, rhs_dilation, padding) \
            and c_in % MXU_LANE == 0 and n_out % MXU_LANE == 0:
        # a 1×…×1 stride-1 conv IS a [M, C_in] × [C_in, C_out] matmul:
        # run it on the MXU kernel and apply the epilogue in the
        # output tile, before the result ever reaches HBM
        _fused_steps.inc(site="conv_matmul")
        y2d = matmul_bias_act(x.reshape(-1, c_in),
                              w.reshape(c_in, n_out), shift, act_name)
        return y2d.reshape(out_shape)
    _fused_steps.inc(site="conv")
    z = jax.lax.conv_general_dilated(
        x, w, window_strides=window_strides, padding=padding,
        rhs_dilation=rhs_dilation, dimension_numbers=dimension_numbers)
    return scale_shift_act(z, jnp.ones((n_out,), acc_t), shift,
                           act_name)


def maybe_fused_bn_train(x, gamma, beta, eps, activation):
    """Training-mode BN forward on the conv-family kernels: one-pass
    channel stats, then the fused normalize(+activation) epilogue.
    Returns ``(y, mean, var)`` with the activation already applied, or
    None when the ``bn_fwd`` ladder demotes the site (the caller runs
    the dense math).  Used on the non-fused-backward path; the
    fused-backward path gets the same stats kernel via
    ``bn_forward_math`` inside ``bn_train_normalize``."""
    sel = select_bn_forward(x.shape, x.dtype, training=True)
    if not sel.fused:
        return None
    _fused_steps.inc(site="bn_train")
    acc_t = _acc_type(x)
    mean, var = channel_stats(x)
    rstd = jax.lax.rsqrt(var + eps)
    scale = gamma.astype(acc_t) * rstd
    shift = beta.astype(acc_t) - mean * scale
    act_name = activation.value
    if act_name in STREAMABLE_ACTIVATIONS:
        y = scale_shift_act(x, scale, shift, act_name)
    else:
        y = activation(scale_shift_act(x, scale, shift, "identity"))
    return y, mean, var


def maybe_bn_inference_epilogue(x, scale, shift, activation):
    """Inference-mode BN as ONE epilogue pass: the running stats fold
    into per-channel scale/shift and the activation streams behind
    them.  Returns the activated output, or None when the
    ``conv_epilogue`` ladder demotes the site."""
    act_name = activation.value
    sel = select_conv_epilogue(x.shape, x.dtype, act_name)
    if not sel.fused:
        return None
    _fused_steps.inc(site="bn_infer")
    return scale_shift_act(x, scale, shift, act_name)
