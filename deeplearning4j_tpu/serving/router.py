"""Multi-replica serving router: least-loaded dispatch, health-gated
routing, and fleet-wide warm-then-drain rollouts.

The scale-out face of the serving stack. A :class:`ServingRouter`
owns N in-process replicas — each a full
(:class:`~deeplearning4j_tpu.serving.registry.ModelRegistry`,
:class:`~deeplearning4j_tpu.serving.admission.AdmissionController`,
:class:`~deeplearning4j_tpu.serving.server.InferenceServer`) stack on
its own port — and fronts them with one HTTP listener:

- ``POST /v1/models/<name>:predict`` — proxied to the healthy replica
  with the fewest outstanding router-dispatched requests
  (least-loaded). Connection-level failures mark the replica unhealthy
  and the request retries on the next one; application-level statuses
  (429/503/504, with ``Retry-After`` / ``X-Model-Version`` headers)
  relay untouched — shedding is the *replica's* verdict, not a router
  failure.
- ``POST /v1/models/<name>:generate`` — same dispatch; a chunked
  (streaming) replica response is relayed chunk-by-chunk, so each
  token reaches the client the moment the replica emits it.
- ``GET /v1/replicas`` — per-replica health/outstanding/url.
- ``GET /v1/models`` — the first healthy replica's catalog.
- ``GET /healthz`` / ``GET /readyz`` — the fleet answers (ready when
  ≥1 replica is ready).
- ``GET /metrics`` — this process's telemetry registry (replica and
  router metrics share it when replicas are in-process).

:meth:`ServingRouter.rollout` is the fleet version of the registry's
hot-swap protocol: replicas are re-registered **one at a time**, and
each replica warms the new version fully before its live pointer
flips — so at every instant every replica serves *some* warm version
and the fleet never drops or colds a request (warm-then-drain,
fleet-wide).

A background thread polls each replica's ``/healthz`` every
``health_interval_s`` (``dl4j_serving_router_healthy`` mirrors the
verdict); a replica marked down by a failed proxy re-enters rotation
on its next successful poll. Liveness, not readiness, gates rotation:
a live replica with no model yet stays routable (readiness is
answered in-process from its registry), while a dead socket is out.
"""
from __future__ import annotations

import http.client
import json
import re
import threading
import time
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.common import telemetry, tracectx
from deeplearning4j_tpu.common.httputil import (QuietHandler,
                                                start_http_server)
from deeplearning4j_tpu.serving import reqrec
from deeplearning4j_tpu.serving.admission import AdmissionController
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.server import InferenceServer
from deeplearning4j_tpu.serving.slo import SLOTracker

_ROUTE_RE = re.compile(r"^/v1/models/([^/:]+):(predict|generate)$")

#: end-to-end headers the proxy relays verbatim in each direction —
#: the trace id crosses BOTH ways, so the replica adopts the router's
#: id and the client reads it back off the response
_RELAY_REQ = ("Content-Type", "X-Deadline-Ms",
              tracectx.TRACE_HEADER)
_RELAY_RESP = ("Content-Type", "Retry-After", "X-Model-Version",
               tracectx.TRACE_HEADER)


def _healthy_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_serving_router_healthy",
        "router's live health verdict per replica (1 = in rotation, "
        "0 = out after a failed readyz poll or connection error)")


class Replica:
    """One in-process serving stack plus the router's bookkeeping."""

    def __init__(self, name: str, registry: ModelRegistry,
                 admission: AdmissionController,
                 server: InferenceServer):
        self.name = name
        self.registry = registry
        self.admission = admission
        self.server = server
        self.healthy = True
        self._outstanding = 0
        self._lock = threading.Lock()

    @property
    def outstanding(self) -> int:
        return self._outstanding

    def begin(self):
        with self._lock:
            self._outstanding += 1

    def end(self):
        with self._lock:
            self._outstanding = max(0, self._outstanding - 1)

    def set_healthy(self, ok: bool):
        self.healthy = ok
        _healthy_gauge().set(1 if ok else 0, replica=self.name)

    def host_port(self):
        httpd = self.server._httpd
        if httpd is None:       # stopped/crashed replica: connection-
            raise OSError("replica server is not running")  # level fail
        host, port = httpd.server_address[0], httpd.server_address[1]
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"
        return host, port

    def describe(self) -> dict:
        return {"name": self.name, "url": self.server.url,
                "healthy": self.healthy,
                "outstanding": self.outstanding,
                "ready": self.registry.ready()
                and not self.admission.draining}


class ServingRouter:
    """N serving replicas behind one least-loaded HTTP front."""

    def __init__(self, n_replicas: int = 2, *, mesh=None,
                 default_buckets=(8, 32),
                 flush_policy: str = "continuous",
                 queue_limit: int = 256,
                 batch_window_ms: float = 2.0,
                 admission_factory=None,
                 request_timeout_s: float = 60.0,
                 health_interval_s: float = 1.0):
        if n_replicas < 1:
            raise ValueError("need at least one replica")
        self.replicas: List[Replica] = []
        for i in range(n_replicas):
            registry = ModelRegistry(
                mesh, default_buckets=default_buckets,
                batch_window_ms=batch_window_ms,
                queue_limit=queue_limit, flush_policy=flush_policy)
            admission = (admission_factory() if admission_factory
                         else AdmissionController())
            server = InferenceServer(
                registry, admission,
                request_timeout_s=request_timeout_s)
            self.replicas.append(
                Replica(f"replica-{i}", registry, admission, server))
        self.health_interval_s = health_interval_s
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._stopping = False
        self.port: Optional[int] = None

    # ------------------------------------------------------------------
    def start(self, port: int = 0) -> "ServingRouter":
        """Start every replica's server (each on a free port), then
        the router front, then the health poller. Idempotent."""
        if self._httpd is not None:
            return self
        for r in self.replicas:
            r.server.start(0)
            r.set_healthy(True)
        router = self

        class Handler(QuietHandler):
            def do_GET(self):               # noqa: N802
                if self.path == "/v1/replicas":
                    self.send_json({"replicas":
                                    [r.describe()
                                     for r in router.replicas]})
                elif self.path == "/v1/models":
                    rep = router._pick() or router.replicas[0]
                    self.send_json({"models":
                                    rep.registry.describe()})
                elif self.path == "/healthz":
                    self.send_body(b"ok\n", "text/plain")
                elif self.path == "/readyz":
                    ok = any(r.healthy and r.registry.ready()
                             and not r.admission.draining
                             for r in router.replicas)
                    self.send_body(b"ready\n" if ok
                                   else b"not ready\n",
                                   "text/plain", 200 if ok else 503)
                elif self.path == "/metrics":
                    self.send_metrics()
                elif self.path == "/api/slo":
                    # replicas are in-process: the tracker is the
                    # shared process singleton
                    self.send_json(SLOTracker.get().report())
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):              # noqa: N802
                m = _ROUTE_RE.match(self.path)
                if not m:
                    if self.path == "/api/reqrec/dump":
                        path = reqrec.get().dump("api")
                        self.send_json({"path": path},
                                       200 if path else 503)
                        return
                    self.send_json({"error": "not found"}, 404)
                    return
                router._proxy(self)

        self._httpd, self._thread = start_http_server(Handler, port)
        # lifecycle transition: assigned before the health thread
        # starts (happens-before), and start/stop are owner-serialized
        # dl4j-lint: disable=lock-discipline
        self.port = self._httpd.server_address[1]
        self._stopping = False
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name="dl4j-tpu-router-health")
        self._health_thread.start()
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop the front, then every replica (draining by default)."""
        self._stopping = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None
            # lifecycle transition, owner-serialized with start()
            # dl4j-lint: disable=lock-discipline
            self.port = None
        for r in self.replicas:
            r.server.stop(drain=drain, timeout=timeout)
            r.registry.shutdown()

    @property
    def url(self) -> Optional[str]:
        return f"http://127.0.0.1:{self.port}" if self.port else None

    # ------------------------------------------------------------------
    def rollout(self, name: str, model, **register_kw) -> List:
        """Register (or hot-swap) ``name`` across the fleet,
        warm-then-drain one replica at a time.

        ``model`` is a zero-arg factory (called once per replica — the
        safe spelling for in-memory models, since each replica needs
        its own instance), an artifact path (each replica loads its
        own copy), or a single object (shared across replicas; fine
        for read-only serving of small models). ``register_kw`` passes
        through to :meth:`ModelRegistry.register` (warmup_shape, mode,
        latency_slo_ms, ...). Returns the new ModelVersions."""
        versions = []
        for r in self.replicas:
            m = model
            if callable(m) and not hasattr(m, "output") \
                    and not hasattr(m, "_forward"):
                m = m()
            elif isinstance(m, (str, Path)):
                m = str(m)
            # register() warms the new version fully BEFORE flipping
            # this replica's live pointer; the other replicas keep
            # serving their current warm version meanwhile
            versions.append(r.registry.register(name, m,
                                                **register_kw))
        telemetry.counter(
            "dl4j_serving_rollouts_total",
            "fleet-wide warm-then-drain version rollouts completed "
            "per model (every replica re-registered sequentially, "
            "each warmed before its live pointer flipped)"
        ).inc(model=name)
        return versions

    # ------------------------------------------------------------------
    def _pick(self, exclude=()) -> Optional[Replica]:
        """The healthy replica with the fewest outstanding
        router-dispatched requests."""
        alive = [r for r in self.replicas
                 if r.healthy and r not in exclude]
        if not alive:
            return None
        return min(alive, key=lambda r: r.outstanding)

    def _health_loop(self):
        while not self._stopping:
            for r in self.replicas:
                if self._stopping:
                    return
                try:
                    host, port = r.host_port()
                    conn = http.client.HTTPConnection(host, port,
                                                      timeout=2.0)
                    conn.request("GET", "/healthz")
                    ok = conn.getresponse().status == 200
                    conn.close()
                except OSError:
                    ok = False
                r.set_healthy(ok)
            time.sleep(self.health_interval_s)

    # ------------------------------------------------------------------
    def _proxy(self, handler: QuietHandler):
        counted = telemetry.counter(
            "dl4j_serving_router_requests_total",
            "requests dispatched by the router per replica and "
            "relayed HTTP status (replica=none -> no replica could "
            "take the request, 502)")
        # trace id minted at the fleet ingress (or adopted from the
        # client); _RELAY_REQ carries it into the replica, which
        # adopts it — the replica's `request` root span nests inside
        # the router's `req.route` envelope under one id
        tid = tracectx._clean_id(
            handler.headers.get(tracectx.TRACE_HEADER))
        if tid is None and tracectx.request_trace_enabled():
            tid = tracectx.mint_trace_id()
        handler._trace_id = tid
        t0_wall, t0_mono = time.time(), time.monotonic()

        def route_span(replica: str, status) -> None:
            if tid:
                telemetry.span_at(
                    "req.route", t0_wall,
                    time.monotonic() - t0_mono, trace=tid,
                    replica=replica, status=str(status))

        body = handler.read_body()
        req_headers = {h: handler.headers[h] for h in _RELAY_REQ
                       if handler.headers.get(h)}
        if tid:
            req_headers[tracectx.TRACE_HEADER] = tid
        tried = []
        while True:
            rep = self._pick(exclude=tried)
            if rep is None:
                counted.inc(replica="none", code="502")
                handler.send_json(
                    {"error": "no healthy replica available"}, 502,
                    {tracectx.TRACE_HEADER: tid} if tid else None)
                route_span("none", 502)
                return
            tried.append(rep)
            rep.begin()
            try:
                host, port = rep.host_port()
                conn = http.client.HTTPConnection(host, port,
                                                  timeout=120.0)
                conn.request("POST", handler.path, body=body,
                             headers=req_headers)
                resp = conn.getresponse()
                chunked = (resp.getheader("Transfer-Encoding", "")
                           .lower() == "chunked")
                resp_headers = {h: resp.getheader(h)
                                for h in _RELAY_RESP
                                if resp.getheader(h)}
                # which replica served is part of the verdict
                resp_headers[tracectx.REPLICA_HEADER] = rep.name
                if tid:
                    resp_headers.setdefault(tracectx.TRACE_HEADER,
                                            tid)
                status = resp.status
                if chunked:
                    # token stream: relay incrementally so the client
                    # sees each token the moment the replica emits it
                    # (no retry past this point — bytes are out)
                    self._relay_stream(handler, rep, resp,
                                       resp_headers, status, counted)
                    conn.close()
                    route_span(rep.name, status)
                    return
                payload = resp.read()
                conn.close()
            except OSError:
                # connection-level failure: out of rotation until the
                # next successful poll; the request retries elsewhere
                rep.set_healthy(False)
                continue
            finally:
                rep.end()
            counted.inc(replica=rep.name, code=str(status))
            ctype = resp_headers.pop("Content-Type",
                                     "application/json")
            handler.send_body(payload, ctype, status,
                              headers=resp_headers)
            route_span(rep.name, status)
            return

    def _relay_stream(self, handler, rep, resp, resp_headers, status,
                      counted):
        """Relay a chunked replica response (the :generate token
        stream) piece by piece. ``http.client`` de-chunks the replica
        side (``read1`` returns each frame as it lands); the router
        re-chunks toward the client. A replica failure mid-stream
        truncates the client's stream (``abort_chunks``); a client
        disconnect just stops the relay — the replica's own disconnect
        handling frees the sequence."""
        ctype = resp_headers.pop("Content-Type",
                                 "application/x-ndjson")
        counted.inc(replica=rep.name, code=str(status))
        handler.begin_chunks(ctype, status, headers=resp_headers)
        try:
            while True:
                piece = resp.read1(65536)
                if not piece:
                    break
                handler.send_chunk(piece)
        except OSError:
            # replica died mid-stream, or the client went away —
            # either way the stream cannot complete cleanly
            handler.abort_chunks()
            return
        handler.end_chunks()
