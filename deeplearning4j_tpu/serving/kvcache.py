"""Paged KV-cache residency for generative serving.

The decode phase of autoregressive inference is bound by KV-cache
memory, not FLOPs: every live sequence keeps ``2 * layers * len *
heads * head_dim`` activations resident between tokens. Allocating
that per-request as contiguous max-length tensors wastes HBM on the
gap between a sequence's current length and its ``max_tokens`` —
the fragmentation paged attention (vLLM) eliminates. This module is
that allocator for the TPU stack:

- One preallocated device array pair per pool — ``k`` / ``v`` shaped
  ``[n_layers, num_blocks, block_size, n_heads, head_dim]`` — carved
  into fixed-size **blocks** of ``block_size`` token slots. A per-layer
  view ``pool.k[l]`` is the ``[num_blocks, block, heads, head_dim]``
  paged layout the decode kernel gathers through.
- A **block table** per sequence: the ordered list of block ids
  holding its tokens. Block ids are shared across layers (layer ``l``
  of token ``t`` lives at ``k[l, table[t // block_size],
  t % block_size]``), so the table is one small int array per
  sequence, not one per layer.
- **Block 0 is reserved scratch**: padded decode-batch rows (slots
  with no live sequence) write their dummy KV there, so the fused
  step never branches on liveness for the write. It is never handed
  to a sequence.
- alloc/extend/free with occupancy accounting: gauges
  ``dl4j_kv_pool_blocks{state=free|live}`` / ``dl4j_kv_pool_bytes``,
  exhaustion counted into ``dl4j_kv_pool_shed_total`` and raised as
  :class:`PoolExhausted` (a :class:`ShedError` — HTTP 429 with a
  drain-rate-measured ``Retry-After`` upstream).

The pool's device bytes are a first-class **resident class** in
``diagnostics.memory_report`` (next to params / updater state), looked
up lazily via ``sys.modules`` so diagnostics keeps zero import edges
into serving. ``pool_report()`` is that join point; the report numbers
reconcile exactly with the gauges (same ``nbytes`` source).
"""
from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.serving.admission import ShedError

#: live pools, for memory_report / pool_report (weak: a retired pool
#: must not be kept resident by the diagnostics join)
_pools: "weakref.WeakSet[KVBlockPool]" = weakref.WeakSet()


class PoolExhausted(ShedError):
    """The KV pool has no free block for an alloc/extend — the
    generative analog of a full admission queue: shed (HTTP 429) with
    a measured ``Retry-After`` instead of queueing unboundedly."""

    def __init__(self, retry_after_s: float = 1.0):
        super().__init__("kv_pool", retry_after_s)


def _blocks_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_kv_pool_blocks",
        "KV-cache pool blocks by state (free | live) per pool — "
        "occupancy = live / (live + free); block 0 is reserved "
        "scratch and counted in neither state")


def _bytes_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_kv_pool_bytes",
        "preallocated device bytes of a KV-cache pool (k + v arrays; "
        "constant for the pool's lifetime — paged residency means "
        "occupancy moves, allocation does not)")


def _shed_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_kv_pool_shed_total",
        "generative requests shed because the KV pool had no free "
        "block (HTTP 429 + measured Retry-After upstream)")


class KVBlockPool:
    """A paged KV-cache pool: preallocated k/v device arrays plus the
    host-side block allocator.

    ``alloc(seq_id, n_tokens)`` reserves the block-table for a new
    sequence, ``extend(seq_id)`` grows it one token (chaining a new
    block at each ``block_size`` boundary), ``free(seq_id)`` returns
    every block to the free list — callable mid-batch, which is the
    whole point of iteration-level scheduling. The device arrays are
    functional values: the jitted decode step consumes ``pool.k`` /
    ``pool.v`` and the engine stores the updated arrays back with
    :meth:`update_arrays`.
    """

    def __init__(self, n_layers: int, num_blocks: int,
                 block_size: int, n_heads: int, head_dim: int, *,
                 dtype=np.float32, name: str = "model",
                 device_arrays: bool = True):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is "
                             "reserved scratch)")
        self.n_layers = int(n_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.name = name
        shape = (self.n_layers, self.num_blocks, self.block_size,
                 self.n_heads, self.head_dim)
        if device_arrays:
            import jax.numpy as jnp
            self.k = jnp.zeros(shape, dtype=dtype)
            self.v = jnp.zeros(shape, dtype=dtype)
        else:               # allocator-only pool (tests, sizing math)
            self.k = np.zeros(shape, dtype=dtype)
            self.v = np.zeros(shape, dtype=dtype)
        self._lock = threading.RLock()
        #: free block ids, LIFO (block 0 reserved — see module doc)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        self._lengths: Dict[object, int] = {}
        _pools.add(self)
        if telemetry.enabled():
            _bytes_gauge().set(self.pool_bytes, pool=self.name)
            self._export_occupancy()

    # -- sizing ---------------------------------------------------------
    @property
    def pool_bytes(self) -> int:
        """Preallocated device bytes (k + v) — the resident class."""
        return int(self.k.nbytes) + int(self.v.nbytes)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1          # minus the scratch block

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks a sequence of ``n_tokens`` occupies (ceil)."""
        return max(1, -(-int(n_tokens) // self.block_size))

    # -- occupancy ------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def live_blocks(self) -> int:
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    @property
    def occupancy(self) -> float:
        """live / usable, in [0, 1]."""
        return self.live_blocks / max(1, self.usable_blocks)

    @property
    def live_sequences(self) -> int:
        with self._lock:
            return len(self._tables)

    def _export_occupancy(self) -> None:
        if not telemetry.enabled():
            return
        g = _blocks_gauge()
        g.set(len(self._free), pool=self.name, state="free")
        g.set(sum(len(t) for t in self._tables.values()),
              pool=self.name, state="live")

    # -- lifecycle ------------------------------------------------------
    def alloc(self, seq_id, n_tokens: int) -> List[int]:
        """Reserve blocks for a new sequence of ``n_tokens`` prompt
        tokens. Raises :class:`PoolExhausted` (counting the shed)
        without partial allocation when the pool cannot hold it."""
        need = self.blocks_for(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already has a "
                                 f"block table")
            if need > len(self._free):
                _shed_counter().inc(pool=self.name)
                raise PoolExhausted()
            blocks = [self._free.pop() for _ in range(need)]
            self._tables[seq_id] = blocks
            self._lengths[seq_id] = int(n_tokens)
            self._export_occupancy()
            return list(blocks)

    def extend(self, seq_id, n_tokens: int = 1) -> List[int]:
        """Grow a sequence by ``n_tokens`` (decode appends one per
        step), chaining new block-table entries across ``block_size``
        boundaries. Returns the current table. On exhaustion raises
        :class:`PoolExhausted` with the sequence's existing blocks
        intact (the caller decides whether to retire it)."""
        with self._lock:
            if seq_id not in self._tables:
                raise KeyError(f"unknown sequence {seq_id!r}")
            new_len = self._lengths[seq_id] + int(n_tokens)
            need = self.blocks_for(new_len) - len(self._tables[seq_id])
            if need > len(self._free):
                _shed_counter().inc(pool=self.name)
                raise PoolExhausted()
            for _ in range(need):
                self._tables[seq_id].append(self._free.pop())
            self._lengths[seq_id] = new_len
            if need:
                self._export_occupancy()
            return list(self._tables[seq_id])

    def free(self, seq_id) -> int:
        """Return a sequence's blocks to the pool (EOS / max_tokens /
        client disconnect — all mid-batch paths). Idempotent; returns
        the number of blocks released."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            self._lengths.pop(seq_id, None)
            if not blocks:
                return 0
            self._free.extend(reversed(blocks))
            self._export_occupancy()
            return len(blocks)

    def table(self, seq_id) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def length(self, seq_id) -> int:
        with self._lock:
            return self._lengths[seq_id]

    def padded_table(self, seq_id, max_blocks: int) -> np.ndarray:
        """The sequence's block table as a fixed-width int32 row
        (padded with the scratch block 0) — the shape-stable form the
        jitted decode step consumes."""
        t = self.table(seq_id)
        if len(t) > max_blocks:
            raise ValueError(f"sequence {seq_id!r} spans {len(t)} "
                             f"blocks > table width {max_blocks}")
        return np.asarray(t + [0] * (max_blocks - len(t)), np.int32)

    def update_arrays(self, k, v) -> None:
        """Store the decode step's updated pool arrays (functional
        update: jit returns new values for the same buffers)."""
        self.k, self.v = k, v

    def report(self) -> dict:
        """The memory_report join row for this pool."""
        return {
            "pool": self.name,
            "bytes": self.pool_bytes,
            "blocks": {"free": self.free_blocks,
                       "live": self.live_blocks,
                       "reserved": 1,
                       "total": self.num_blocks},
            "occupancy": round(self.occupancy, 4),
            "live_sequences": self.live_sequences,
            "block_tokens": self.block_size,
            "layout": [self.n_layers, self.num_blocks, self.block_size,
                       self.n_heads, self.head_dim],
        }


def pool_report() -> List[dict]:
    """Reports for every live pool — the ``kv_pools`` resident class
    ``diagnostics.memory_report`` joins in (lazy ``sys.modules``
    lookup on its side; no import edge)."""
    return sorted((p.report() for p in list(_pools)),
                  key=lambda r: r["pool"])


def pool_resident_bytes() -> int:
    """Total preallocated KV bytes across live pools (the number that
    must reconcile with the summed ``dl4j_kv_pool_bytes`` gauge)."""
    return sum(p.pool_bytes for p in list(_pools))
