"""Request flight recorder: the last N served requests as a black box.

The PR-7 :class:`~deeplearning4j_tpu.common.diagnostics.FlightRecorder`
pattern recast for serving. Every completed request (any verdict —
200s, sheds, deadline 504s, client 499s) appends one bounded-ring
record: trace id, model, kind, verdict, per-phase millisecond
breakdown (from its
:class:`~deeplearning4j_tpu.common.tracectx.TraceContext`), queue
depth at completion, KV blocks, batch occupancy. The ring dumps as
JSONL plus a chrome trace of the span ring (so the offending
requests' ``req.*`` span trees ride along) on three triggers:

- **crash**: a lazily-installed ``sys.excepthook`` wrapper (one dump
  per process, chained to any previously installed hook);
- **shed storm**: :meth:`RequestRecorder.note_shed` keeps a sliding
  window of shed instants; when ``DL4J_TPU_REQREC_SHED_THRESHOLD``
  sheds land within ``DL4J_TPU_REQREC_SHED_WINDOW_S`` seconds the
  ring dumps once per storm (cooldown-limited) — the artifact that
  says WHICH requests were in flight when admission collapsed;
- **on demand**: ``POST /api/reqrec/dump`` on the replica server and
  the router.

``scripts/dl4j_requests.py`` renders a dump (or the live ring via
``GET /api/reqrec``) as a slowest-N table with the phase breakdown.

Env knobs (read at construction): ``DL4J_TPU_REQREC`` (default on),
``DL4J_TPU_REQREC_CAPACITY`` (ring size, default 512),
``DL4J_TPU_REQREC_DIR`` (default ``flightrec``, beside the training
recorder's dumps), ``DL4J_TPU_REQREC_SHED_THRESHOLD`` (default 20),
``DL4J_TPU_REQREC_SHED_WINDOW_S`` (default 5),
``DL4J_TPU_REQREC_STORM_COOLDOWN_S`` (default 60).
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque
from typing import List, Optional

from deeplearning4j_tpu.common import telemetry

log = logging.getLogger("deeplearning4j_tpu")

SCHEMA_VERSION = 1


def _dumps_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_reqrec_dumps_total",
        "request-flight-recorder dumps, by trigger reason "
        "(crash | shed_storm | api)")


def _depth_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_reqrec_ring_depth",
        "per-request records currently held in the request flight "
        "recorder's bounded ring")


class RequestRecorder:
    """Bounded ring of per-request records with storm/crash dumps."""

    _instance: Optional["RequestRecorder"] = None
    _instance_lock = threading.Lock()

    def __init__(self):
        env = os.environ.get
        self.enabled = env("DL4J_TPU_REQREC", "1") not in (
            "0", "false", "False")
        self.capacity = max(1, int(env("DL4J_TPU_REQREC_CAPACITY",
                                       "512")))
        self.dir = env("DL4J_TPU_REQREC_DIR", "") or \
            env("DL4J_TPU_FLIGHT_RECORDER_DIR", "") or "flightrec"
        self.shed_threshold = max(1, int(
            env("DL4J_TPU_REQREC_SHED_THRESHOLD", "20")))
        self.shed_window_s = float(
            env("DL4J_TPU_REQREC_SHED_WINDOW_S", "5"))
        self.storm_cooldown_s = float(
            env("DL4J_TPU_REQREC_STORM_COOLDOWN_S", "60"))
        self._ring: "deque[dict]" = deque()
        self._sheds: "deque[float]" = deque()
        self._lock = threading.Lock()
        self._installed = False
        self._prev_excepthook = None
        self._crash_dumped = False
        self._last_storm_dump = -float("inf")
        self._dump_seq = 0

    @classmethod
    def get(cls) -> "RequestRecorder":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance._uninstall()
            cls._instance = None

    # -- crash hook ----------------------------------------------------
    def _install(self) -> None:
        """Lazily wrap ``sys.excepthook`` on the first record — the
        training FlightRecorder and this one chain (each restores the
        previous hook after its own dump)."""
        if self._installed:
            return
        self._installed = True
        self._prev_excepthook = sys.excepthook

        def _hook(tp, val, tb):
            try:
                if not self._crash_dumped:
                    self._crash_dumped = True
                    self.dump("crash", event={"error": repr(val)})
            finally:
                (self._prev_excepthook or sys.__excepthook__)(
                    tp, val, tb)

        sys.excepthook = _hook

    def _uninstall(self) -> None:
        if self._installed and self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        self._installed = False

    # -- recording -----------------------------------------------------
    def record(self, ctx, verdict, **extra) -> None:
        """Append one completed request. ``ctx`` is its TraceContext
        (ignored when falsy — the tracing gate also gates the
        recorder); ``extra`` carries queue_depth / kv_blocks / batch
        facts the serving layer knows at completion."""
        if not self.enabled or not ctx:
            return
        if not self._installed:
            self._install()
        rec = {
            "t": time.time(),
            "trace_id": ctx.trace_id,
            "model": ctx.model,
            "kind": ctx.kind,
            "verdict": str(verdict),
            "total_ms": ctx.elapsed_s() * 1e3,
            "phase_ms": {k: round(v, 3)
                         for k, v in ctx.phase_ms().items()},
        }
        attrs = dict(getattr(ctx, "attrs", {}) or {})
        attrs.update(extra)
        rec.update({k: v for k, v in attrs.items()
                    if k not in rec})
        with self._lock:
            self._ring.append(rec)
            while len(self._ring) > self.capacity:
                self._ring.popleft()
            depth = len(self._ring)
        if telemetry.enabled():
            _depth_gauge().set(depth)

    def records(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._ring)
        return out[-int(n):] if n else out

    # -- shed-storm detection ------------------------------------------
    def note_shed(self, model: str, reason: str) -> Optional[str]:
        """Count one shed; when the sliding window crosses the storm
        threshold, dump (cooldown-limited). Returns the dump path when
        a storm fired."""
        if not self.enabled:
            return None
        now = time.monotonic()
        with self._lock:
            self._sheds.append(now)
            horizon = now - self.shed_window_s
            while self._sheds and self._sheds[0] < horizon:
                self._sheds.popleft()
            storm = (len(self._sheds) >= self.shed_threshold
                     and now - self._last_storm_dump
                     >= self.storm_cooldown_s)
            if storm:
                self._last_storm_dump = now
                n_sheds = len(self._sheds)
        if not storm:
            return None
        return self.dump("shed_storm",
                         event={"model": model, "reason": reason,
                                "sheds_in_window": n_sheds,
                                "window_s": self.shed_window_s})

    # -- dumping -------------------------------------------------------
    def dump(self, reason: str,
             event: Optional[dict] = None) -> Optional[str]:
        """Write the ring as ``reqrec_<pid>_<reason>[_<seq>].jsonl``
        (meta line + one record per request) plus a chrome trace of
        the span ring; returns the JSONL path. Unlike the training
        recorder, repeated dumps per reason are allowed (the API
        trigger, successive storms after cooldown) — the sequence
        number keeps artifacts distinct."""
        if not self.enabled:
            return None
        with self._lock:
            ring = list(self._ring)
            self._dump_seq += 1
            seq = self._dump_seq
        base = os.path.join(
            self.dir, f"reqrec_{os.getpid()}_{reason}_{seq}")
        path = base + ".jsonl"
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps({
                    "record": "meta",
                    "schema_version": SCHEMA_VERSION,
                    "reason": reason,
                    "time": time.time(),
                    "pid": os.getpid(),
                    "n_requests": len(ring),
                    "ring_capacity": self.capacity,
                    "event": event,
                }) + "\n")
                for rec in ring:
                    f.write(json.dumps(rec) + "\n")
            trace = telemetry.export_chrome_trace(base + ".trace.json")
        except Exception as e:      # noqa: BLE001 — dumping is best-
            log.warning("request recorder dump failed: %r", e)
            return None
        if telemetry.enabled():
            _dumps_counter().inc(reason=reason)
        log.warning("request recorder: dumped %d request records to "
                    "%s (+ %s) reason=%s", len(ring), path, trace,
                    reason)
        return path


telemetry.on_reset(RequestRecorder._reset_for_tests)


def get() -> RequestRecorder:
    return RequestRecorder.get()
