"""Versioned model registry with warmed, atomically hot-swappable
serving state.

Reference parity: the model-server half of the DL4J serving story — a
named catalog of models, each with numbered versions, where exactly
one version per name is *live* and replacing it never drops an
in-flight request.

Loading dispatches on the artifact:

- ``*.zip`` (ModelSerializer / SameDiff archives) →
  :meth:`ModelSerializer.restore_model` (which sniffs SameDiff zips)
- ``*.h5`` / ``*.keras`` → ``KerasModelImport``
- ``*.onnx`` → ``modelimport.onnx.import_onnx``
- any in-memory model object passes straight through

Every registered version is wrapped in a
:class:`~deeplearning4j_tpu.serving.batcher.ServingBatcher` and —
when ``warmup_shape`` is given — warmed: each batch-size bucket's XLA
program compiles *before* the version goes live, so the first real
request never pays the compile stall. The version's ``RetraceGuard``
signature count is frozen at warmup end;
:meth:`ModelRegistry.retraces_since_warmup` returning 0 is the proof
that steady-state serving never recompiled.

Hot-swap protocol (``register`` on an existing name): load → warm →
flip the current pointer under the registry lock → retire the old
version. The old batcher keeps draining its queue (its ``shutdown``
flushes pending requests), so swaps are hitless.
"""
from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.compilecache import RetraceGuard
from deeplearning4j_tpu.serving.batcher import ServingBatcher


class ModelStatus:
    LOADING = "LOADING"
    WARMING = "WARMING"
    READY = "READY"
    RETIRED = "RETIRED"


def load_model(path):
    """Load a serving artifact, dispatching on its extension."""
    p = str(path)
    if p.endswith((".h5", ".keras")):
        from deeplearning4j_tpu.modelimport.keras.importer import \
            KerasModelImport
        return KerasModelImport.import_keras_model_and_weights(p)
    if p.endswith(".onnx"):
        from deeplearning4j_tpu.modelimport.onnx import import_onnx
        return import_onnx(p)
    from deeplearning4j_tpu.utils.serializer import ModelSerializer
    return ModelSerializer.restore_model(p)


class _SameDiffAdapter:
    """Serve a ``SameDiff`` graph through the generic batcher surface:
    ``output(batch) -> array``. Placeholder/output names are explicit
    or inferred (single placeholder, single terminal op output)."""

    def __init__(self, sd, input_name: Optional[str] = None,
                 output_name: Optional[str] = None):
        from deeplearning4j_tpu.autodiff.samediff import VariableType
        self.sd = sd
        if input_name is None:
            phs = [v.name for v in sd.vars.values()
                   if v.var_type == VariableType.PLACEHOLDER]
            if len(phs) != 1:
                raise ValueError(
                    f"cannot infer the input placeholder from "
                    f"{phs!r}; pass input_name=")
            input_name = phs[0]
        if output_name is None:
            consumed = {n for op in sd.ops for n in op.inputs}
            outs = [n for op in sd.ops for n in op.outputs
                    if n not in consumed]
            if len(outs) != 1:
                raise ValueError(
                    f"cannot infer the output from terminal values "
                    f"{outs!r}; pass output_name=")
            output_name = outs[0]
        self.input_name = input_name
        self.output_name = output_name

    def output(self, x):
        return self.sd.output({self.input_name: x},
                              [self.output_name])[self.output_name]


class _OnnxAdapter:
    """Serve an imported ONNX graph (``OnnxImporter``) the same way:
    single declared-or-inferred input, first graph output."""

    def __init__(self, imp, input_name: Optional[str] = None,
                 output_name: Optional[str] = None):
        ins = [input_name] if input_name else list(imp.placeholders)
        if len(ins) != 1:
            raise ValueError(f"cannot infer the input from ONNX "
                             f"placeholders {ins!r}; pass input_name=")
        self.imp = imp
        self.input_name = ins[0]
        self.outputs = [output_name] if output_name else None

    def output(self, x):
        return self.imp.output({self.input_name: x}, self.outputs)[0]


class ModelVersion:
    """One immutable (model, batcher, guard) serving unit."""

    def __init__(self, name: str, version: int, model,
                 batcher: ServingBatcher, source: str,
                 latency_slo_ms: Optional[float] = None):
        self.name = name
        self.version = version
        self.model = model
        self.batcher = batcher
        self.source = source
        #: per-model latency SLO driving the adaptive admission budget
        self.latency_slo_ms = latency_slo_ms
        self.status = ModelStatus.LOADING
        self.created = time.time()
        self.warm_signatures = 0      # guard count frozen at warmup end

    @property
    def guard(self) -> RetraceGuard:
        return self.batcher.guard

    def retraces_since_warmup(self) -> int:
        """Distinct signatures compiled after warmup finished — the
        number that must stay 0 in steady state."""
        return self.guard.n_signatures - self.warm_signatures

    def describe(self) -> dict:
        return {
            "name": self.name,
            "version": self.version,
            "status": self.status,
            "source": self.source,
            "warm_buckets": list(self.batcher.buckets),
            "signatures": self.guard.n_signatures,
            "retraces_since_warmup": self.retraces_since_warmup(),
            "mode": self.batcher.mode,
            "flush_policy": self.batcher.flush_policy,
            "generative": self.batcher.is_generative,
            "latency_slo_ms": self.latency_slo_ms,
            "created": self.created,
        }


class ModelRegistry:
    """Named, versioned models with an atomic live pointer per name."""

    def __init__(self, mesh=None, *,
                 default_buckets: Sequence[int] = (8, 32),
                 batch_window_ms: float = 2.0,
                 queue_limit: int = 256,
                 flush_policy: str = "continuous"):
        self.mesh = mesh
        self.default_buckets = tuple(default_buckets)
        self.batch_window_ms = batch_window_ms
        self.queue_limit = queue_limit
        self.flush_policy = flush_policy
        self._lock = threading.Lock()
        self._current: Dict[str, ModelVersion] = {}
        self._versions: Dict[str, List[ModelVersion]] = {}

    # ------------------------------------------------------------------
    def register(self, name: str, model, *,
                 warmup_shape: Optional[Sequence[int]] = None,
                 warmup_dtype=None,
                 buckets: Optional[Sequence[int]] = None,
                 batch_window_ms: Optional[float] = None,
                 flush_policy: Optional[str] = None,
                 mode: str = "dense",
                 tensor_parallel: Optional[int] = None,
                 latency_slo_ms: Optional[float] = None,
                 input_name: Optional[str] = None,
                 output_name: Optional[str] = None,
                 generate: Optional[dict] = None,
                 param_dtype: Optional[str] = None) -> ModelVersion:
        """Register (or hot-swap) the live version of ``name``.

        ``model`` is an in-memory model or an artifact path (zip / h5
        / keras / onnx). ``warmup_shape`` (one request's shape without
        the batch dim) triggers per-bucket pre-compilation BEFORE the
        version goes live; without it the version serves cold (first
        request compiles). ``input_name``/``output_name`` disambiguate
        SameDiff placeholders when serving a graph.

        ``mode`` picks the parameter residency: ``"dense"`` (params
        replicated, the classic path), or ``"sharded"``/``"fsdp"`` —
        the checkpoint stays resident 1/N-sharded over the registry
        mesh between requests (``serving.residency``), optionally ×tp
        on a 2D ``(data, model)`` mesh via ``tensor_parallel``.
        Outputs stay bitwise-equal to dense in every mode.
        ``flush_policy`` (``"continuous"`` default) and
        ``latency_slo_ms`` (arms the SLO-adaptive admission budget and
        is surfaced to the server) ride on the version.

        ``generate`` configures the generative decode engine for a
        model with a prefill/decode_step surface (``kv_blocks``,
        ``kv_block_size``, ``prompt_buckets``, ``decode_buckets``,
        ``max_seq_len``, ``paged``, ``kv_dtype`` — defaulting from
        ``DL4J_TPU_KV_DTYPE``) — its prefill/commit/decode programs
        warm with the version, so the zero-retrace proof covers
        :generate too.

        ``param_dtype`` (``"bf16"`` | ``"int8"``; defaults from
        ``DL4J_TPU_SERVING_PARAM_DTYPE``) stores the resident shards of
        a ``sharded``/``fsdp`` version low-precision — half or a
        quarter of ``dl4j_serving_param_resident_bytes`` — with compute
        restored to float32 post-gather (tolerance-level, not bitwise,
        outputs)."""
        if param_dtype is None:
            import os
            param_dtype = (os.environ.get(
                "DL4J_TPU_SERVING_PARAM_DTYPE") or None)
        if isinstance(model, (str, Path)):
            source = str(model)
            model = load_model(model)
        else:
            source = f"memory:{type(model).__name__}"
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.modelimport.onnx.importer import \
            OnnxImporter
        if isinstance(model, SameDiff):
            model = _SameDiffAdapter(model, input_name, output_name)
        elif isinstance(model, OnnxImporter):
            model = _OnnxAdapter(model, input_name, output_name)

        with self._lock:
            version_no = len(self._versions.get(name, ())) + 1
        guard = RetraceGuard(f"serving:{name}:v{version_no}")
        batcher = ServingBatcher(
            model, buckets or self.default_buckets, self.mesh,
            name=name,
            batch_window_ms=(batch_window_ms
                             if batch_window_ms is not None
                             else self.batch_window_ms),
            queue_limit=self.queue_limit, guard=guard,
            flush_policy=(flush_policy if flush_policy is not None
                          else self.flush_policy),
            mode=mode, tensor_parallel=tensor_parallel,
            generate=generate, param_dtype=param_dtype)
        ver = ModelVersion(name, version_no, model, batcher, source,
                           latency_slo_ms=latency_slo_ms)

        if warmup_shape is not None:
            ver.status = ModelStatus.WARMING
            import numpy as np
            secs = batcher.warmup(warmup_shape,
                                  warmup_dtype or np.float32)
            telemetry.histogram(
                "dl4j_serving_warmup_total_seconds",
                "whole-version warmup wall time: every bucket "
                "compiled + executed once (seconds)").observe(
                    secs, model=name)
        if generate is not None and batcher.is_generative:
            ver.status = ModelStatus.WARMING
            secs = batcher.warmup_generate()
            telemetry.histogram(
                "dl4j_serving_warmup_total_seconds",
                "whole-version warmup wall time: every bucket "
                "compiled + executed once (seconds)").observe(
                    secs, model=name)
        ver.warm_signatures = guard.n_signatures
        ver.status = ModelStatus.READY

        # atomic flip: requests resolving `name` after this line land
        # on the new version; the old one drains and retires
        with self._lock:
            old = self._current.get(name)
            self._current[name] = ver
            self._versions.setdefault(name, []).append(ver)
        if old is not None:
            telemetry.counter(
                "dl4j_serving_hot_swaps_total",
                "live-version replacements per model (old version "
                "drained, no request dropped)").inc(model=name)
            old.status = ModelStatus.RETIRED
            # flushes anything still queued on the old version, then
            # stops its worker — in-flight futures all resolve
            old.batcher.shutdown()
        return ver

    # ------------------------------------------------------------------
    def model(self, name: str) -> ModelVersion:
        """The live version of ``name`` (KeyError when unknown)."""
        with self._lock:
            return self._current[name]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._current)

    def describe(self) -> List[dict]:
        """Every name's versions, live one first (GET /v1/models)."""
        with self._lock:
            items = {n: list(vs) for n, vs in self._versions.items()}
            current = dict(self._current)
        out = []
        for name in sorted(items):
            live = current.get(name)
            out.append({
                "name": name,
                "live_version": live.version if live else None,
                "versions": [v.describe() for v in items[name]],
            })
        return out

    def ready(self) -> bool:
        """At least one live version is READY (the /readyz answer)."""
        with self._lock:
            return any(v.status == ModelStatus.READY
                       for v in self._current.values())

    def retraces_since_warmup(self, name: str) -> int:
        return self.model(name).retraces_since_warmup()

    def shutdown(self):
        """Drain and stop every live batcher (pending requests are
        flushed, not dropped)."""
        with self._lock:
            vers = list(self._current.values())
        for v in vers:
            v.batcher.shutdown()
            v.status = ModelStatus.RETIRED
