"""SLO error-budget accounting over the serving latency stream.

SRE-style burn-rate accounting applied to the per-model
``dl4j_serving_total_seconds`` observations the admission controller
already collects: each completed request is classified in/out of SLO
against the model's ``latency_slo_ms``, and per-model rolling windows
answer the three questions a pager needs —

- **in-SLO fraction**: what share of recent requests met the SLO,
  over a fast (default 5m) and a slow (default 1h) window;
- **budget remaining**: with an availability target of ``target``
  (default 0.99 → a 1% error budget), how much of the slow window's
  budget is left (1.0 = untouched, 0.0 = exhausted, negative =
  overdrawn);
- **burn rate**: violation fraction ÷ error budget per window — the
  multi-window signal (fast AND slow both >1 means "burning now and
  it's not a blip"). The AIMD admission controller logs the fast burn
  rate against every budget shrink, so a shrink decision is
  explainable after the fact.

Surfaced three ways: ``dl4j_slo_*`` gauges on ``/metrics``, the
``GET /api/slo`` report on both the replica server and the router,
and :meth:`SLOTracker.report` for tests/tools.

One process-wide tracker (replicas share a process in the router
harness, so the router's endpoint reads the same object); windows and
target are env-tunable (``DL4J_TPU_SLO_TARGET``,
``DL4J_TPU_SLO_FAST_S``, ``DL4J_TPU_SLO_SLOW_S``) and ``now`` is
injectable everywhere for deterministic tests.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from deeplearning4j_tpu.common import telemetry

#: per-model event-window bound — 1h of history at bounded memory;
#: beyond it the oldest events age out early (conservative: the
#: report then covers a shorter effective window, never a stale one)
_MAX_EVENTS = 8192


def _in_fraction_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_slo_in_fraction",
        "fraction of completed requests inside the model's "
        "latency_slo_ms over the rolling window "
        "(window=fast|slow), per model")


def _burn_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_slo_burn_rate",
        "SLO error-budget burn rate per rolling window "
        "(violation fraction / error budget; 1.0 = burning exactly "
        "at budget, >1 = on course to exhaust it), per model")


def _budget_gauge() -> telemetry.Gauge:
    return telemetry.gauge(
        "dl4j_slo_budget_remaining",
        "share of the slow window's error budget still unspent "
        "(1 = untouched, 0 = exhausted, negative = overdrawn), "
        "per model")


class SLOTracker:
    """Per-model rolling-window in-SLO / burn-rate accounting."""

    _instance: Optional["SLOTracker"] = None
    _instance_lock = threading.Lock()

    def __init__(self, target: Optional[float] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None):
        self.target = float(target if target is not None else
                            os.environ.get("DL4J_TPU_SLO_TARGET",
                                           "0.99"))
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got "
                             f"{self.target}")
        self.fast_window_s = float(
            fast_window_s if fast_window_s is not None else
            os.environ.get("DL4J_TPU_SLO_FAST_S", "300"))
        self.slow_window_s = float(
            slow_window_s if slow_window_s is not None else
            os.environ.get("DL4J_TPU_SLO_SLOW_S", "3600"))
        self._lock = threading.Lock()
        #: per model: (monotonic_ts, in_slo) completion events
        self._events: Dict[str, Deque[Tuple[float, bool]]] = {}
        self._slo_ms: Dict[str, float] = {}

    @classmethod
    def get(cls) -> "SLOTracker":
        inst = cls._instance
        if inst is not None:
            return inst
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def _reset_for_tests(cls):
        with cls._instance_lock:
            cls._instance = None

    # ------------------------------------------------------------------
    def observe(self, model: str, seconds: float, slo_ms: float,
                now: Optional[float] = None) -> None:
        """Classify one completed request against ``slo_ms`` and
        refresh the model's gauges. ``now`` (monotonic) is injectable
        for deterministic tests."""
        now = time.monotonic() if now is None else now
        ok = (seconds * 1e3) <= float(slo_ms)
        with self._lock:
            self._slo_ms[model] = float(slo_ms)
            events = self._events.setdefault(
                model, deque(maxlen=_MAX_EVENTS))
            events.append((now, ok))
        if telemetry.enabled():
            self._publish(model, now)

    def _window_stats(self, events, horizon: float
                      ) -> Tuple[int, int]:
        """(n, violations) among events at/after ``horizon``."""
        n = bad = 0
        for ts, ok in reversed(events):
            if ts < horizon:
                break
            n += 1
            if not ok:
                bad += 1
        return n, bad

    def _stats_locked(self, model: str, now: float) -> dict:
        events = self._events.get(model)
        if not events:
            return {}
        budget = 1.0 - self.target
        out = {"slo_ms": self._slo_ms.get(model),
               "target": self.target, "windows": {}}
        for label, win in (("fast", self.fast_window_s),
                           ("slow", self.slow_window_s)):
            n, bad = self._window_stats(events, now - win)
            frac_in = (n - bad) / n if n else 1.0
            burn = (bad / n) / budget if n else 0.0
            out["windows"][label] = {
                "window_s": win, "n": n,
                "in_slo_fraction": frac_in,
                "burn_rate": burn}
        slow = out["windows"]["slow"]
        out["budget_remaining"] = 1.0 - slow["burn_rate"]
        return out

    def _publish(self, model: str, now: float) -> None:
        with self._lock:
            stats = self._stats_locked(model, now)
        if not stats:
            return
        for label, w in stats["windows"].items():
            _in_fraction_gauge().set(w["in_slo_fraction"],
                                     model=model, window=label)
            _burn_gauge().set(w["burn_rate"], model=model,
                              window=label)
        _budget_gauge().set(stats["budget_remaining"], model=model)

    # ------------------------------------------------------------------
    def burn_rate(self, model: str, window: str = "fast",
                  now: Optional[float] = None) -> Optional[float]:
        """The named window's current burn rate (None before any
        observation for ``model``)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            stats = self._stats_locked(model, now)
        if not stats:
            return None
        return stats["windows"][window]["burn_rate"]

    def report(self, now: Optional[float] = None) -> dict:
        """The ``GET /api/slo`` document: per-model windows, in-SLO
        fractions, burn rates, and remaining budget."""
        now = time.monotonic() if now is None else now
        with self._lock:
            models = {m: self._stats_locked(m, now)
                      for m in self._events}
        return {"target": self.target,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "models": models}


telemetry.on_reset(SLOTracker._reset_for_tests)
