"""Model-serving subsystem: versioned registry, shape-bucketed
batching, admission control, and the HTTP inference server.

The reference ecosystem pairs ``ParallelInference`` with a
network-facing model server; this package is that layer for the TPU
stack. The serving-latency discipline follows TVM (PAPERS.md
1802.04799): compilation happens at *warmup*, never on a request —
every flush is padded up to a pre-jitted batch-size bucket, and a
``RetraceGuard`` per model version proves steady state never
recompiles.

    from deeplearning4j_tpu.serving import ModelRegistry, InferenceServer

    reg = ModelRegistry()
    reg.register("mnist", net, warmup_shape=(28, 28, 1),
                 buckets=(8, 32), latency_slo_ms=50.0)
    srv = InferenceServer(reg).start(port=8500)
    # POST /v1/models/mnist:predict   {"inputs": [[...], ...]}

Scale-out pieces: flushes are *continuous* by default (the worker
flushes the instant the device frees — ``flush_policy="window"``
restores the fixed-window seed), admission budgets adapt to a
per-model ``latency_slo_ms`` with a drain-rate-derived ``Retry-After``,
the raw ``.npy`` request/response path is zero-copy, ``mode="sharded"``
/ ``"fsdp"`` keeps a checkpoint resident 1/N-sharded over a mesh
between requests (``serving.residency``), and :class:`ServingRouter`
fronts N replicas with least-loaded dispatch and fleet-wide
warm-then-drain rollouts.

Generative serving: a model with a ``prefill``/``decode_step``
surface registered with ``generate={...}`` gets a paged KV-cache
pool (:class:`KVBlockPool`) and a continuous-batching decode engine
(:class:`DecodeEngine`) — ``POST /v1/models/<name>:generate`` streams
tokens as chunked ndjson the moment they decode.

Request observatory: every request carries a trace id
(``common.tracectx``) through admission, batching and the device into
a connected span tree; :class:`SLOTracker` folds the total-latency
stream into per-model error-budget burn rates (``GET /api/slo``), and
:class:`RequestRecorder` keeps the flight-recorder ring of completed
requests with per-phase timings (``GET /api/reqrec``, dumps on crash
or shed storm).
"""
from deeplearning4j_tpu.serving.admission import (AdmissionController,
                                                  DeadlineExceeded,
                                                  ShedError)
from deeplearning4j_tpu.serving.batcher import ServingBatcher
from deeplearning4j_tpu.serving.generative import (DecodeEngine,
                                                   TokenStream)
from deeplearning4j_tpu.serving.kvcache import (KVBlockPool,
                                                PoolExhausted)
from deeplearning4j_tpu.serving.registry import (ModelRegistry,
                                                 ModelStatus,
                                                 ModelVersion)
from deeplearning4j_tpu.serving.reqrec import RequestRecorder
from deeplearning4j_tpu.serving.router import ServingRouter
from deeplearning4j_tpu.serving.server import InferenceServer
from deeplearning4j_tpu.serving.slo import SLOTracker

__all__ = [
    "AdmissionController", "DeadlineExceeded", "ShedError",
    "ServingBatcher", "ModelRegistry", "ModelStatus", "ModelVersion",
    "InferenceServer", "ServingRouter",
    "DecodeEngine", "TokenStream", "KVBlockPool", "PoolExhausted",
    "SLOTracker", "RequestRecorder",
]
