"""HTTP inference server over the model registry.

Zero-dependency stdlib ``ThreadingHTTPServer`` (the ``ui.server``
pattern, via the shared ``common.httputil`` plumbing). One handler
thread per connection blocks on its request's Future while the
per-model batcher aggregates concurrent requests into bucket-padded
flushes.

Endpoints:

- ``POST /v1/models/<name>:predict`` — JSON body
  ``{"inputs": [[...], ...], "deadline_ms": optional}`` (row-major
  nested lists, leading batch dim) → ``{"outputs": ..., "model",
  "version", "batch"}``; or a raw ``.npy`` body
  (``Content-Type: application/octet-stream``) → raw ``.npy``
  response. ``X-Deadline-Ms`` header works for both body types.
- ``GET /v1/models`` — names, versions, status, warm buckets.
- ``GET /healthz`` — process liveness (200 while serving).
- ``GET /readyz`` — 200 once ≥1 model is READY and not draining.
- ``GET /metrics`` — the process-wide Prometheus registry.
- ``GET /api/slo`` — per-model in-SLO fraction / burn rates /
  remaining error budget (``serving.slo.SLOTracker.report``).
- ``GET /api/reqrec`` — the request flight recorder's live ring
  (``?n=`` caps the tail); ``POST /api/reqrec/dump`` forces a dump.

Request observatory: every request gets a
:class:`~deeplearning4j_tpu.common.tracectx.TraceContext` at ingress
(trace id minted, or adopted from ``X-Dl4j-Trace-Id``; echoed on the
response), phase spans (``admit``/``queue``/``batch_wait``/``device``/
``serialize``; ``stream`` + per-token instants for generate) land in
the chrome-trace ring under one ``request`` root span, the total
latency carries the trace id as a histogram exemplar, and the
completed request is appended to the
:class:`~deeplearning4j_tpu.serving.reqrec.RequestRecorder` ring
(sheds feed its storm detector). ``DL4J_TPU_REQUEST_TRACE=0``
disables all of it.

The raw ``.npy`` path is **zero-copy** end to end: the request body is
parsed with ``httputil.npy_view`` (an ndarray aliasing the received
bytes — no json/base64 detour, no second ``np.array``), and the
response streams ``npy_header`` + the result array's own buffer via
``send_body_parts`` (no ``np.save``-into-BytesIO materialization).
``bench_serving.py`` measures the per-request tax this removes.

Every completed request's total latency feeds
``AdmissionController.observe_total`` — the observation stream behind
the SLO-adaptive budget and the measured ``Retry-After`` — and a
version's ``latency_slo_ms`` is wired into the controller the first
time the version serves.

Status mapping: shed (queue full) → 429 + ``Retry-After``; draining →
503 + ``Retry-After``; deadline expired (at admission — fast-fail
before a slot is taken — or while queued) → 504; unknown model → 404;
bad body → 400.
"""
from __future__ import annotations

import json
import re
import threading
import time
from concurrent import futures
from typing import Optional

import numpy as np

from deeplearning4j_tpu.common import telemetry, tracectx
from deeplearning4j_tpu.common.httputil import (QuietHandler, npy_header,
                                                npy_view,
                                                start_http_server)
from deeplearning4j_tpu.serving import reqrec
from deeplearning4j_tpu.serving.admission import (AdmissionController,
                                                  DeadlineExceeded,
                                                  ShedError,
                                                  deadline_after_ms)
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.slo import SLOTracker

_PREDICT_RE = re.compile(r"^/v1/models/([^/:]+):predict$")
_GENERATE_RE = re.compile(r"^/v1/models/([^/:]+):generate$")

_NPY_TYPES = ("application/octet-stream", "application/x-npy")


def _query_int(path: str, key: str, default: int) -> int:
    """``?key=N`` from a request path (default on absence/garbage)."""
    from urllib.parse import parse_qs, urlsplit
    try:
        vals = parse_qs(urlsplit(path).query).get(key)
        return int(vals[0]) if vals else default
    except (ValueError, TypeError, IndexError):
        return default


class InferenceServer:
    """Serve a :class:`ModelRegistry` over HTTP."""

    def __init__(self, registry: ModelRegistry,
                 admission: Optional[AdmissionController] = None,
                 *, request_timeout_s: float = 60.0):
        self.registry = registry
        self.admission = admission if admission is not None \
            else AdmissionController()
        #: cap on how long a handler thread waits for its Future when
        #: the request carries no deadline
        self.request_timeout_s = request_timeout_s
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None

    # ------------------------------------------------------------------
    def start(self, port: int = 0) -> "InferenceServer":
        """Serve on ``DL4J_TPU_HTTP_HOST``:port (0 picks a free port;
        see ``self.port``). Idempotent."""
        if self._httpd is not None:
            return self
        server = self

        class Handler(QuietHandler):
            def do_GET(self):               # noqa: N802
                if self.path == "/v1/models":
                    self.send_json({"models":
                                    server.registry.describe()})
                elif self.path == "/healthz":
                    self.send_body(b"ok\n", "text/plain")
                elif self.path == "/readyz":
                    ok = (server.registry.ready()
                          and not server.admission.draining)
                    self.send_body(b"ready\n" if ok else b"not ready\n",
                                   "text/plain", 200 if ok else 503)
                elif self.path == "/metrics":
                    self.send_metrics()
                elif self.path == "/api/slo":
                    self.send_json(SLOTracker.get().report())
                elif self.path.split("?")[0] == "/api/reqrec":
                    n = _query_int(self.path, "n", 100)
                    self.send_json(
                        {"requests": reqrec.get().records(n)})
                else:
                    self.send_json({"error": "not found"}, 404)

            def do_POST(self):              # noqa: N802
                m = _PREDICT_RE.match(self.path)
                if m:
                    server._predict(self, m.group(1))
                    return
                g = _GENERATE_RE.match(self.path)
                if g:
                    server._generate(self, g.group(1))
                    return
                if self.path == "/api/reqrec/dump":
                    path = reqrec.get().dump("api")
                    self.send_json({"path": path},
                                   200 if path else 503)
                    return
                self.send_json({"error": "not found"}, 404)

        self._httpd, self._thread = start_http_server(Handler, port)
        self.port = self._httpd.server_address[1]
        return self

    def stop(self, drain: bool = True, timeout: float = 30.0):
        """Stop serving. With ``drain`` (default), admission first
        rejects new work (503) and in-flight requests finish before
        the listener closes — the graceful path."""
        if self._httpd is None:
            return
        if drain:
            self.admission.drain(timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        self._thread = None
        self.port = None

    @property
    def url(self) -> Optional[str]:
        if not self.port:
            return None
        host = self._httpd.server_address[0] if self._httpd else \
            "127.0.0.1"
        if host in ("0.0.0.0", "::"):   # wildcard bind: loopback works
            host = "127.0.0.1"
        return f"http://{host}:{self.port}"

    # ------------------------------------------------------------------
    def _finish_request(self, ctx, verdict) -> None:
        """Close a request's trace (root span with the verdict) and
        append it to the flight-recorder ring."""
        if not ctx:
            return
        ctx.finish(verdict)
        reqrec.get().record(
            ctx, verdict,
            queue_depth=self.admission.inflight(ctx.model))

    def _predict(self, handler: QuietHandler, name: str):
        ctx = tracectx.start(name, "predict",
                             handler.headers.get(tracectx.TRACE_HEADER))
        handler._trace_id = ctx.trace_id if ctx else None
        with tracectx.bind(ctx):
            self._predict_traced(handler, name, ctx)

    def _predict_traced(self, handler: QuietHandler, name: str, ctx):
        counted = telemetry.counter(
            "dl4j_serving_requests_total",
            "predict requests by model and HTTP status code")
        trace_headers = ({tracectx.TRACE_HEADER: ctx.trace_id}
                         if ctx else {})

        def finish_json(obj, code, headers=None):
            counted.inc(model=name, code=str(code))
            hdrs = dict(trace_headers)
            if headers:
                hdrs.update(headers)
            with ctx.phase("serialize"):
                handler.send_json(obj, code, hdrs or None)
            self._finish_request(ctx, code)

        try:
            version = self.registry.model(name)
        except KeyError:
            finish_json({"error": f"model {name!r} not found"}, 404)
            return
        if version.latency_slo_ms is not None:
            # arm (or refresh) the SLO-adaptive budget for this model
            self.admission.set_slo(name, version.latency_slo_ms)
        raw = (handler.headers.get("Content-Type", "")
               .split(";")[0].strip() in _NPY_TYPES)
        body = handler.read_body()
        deadline_ms = handler.headers.get("X-Deadline-Ms")
        try:
            if raw:
                # zero-copy: an ndarray view over the received bytes —
                # the batcher pads/concatenates from here, so the only
                # tensor copy on the path is the batch assembly itself
                x = npy_view(body)
            else:
                doc = json.loads(body.decode() or "{}")
                if "inputs" not in doc:
                    finish_json({"error": "body must carry 'inputs'"},
                                400)
                    return
                x = np.asarray(doc["inputs"], dtype=np.float32)
                if deadline_ms is None:
                    deadline_ms = doc.get("deadline_ms")
            if x.ndim < 1 or x.shape[0] < 1:
                finish_json({"error": "inputs need a leading batch "
                                      "dim of >= 1"}, 400)
                return
        except Exception as e:          # malformed json / npy
            finish_json({"error": f"bad request body: {e}"}, 400)
            return
        deadline = deadline_after_ms(
            float(deadline_ms) if deadline_ms is not None else None)
        t_start = time.monotonic()
        try:
            # admit first (the unrolled track()): an already-expired
            # deadline fast-fails 504 here without occupying a slot
            with ctx.phase("admit"):
                self.admission.admit(name, deadline)
            try:
                fut = version.batcher.submit(x, deadline=deadline,
                                             ctx=ctx or None)
                timeout = (float(deadline_ms) / 1e3 + 1.0
                           if deadline_ms is not None
                           else self.request_timeout_s)
                try:
                    out = fut.result(timeout=timeout)
                except (TimeoutError, futures.TimeoutError):
                    # pre-3.11 futures.TimeoutError is its own type
                    fut.cancel()
                    raise
            finally:
                self.admission.release(name)
        except DeadlineExceeded as e:
            finish_json({"error": str(e)}, 504)
            return
        except ShedError as e:
            code = 503 if e.reason == "draining" else 429
            reqrec.get().note_shed(name, e.reason)
            finish_json(
                {"error": str(e), "reason": e.reason}, code,
                {"Retry-After": self.admission.retry_after_header(name)})
            return
        except (TimeoutError, futures.TimeoutError):
            finish_json({"error": "request timed out"}, 504)
            return
        except Exception as e:          # model raised during compute
            finish_json({"error": f"inference failed: {e}"}, 500)
            return
        self.admission.observe_total(
            name, time.monotonic() - t_start,
            trace_id=ctx.trace_id if ctx else None)
        if raw:
            out_arr = np.ascontiguousarray(np.asarray(out))
            counted.inc(model=name, code="200")
            hdrs = {"X-Model-Version": str(version.version)}
            hdrs.update(trace_headers)
            # header + the array's own buffer, streamed — np.save's
            # BytesIO join copy is gone
            with ctx.phase("serialize"):
                handler.send_body_parts(
                    [npy_header(out_arr), memoryview(out_arr)],
                    "application/octet-stream", headers=hdrs)
            self._finish_request(ctx, 200)
        else:
            finish_json({"outputs": np.asarray(out).tolist(),
                         "model": name,
                         "version": version.version,
                         "batch": int(x.shape[0])}, 200)

    # ------------------------------------------------------------------
    def _generate(self, handler: QuietHandler, name: str):
        ctx = tracectx.start(name, "generate",
                             handler.headers.get(tracectx.TRACE_HEADER))
        handler._trace_id = ctx.trace_id if ctx else None
        with tracectx.bind(ctx):
            self._generate_traced(handler, name, ctx)

    def _generate_traced(self, handler: QuietHandler, name: str, ctx):
        """``POST /v1/models/<name>:generate`` — autoregressive decode
        with streaming response.

        JSON body: ``{"prompt": [ids...], "max_tokens": N,
        "temperature": 0.0, "top_k": 0, "deadline_ms": optional,
        "stream": true}``. With ``stream`` (default) the response is
        chunked ``application/x-ndjson``: one ``{"token": id,
        "index": i}`` line per decoded token the moment it decodes,
        then a terminal ``{"done": true, "reason": ..., "tokens": n}``
        line. ``stream=false`` buffers the whole completion into one
        JSON object. Admission is by token-cost (the prompt's KV-block
        footprint) through the same AIMD controller as predict; pool
        exhaustion sheds 429 + measured Retry-After *before* any
        chunk is sent. The first token's latency feeds the SLO
        machinery as time-to-first-token."""
        counted = telemetry.counter(
            "dl4j_serving_requests_total",
            "predict requests by model and HTTP status code")
        trace_headers = ({tracectx.TRACE_HEADER: ctx.trace_id}
                         if ctx else {})

        def finish_json(obj, code, headers=None):
            counted.inc(model=name, code=str(code))
            hdrs = dict(trace_headers)
            if headers:
                hdrs.update(headers)
            with ctx.phase("serialize"):
                handler.send_json(obj, code, hdrs or None)
            self._finish_request(ctx, code)

        try:
            version = self.registry.model(name)
        except KeyError:
            finish_json({"error": f"model {name!r} not found"}, 404)
            return
        if not version.batcher.is_generative:
            finish_json({"error": f"model {name!r} has no generate "
                                  f"surface"}, 400)
            return
        if version.latency_slo_ms is not None:
            self.admission.set_slo(name, version.latency_slo_ms)
        try:
            doc = json.loads(handler.read_body().decode() or "{}")
            prompt = [int(t) for t in doc["prompt"]]
            if not prompt:
                raise ValueError("prompt must not be empty")
            max_tokens = int(doc.get("max_tokens", 16))
            temperature = float(doc.get("temperature", 0.0))
            top_k = int(doc.get("top_k", 0))
            streaming = bool(doc.get("stream", True))
            deadline_ms = (handler.headers.get("X-Deadline-Ms")
                           or doc.get("deadline_ms"))
        except (KeyError, ValueError, TypeError,
                json.JSONDecodeError) as e:
            finish_json({"error": f"bad request body: {e}"}, 400)
            return
        deadline = deadline_after_ms(
            float(deadline_ms) if deadline_ms is not None else None)
        t_start = time.monotonic()
        cost = version.batcher.generate_cost(len(prompt), max_tokens)
        tokens_out, idx = [], 0
        headers_sent = False
        t_first = None
        try:
            # unrolled track(): admit by token-cost, release in the
            # finally below
            with ctx.phase("admit"):
                self.admission.admit(name, deadline, cost=cost)
            try:
                stream = version.batcher.submit_generate(
                    prompt, max_tokens, temperature=temperature,
                    top_k=top_k, deadline=deadline, ctx=ctx or None)
                per_token_timeout = self.request_timeout_s
                try:
                    while True:
                        tok = stream.next(timeout=per_token_timeout)
                        if tok is None:          # closed: see reason
                            break
                        if idx == 0:
                            t_first = time.monotonic()
                            # TTFT feeds the AIMD controller — the
                            # generative SLO observation stream
                            self.admission.observe_total(
                                name, t_first - t_start,
                                trace_id=(ctx.trace_id if ctx
                                          else None))
                            ctx.instant("ttft", ms=round(
                                (t_first - t_start) * 1e3, 3))
                            if streaming:
                                hdrs = {"X-Model-Version":
                                        str(version.version)}
                                hdrs.update(trace_headers)
                                handler.begin_chunks(
                                    "application/x-ndjson",
                                    headers=hdrs)
                                headers_sent = True
                        if streaming:
                            handler.send_chunk(json.dumps(
                                {"token": tok,
                                 "index": idx}).encode() + b"\n")
                        else:
                            tokens_out.append(tok)
                        idx += 1
                    if t_first is not None:
                        # the stream phase: first token -> stream end
                        ctx.phase_at("stream", t_first,
                                     time.monotonic())
                except (OSError, BrokenPipeError):
                    # client went away mid-stream: cancel so the
                    # engine retires the sequence and frees its KV
                    # blocks on the next iteration
                    stream.cancel()
                    counted.inc(model=name, code="499")
                    handler.close_connection = True
                    if t_first is not None:
                        ctx.phase_at("stream", t_first,
                                     time.monotonic())
                    ctx.note(tokens=idx)
                    self._finish_request(ctx, 499)
                    return
                except Exception:
                    stream.cancel()
                    raise
            finally:
                self.admission.release(name, cost=cost)
        except DeadlineExceeded as e:
            if headers_sent:
                handler.abort_chunks()
                self._finish_request(ctx, 504)
            else:
                finish_json({"error": str(e)}, 504)
            return
        except ShedError as e:
            reqrec.get().note_shed(name, e.reason)
            code = 503 if e.reason == "draining" else 429
            if headers_sent:
                handler.abort_chunks()
                self._finish_request(ctx, code)
            else:
                finish_json(
                    {"error": str(e), "reason": e.reason}, code,
                    {"Retry-After":
                     self.admission.retry_after_header(name)})
            return
        except Exception as e:
            # mid-stream failure after headers: terminate the chunk
            # stream hard (truncated body = clean client error, not a
            # wedged connection); before headers: a plain 500
            if headers_sent:
                handler.abort_chunks()
                self._finish_request(ctx, 500)
            else:
                finish_json({"error": f"generate failed: {e}"}, 500)
            return
        ctx.note(tokens=idx)
        if streaming:
            if not headers_sent:
                # closed before the first token (e.g. deadline hit in
                # the prefill queue): map the reason to a status
                code = 504 if stream.reason == "deadline" else 500
                finish_json({"error": f"generate ended before the "
                                      f"first token "
                                      f"({stream.reason})"}, code)
                return
            with ctx.phase("serialize"):
                handler.send_chunk(json.dumps(
                    {"done": True, "reason": stream.reason,
                     "tokens": idx}).encode() + b"\n")
                handler.end_chunks()
            counted.inc(model=name, code="200")
            self._finish_request(ctx, 200)
        else:
            finish_json({"tokens": tokens_out,
                         "reason": stream.reason,
                         "model": name,
                         "version": version.version}, 200)
