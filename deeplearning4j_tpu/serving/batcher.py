"""Per-model dynamic batcher with shape-bucketed flushes.

Reuses the ``ParallelInference`` submit/flush discipline (background
worker drains a queue, aggregates up to ``batch_limit`` requests per
``batch_window_ms`` window) with one serving-critical change: every
flush is padded UP to the nearest *warm bucket* — a batch size whose
XLA program was compiled at warmup — so steady-state requests never
retrace (TVM's ahead-of-time compilation discipline, PAPERS.md
1802.04799). A per-version ``RetraceGuard`` counts signatures; after
warmup its count must not move.

Two model surfaces:

- MLN/ComputationGraph: the jitted sharded forward inherited from
  ``ParallelInference`` (params replicated over the mesh, batch
  sharded over ``data``).
- generic (``SameDiff`` adapters, ONNX importers): any object whose
  ``output(batch) -> array`` is signature-cached internally — bucket
  padding keeps *its* cache to one entry per bucket too.

Requests carry an optional ``time.monotonic()`` deadline: a request
whose deadline expires while queued is cancelled at flush time with
:class:`~deeplearning4j_tpu.serving.admission.DeadlineExceeded` —
never computed.
"""
from __future__ import annotations

import concurrent.futures
import time
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.compilecache import RetraceGuard
from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                   ParallelInference)
from deeplearning4j_tpu.serving.admission import DeadlineExceeded

_LATENCY_HELP = ("serving request latency by stage: queue "
                 "(submit->flush), compute (flush forward), total "
                 "(submit->result), warmup (per-bucket pre-compile) "
                 "(seconds)")


def _latency() -> telemetry.Histogram:
    return telemetry.histogram("dl4j_serving_latency_seconds",
                               _LATENCY_HELP)


class ServingBatcher(ParallelInference):
    """A ``ParallelInference`` whose flushes land on warm buckets."""

    def __init__(self, model, buckets: Sequence[int] = (8, 32),
                 mesh=None, *, name: str = "model",
                 batch_window_ms: float = 2.0,
                 queue_limit: int = 256,
                 guard: Optional[RetraceGuard] = None):
        #: generic path: no MLN `_forward` funnel — serve through the
        #: model's own `output(batch)` (SameDiff/ONNX adapters)
        self._generic = None if hasattr(model, "_forward") \
            else model.output
        if not buckets:
            raise ValueError("need at least one warmup bucket")
        super().__init__(model, mesh,
                         inference_mode=InferenceMode.BATCHED,
                         batch_limit=max(int(b) for b in buckets),
                         queue_limit=queue_limit,
                         batch_window_ms=batch_window_ms)
        if self._generic is None:
            # sharded forward: buckets must be shard multiples, or the
            # place-time pad would silently shift them to a new shape
            w = self.n_workers
            buckets = {-(-int(b) // w) * w for b in buckets}
        self.buckets = tuple(sorted(int(b) for b in set(buckets)))
        self.batch_limit = self.buckets[-1]
        self.name = name
        self.guard = guard if guard is not None else RetraceGuard(
            f"serving:{name}", threshold=len(self.buckets) + 1)
        self._warmed = False

    # ------------------------------------------------------------------
    def _ensure(self):
        if self._generic is not None:
            return
        super()._ensure()

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _pad_to_bucket(self, chunk: np.ndarray) -> np.ndarray:
        """Pad the chunk's batch dim up to the nearest warm bucket by
        repeating the final row (sliced back off after the forward).
        Chunks are pre-capped at the largest bucket, so a bucket
        always exists."""
        n = chunk.shape[0]
        b = self._bucket_for(n)
        if b is None or b == n:
            return chunk
        reps = np.repeat(chunk[-1:], b - n, axis=0)
        return np.concatenate([chunk, reps], axis=0)

    def _record(self, sig_array) -> None:
        """Guard bookkeeping for one dispatch: a NEW signature after
        warmup finished is a bucket miss — the request paid the cold
        compile the warmup set was supposed to cover (feature-shape/
        dtype drift, or a bucket the set is missing)."""
        hit = self.guard.record(sig_array)
        if self._warmed and not hit:
            telemetry.counter(
                "dl4j_serving_bucket_miss_total",
                "post-warmup flushes whose padded signature no warm "
                "bucket covered — a cold XLA compile on the serving "
                "path (shape/dtype drift, or grow the bucket set)"
            ).inc(model=self.name)

    def _forward_padded(self, padded: np.ndarray, orig: int
                        ) -> np.ndarray:
        if self._generic is not None:
            self._record(padded)
            return np.asarray(self._generic(padded))[:orig]
        placed, _ = self._place_chunk(padded)
        self._record(placed)
        out = self._fwd(self.model.params, self.model.states, placed)
        return np.asarray(out)[:orig]

    # ------------------------------------------------------------------
    def warmup(self, input_shape: Sequence[int],
               dtype=np.float32) -> float:
        """Pre-compile every bucket's program (one forward per bucket,
        blocked to completion) so the first real request hits a warm
        signature. ``input_shape`` is one request's shape WITHOUT the
        batch dim. Returns total warmup seconds."""
        self._ensure()
        lat = _latency()
        t_all = time.perf_counter()
        for b in self.buckets:
            x = np.zeros((b,) + tuple(input_shape), dtype)
            t0 = time.perf_counter()
            with telemetry.span("serving.warmup", model=self.name,
                                bucket=b):
                # _forward_padded's np.asarray is the sync point: the
                # bucket's program has fully compiled AND run once by
                # the time this returns
                self._forward_padded(x, b)
            lat.observe(time.perf_counter() - t0, model=self.name,
                        stage="warmup")
        self._warmed = True
        return time.perf_counter() - t_all

    # ------------------------------------------------------------------
    def output_batched(self, requests: List) -> List[np.ndarray]:
        """Aggregate ``requests`` into bucket-padded flushes. Unlike
        the base class this never compiles an odd shape in steady
        state: total rows are chunked by the largest bucket and each
        chunk padded to its nearest bucket."""
        if not requests:
            return []
        self._ensure()
        arrays = [np.asarray(r) for r in requests]
        sizes = [a.shape[0] for a in arrays]
        big = np.concatenate(arrays, axis=0) if len(arrays) > 1 \
            else arrays[0]
        cap = self.buckets[-1]
        outs = []
        for i in range(0, big.shape[0], cap):
            chunk = np.asarray(big[i:i + cap])
            n = chunk.shape[0]
            outs.append(self._forward_padded(
                self._pad_to_bucket(chunk), n))
        flat = np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
        result, off = [], 0
        for s in sizes:
            result.append(flat[off:off + s])
            off += s
        return result

    # ------------------------------------------------------------------
    def submit(self, x,
               deadline: Optional[float] = None
               ) -> "concurrent.futures.Future":
        """Enqueue one request; ``deadline`` is an absolute
        ``time.monotonic()`` instant past which the request must not
        be computed (its Future then raises DeadlineExceeded)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if deadline is not None:
            fut._serving_deadline = float(deadline)
        telemetry.counter(
            "dl4j_inference_requests_total",
            "requests submitted to ParallelInference").inc(
                mode=self.inference_mode)
        # same locking discipline as the base class: the put happens
        # under the lock shutdown() takes to enqueue its sentinel
        with self._lock:
            self._ensure_worker()
            self._requests.put((x, fut, time.monotonic()))
        return fut

    def _flush(self, batch):
        now = time.monotonic()
        live = []
        for x, f, t in batch:
            dl = getattr(f, "_serving_deadline", None)
            if dl is not None and now >= dl:
                # expired while queued: cancel, never compute
                telemetry.counter(
                    "dl4j_serving_deadline_expired_total",
                    "requests whose deadline passed while queued — "
                    "cancelled before compute").inc(model=self.name)
                if f.set_running_or_notify_cancel():
                    f.set_exception(DeadlineExceeded(
                        f"deadline passed {now - dl:.3f}s before "
                        f"flush"))
                continue
            if f.set_running_or_notify_cancel():
                live.append((x, f, t))
        if not live:
            return
        lat = _latency()
        if telemetry.enabled():
            for _, _, t in live:
                lat.observe(now - t, model=self.name, stage="queue")
            telemetry.histogram(
                "dl4j_inference_batch_occupancy",
                "aggregated-batch fill fraction per flush "
                "(requests / batch_limit)",
                buckets=telemetry.RATIO_BUCKETS).observe(
                    len(live) / max(1, self.batch_limit))
        t0 = time.perf_counter()
        try:
            with telemetry.span("serving.flush", model=self.name,
                                requests=len(live)):
                outs = self.output_batched([x for x, _, _ in live])
        except BaseException as e:           # noqa: BLE001
            for _, f, _ in live:
                f.set_exception(e)
            return
        lat.observe(time.perf_counter() - t0, model=self.name,
                    stage="compute")
        end = time.monotonic()
        for (_, f, t), o in zip(live, outs):
            lat.observe(end - t, model=self.name, stage="total")
            f.set_result(o)
