"""Per-model dynamic batcher with shape-bucketed flushes.

Reuses the ``ParallelInference`` submit/flush discipline (background
worker drains a queue and aggregates requests) with two
serving-critical changes. First, every flush is padded UP to the
nearest *warm bucket* — a batch size whose XLA program was compiled at
warmup — so steady-state requests never retrace (TVM's ahead-of-time
compilation discipline, PAPERS.md 1802.04799). A per-version
``RetraceGuard`` counts signatures; after warmup its count must not
move. Second, the default flush trigger is **continuous** (Orca-style
iteration-level scheduling): the worker flushes the moment the device
is free and takes whatever is waiting — occupancy-driven, not
clock-driven. A request never waits out a fixed window behind an idle
device; under load, queue depth alone fills the buckets. The classic
fixed ``batch_window_ms`` behavior stays available as
``flush_policy="window"``. Realized fill lands in the
``dl4j_serving_batch_occupancy`` histogram (live rows / padded rows).

Two model surfaces:

- MLN/ComputationGraph: the jitted sharded forward inherited from
  ``ParallelInference`` (params replicated over the mesh, batch
  sharded over ``data``) — or, with ``mode="sharded"``/``"fsdp"``,
  the ZeRO-layout resident placement from ``serving.residency``:
  params live 1/N-sharded between requests and are gathered inside
  the jitted forward, bitwise-equal to the dense path. The sharded
  tree lives on the *batcher* (``_serve_params``), never on the model,
  so ``model.output`` and training paths stay untouched.
- generic (``SameDiff`` adapters, ONNX importers): any object whose
  ``output(batch) -> array`` is signature-cached internally — bucket
  padding keeps *its* cache to one entry per bucket too (dense only).

Requests carry an optional ``time.monotonic()`` deadline: a request
whose deadline expires while queued is cancelled at flush time with
:class:`~deeplearning4j_tpu.serving.admission.DeadlineExceeded` —
never computed (counted under
``dl4j_serving_deadline_shed_total{where="queue"}``).
"""
from __future__ import annotations

import concurrent.futures
import queue as _queue
import threading
import time
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.compilecache import RetraceGuard
from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                   ParallelInference)
from deeplearning4j_tpu.serving.admission import (DeadlineExceeded,
                                                  _deadline_shed_counter)

_LATENCY_HELP = ("serving request latency by stage: queue "
                 "(submit->flush), compute (flush forward), total "
                 "(submit->result), warmup (per-bucket pre-compile) "
                 "(seconds)")

#: flush triggers: continuous = flush whenever the device frees and
#: requests wait (iteration-level scheduling); window = hold the first
#: request up to batch_window_ms hoping for batch-mates (the PR-3 seed)
FLUSH_POLICIES = ("continuous", "window")


def _latency() -> telemetry.Histogram:
    return telemetry.histogram("dl4j_serving_latency_seconds",
                               _LATENCY_HELP)


class ServingBatcher(ParallelInference):
    """A ``ParallelInference`` whose flushes land on warm buckets."""

    def __init__(self, model, buckets: Sequence[int] = (8, 32),
                 mesh=None, *, name: str = "model",
                 batch_window_ms: float = 2.0,
                 queue_limit: int = 256,
                 guard: Optional[RetraceGuard] = None,
                 flush_policy: str = "continuous",
                 mode: str = "dense",
                 tensor_parallel: Optional[int] = None,
                 generate: Optional[dict] = None,
                 param_dtype=None):
        #: generic path: no MLN `_forward` funnel — serve through the
        #: model's own `output(batch)` (SameDiff/ONNX adapters)
        self._generic = None if hasattr(model, "_forward") \
            else model.output
        #: generative path: a model exposing the prefill/decode_step
        #: contract gets a DecodeEngine beside the predict path
        self._generative = (hasattr(model, "prefill")
                            and hasattr(model, "decode_step"))
        self.generate_config = dict(generate or {})
        self.engine = None
        if not buckets:
            raise ValueError("need at least one warmup bucket")
        if flush_policy not in FLUSH_POLICIES:
            raise ValueError(f"flush_policy must be one of "
                             f"{FLUSH_POLICIES}, got {flush_policy!r}")
        from deeplearning4j_tpu.serving.residency import (
            assert_mode, resolve_param_dtype)
        assert_mode(mode)
        self.param_dtype = resolve_param_dtype(param_dtype)
        if self.param_dtype is not None and mode == "dense":
            raise ValueError(
                f"param_dtype={self.param_dtype!r} needs a sharded "
                f"residency mode ('sharded'/'fsdp'); dense serving "
                f"keeps the model's own float32 tree")
        if mode != "dense" and self._generic is not None \
                and not self._generative:
            raise ValueError(
                f"residency mode {mode!r} needs a param-tree model "
                f"(MLN/ComputationGraph); generic output() models "
                f"serve dense only")
        super().__init__(model, mesh,
                         inference_mode=InferenceMode.BATCHED,
                         batch_limit=max(int(b) for b in buckets),
                         queue_limit=queue_limit,
                         batch_window_ms=batch_window_ms)
        if self._generic is None:
            # sharded forward: buckets must be shard multiples, or the
            # place-time pad would silently shift them to a new shape
            w = self.n_workers
            buckets = {-(-int(b) // w) * w for b in buckets}
        self.buckets = tuple(sorted(int(b) for b in set(buckets)))
        self.batch_limit = self.buckets[-1]
        self.name = name
        self.flush_policy = flush_policy
        self.mode = mode
        self.tensor_parallel = tensor_parallel
        self.guard = guard if guard is not None else RetraceGuard(
            f"serving:{name}", threshold=len(self.buckets) + 1)
        self._warmed = False
        #: the resident-sharded serving layout (mode != dense); lives
        #: here — never on the model — so model.output stays dense
        self._serve_params = None
        self._serve_states = None
        self._fsdp_specs = None
        self._serve_tp_specs = None

    # ------------------------------------------------------------------
    @property
    def params(self):
        """What this batcher actually holds resident — the sharded
        serving layout when one is placed, else the model's own tree
        (the ``memory_report`` attribution surface)."""
        if self._serve_params is not None:
            return self._serve_params
        return getattr(self.model, "params", None)

    def _ensure(self):
        if self._generic is not None:
            return
        if self.mode == "dense":
            super()._ensure()
            return
        m = self.model
        if not m._initialized:
            m.init()
        if not self._placed:
            from deeplearning4j_tpu.parallel.mesh import replicate_tree
            from deeplearning4j_tpu.serving.residency import \
                serving_layouts
            (self._serve_params, self._fsdp_specs,
             self._serve_tp_specs) = serving_layouts(
                self.mesh, m.params, self.mode, self.tensor_parallel,
                name=self.name, param_dtype=self.param_dtype)
            self._serve_states = replicate_tree(self.mesh, m.states)
            self._placed = True
        if self._fwd is None:
            import jax

            from deeplearning4j_tpu.common.compilecache import \
                enable_persistent_cache
            enable_persistent_cache()
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            from deeplearning4j_tpu.serving.residency import \
                serving_param_view
            is_graph = isinstance(m, ComputationGraph)
            mesh, mode = self.mesh, self.mode
            specs, tp_specs = self._fsdp_specs, self._serve_tp_specs
            pd = self.param_dtype

            def fwd(params, states, x):
                view = serving_param_view(params, specs, mesh,
                                          tp_specs, mode,
                                          param_dtype=pd)
                if is_graph:
                    acts, _ = m._forward(view, states, [x],
                                         training=False, rng=None,
                                         want_logits=False)
                    return acts[m.conf.network_outputs[0]]
                out, _ = m._forward(view, states, x, training=False,
                                    rng=None, want_logits=False)
                return out

            self._fwd = jax.jit(fwd)

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _pad_to_bucket(self, chunk: np.ndarray) -> np.ndarray:
        """Pad the chunk's batch dim up to the nearest warm bucket by
        repeating the final row (sliced back off after the forward).
        Chunks are pre-capped at the largest bucket, so a bucket
        always exists."""
        n = chunk.shape[0]
        b = self._bucket_for(n)
        if b is None or b == n:
            return chunk
        reps = np.repeat(chunk[-1:], b - n, axis=0)
        return np.concatenate([chunk, reps], axis=0)

    def _record(self, sig_array) -> None:
        """Guard bookkeeping for one dispatch: a NEW signature after
        warmup finished is a bucket miss — the request paid the cold
        compile the warmup set was supposed to cover (feature-shape/
        dtype drift, or a bucket the set is missing)."""
        hit = self.guard.record(sig_array)
        if self._warmed and not hit:
            telemetry.counter(
                "dl4j_serving_bucket_miss_total",
                "post-warmup flushes whose padded signature no warm "
                "bucket covered — a cold XLA compile on the serving "
                "path (shape/dtype drift, or grow the bucket set)"
            ).inc(model=self.name)

    def _forward_padded(self, padded: np.ndarray, orig: int
                        ) -> np.ndarray:
        if self._generic is not None:
            self._record(padded)
            return np.asarray(self._generic(padded))[:orig]
        placed, _ = self._place_chunk(padded)
        self._record(placed)
        if self._serve_params is not None:
            out = self._fwd(self._serve_params, self._serve_states,
                            placed)
        else:
            out = self._fwd(self.model.params, self.model.states,
                            placed)
        return np.asarray(out)[:orig]

    # ------------------------------------------------------------------
    def warmup(self, input_shape: Sequence[int],
               dtype=np.float32) -> float:
        """Pre-compile every bucket's program (one forward per bucket,
        blocked to completion) so the first real request hits a warm
        signature. ``input_shape`` is one request's shape WITHOUT the
        batch dim. Returns total warmup seconds."""
        self._ensure()
        lat = _latency()
        t_all = time.perf_counter()
        for b in self.buckets:
            x = np.zeros((b,) + tuple(input_shape), dtype)
            t0 = time.perf_counter()
            with telemetry.span("serving.warmup", model=self.name,
                                bucket=b):
                # _forward_padded's np.asarray is the sync point: the
                # bucket's program has fully compiled AND run once by
                # the time this returns
                self._forward_padded(x, b)
            lat.observe(time.perf_counter() - t0, model=self.name,
                        stage="warmup")
        self._warmed = True
        return time.perf_counter() - t_all

    # -- generative path (ISSUE 16) ------------------------------------
    @property
    def is_generative(self) -> bool:
        return self._generative

    def _ensure_generate(self):
        """Build the KV pool + DecodeEngine on first use. Residency
        modes compose: under ``sharded``/``fsdp`` the model's params
        are placed resident-sharded (``serving.residency``) and the
        engine's jitted programs consume them through the serving
        param view — the KV pool itself stays dense-replicated (every
        chip decodes every sequence, classifier-serving style)."""
        if not self._generative:
            raise ValueError(f"model {self.name!r} has no "
                             f"prefill/decode_step surface")
        if self.engine is not None:
            return self.engine
        import functools

        from deeplearning4j_tpu.serving.generative import DecodeEngine
        from deeplearning4j_tpu.serving.kvcache import KVBlockPool
        cfg = self.generate_config
        m = self.model
        if getattr(m, "params", None) is None:
            m.init()
        c = m.conf
        from deeplearning4j_tpu.common.dtypes import to_jnp_dtype
        kv_dtype = cfg.get("kv_dtype")
        if kv_dtype is None:
            # fleet-wide default; per-model generate={'kv_dtype': ...}
            # overrides it
            import os
            kv_dtype = os.environ.get("DL4J_TPU_KV_DTYPE", "").strip() \
                or "float32"
        if isinstance(kv_dtype, str):
            kv_dtype = to_jnp_dtype(
                "bfloat16" if kv_dtype in ("bf16", "bfloat16")
                else kv_dtype)
        pool = KVBlockPool(
            c.n_layers,
            int(cfg.get("kv_blocks", 64)),
            int(cfg.get("kv_block_size", 16)),
            c.n_heads, c.head_dim,
            dtype=kv_dtype, name=self.name)
        params, view_fn = m.params, None
        if self.mode != "dense":
            from deeplearning4j_tpu.serving.residency import (
                serving_layouts, serving_param_view)
            placed, fsdp_specs, tp_specs = serving_layouts(
                self.mesh, m.params, self.mode, self.tensor_parallel,
                name=self.name, param_dtype=self.param_dtype)
            self._serve_params = placed
            self._fsdp_specs = fsdp_specs
            self._serve_tp_specs = tp_specs
            params = placed
            view_fn = functools.partial(
                serving_param_view, fsdp_specs=fsdp_specs,
                mesh=self.mesh, tp_specs=tp_specs, mode=self.mode,
                param_dtype=self.param_dtype)
        self.engine = DecodeEngine(
            m, params, pool, view_fn=view_fn, name=self.name,
            prompt_buckets=cfg.get("prompt_buckets", (16, 64)),
            decode_buckets=cfg.get("decode_buckets", (4, 8)),
            max_seq_len=cfg.get("max_seq_len"),
            paged=cfg.get("paged"), guard=self.guard,
            rng_seed=int(cfg.get("rng_seed", 0)))
        return self.engine

    def warmup_generate(self) -> float:
        """Compile every prefill/commit/decode bucket program before
        the first real generate request (the generative half of
        :meth:`warmup`). Returns warmup seconds."""
        engine = self._ensure_generate()
        lat = _latency()
        t0 = time.perf_counter()
        with telemetry.span("serving.warmup_generate",
                            model=self.name):
            secs = engine.warmup()
        lat.observe(secs, model=self.name, stage="warmup")
        self._warmed = True
        return time.perf_counter() - t0

    def generate_cost(self, prompt_len: int, max_tokens: int = 0
                      ) -> int:
        """Token-cost of a generate admission (KV blocks)."""
        return self._ensure_generate().generate_cost(prompt_len,
                                                     max_tokens)

    def submit_generate(self, prompt, max_tokens: int, *,
                        temperature: float = 0.0, top_k: int = 0,
                        deadline: Optional[float] = None,
                        ctx=None):
        """Enqueue a generate request; returns the
        :class:`~deeplearning4j_tpu.serving.generative.TokenStream`.
        Raises PoolExhausted synchronously when the KV pool cannot
        hold the prompt (shed upstream as 429 + Retry-After).
        ``ctx`` (the request's TraceContext) rides the pending entry
        into the engine for cross-thread phase attribution."""
        engine = self._ensure_generate()
        telemetry.counter(
            "dl4j_inference_requests_total",
            "requests submitted to ParallelInference").inc(
                mode="generate")
        return engine.submit(prompt, max_tokens,
                             temperature=temperature, top_k=top_k,
                             deadline=deadline, ctx=ctx)

    def shutdown(self, *a, **kw):
        if self.engine is not None:
            self.engine.shutdown()
        return super().shutdown(*a, **kw)

    # ------------------------------------------------------------------
    def output_batched(self, requests: List) -> List[np.ndarray]:
        """Aggregate ``requests`` into bucket-padded flushes. Unlike
        the base class this never compiles an odd shape in steady
        state: total rows are chunked by the largest bucket and each
        chunk padded to its nearest bucket."""
        if not requests:
            return []
        self._ensure()
        arrays = [np.asarray(r) for r in requests]
        sizes = [a.shape[0] for a in arrays]
        big = np.concatenate(arrays, axis=0) if len(arrays) > 1 \
            else arrays[0]
        cap = self.buckets[-1]
        outs = []
        for i in range(0, big.shape[0], cap):
            chunk = np.asarray(big[i:i + cap])
            n = chunk.shape[0]
            outs.append(self._forward_padded(
                self._pad_to_bucket(chunk), n))
        flat = np.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]
        result, off = [], 0
        for s in sizes:
            result.append(flat[off:off + s])
            off += s
        return result

    # ------------------------------------------------------------------
    def submit(self, x,
               deadline: Optional[float] = None,
               ctx=None) -> "concurrent.futures.Future":
        """Enqueue one request; ``deadline`` is an absolute
        ``time.monotonic()`` instant past which the request must not
        be computed (its Future then raises DeadlineExceeded).
        ``ctx`` is the request's
        :class:`~deeplearning4j_tpu.common.tracectx.TraceContext`:
        the flush worker runs on its own thread, so the context rides
        the Future and phase intervals are attributed back with
        ``phase_at``."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        if deadline is not None:
            fut._serving_deadline = float(deadline)
        if ctx is not None:
            fut._trace_ctx = ctx
        telemetry.counter(
            "dl4j_inference_requests_total",
            "requests submitted to ParallelInference").inc(
                mode=self.inference_mode)
        # same locking discipline as the base class: the put happens
        # under the lock shutdown() takes to enqueue its sentinel
        with self._lock:
            self._ensure_worker()
            self._requests.put((x, fut, time.monotonic()))
        return fut

    def _ensure_worker(self):
        """Start the flush worker (caller holds ``self._lock``).

        ``window`` policy keeps the base loop: hold the first request
        up to ``batch_window_ms`` collecting batch-mates. The
        ``continuous`` loop never arms a clock — it blocks for ONE
        request, greedily drains whatever else is already queued (up
        to ``batch_limit``), and flushes immediately. Batch formation
        comes from device busy time alone: while a flush computes,
        arrivals accumulate in the queue and the next iteration takes
        them all. An idle device therefore gives a lone request
        zero added latency, and a saturated one fills buckets — the
        fixed window's latency floor is gone in both regimes."""
        if self.flush_policy != "continuous":
            super()._ensure_worker()
            return
        if self._worker is not None:
            return
        self._requests = _queue.Queue(self.queue_limit)
        self._shutdown = False
        q = self._requests                       # bind THIS queue

        def loop():
            while True:
                try:
                    first = q.get(timeout=0.1)
                except _queue.Empty:
                    if self._shutdown:
                        return
                    continue
                if first is None:
                    return
                batch = [first]
                while len(batch) < self.batch_limit:
                    try:
                        nxt = q.get_nowait()
                    except _queue.Empty:
                        break
                    if nxt is None:
                        self._flush(batch)
                        return
                    batch.append(nxt)
                self._flush(batch)

        self._worker = threading.Thread(target=loop, daemon=True,
                                        name="dl4j-tpu-serving")
        self._worker.start()

    def _padded_rows(self, rows: int) -> int:
        """Rows the device actually computes for ``rows`` live rows
        after chunking by the largest bucket and padding each chunk
        up — the occupancy denominator."""
        cap, total = self.buckets[-1], 0
        while rows > 0:
            take = min(rows, cap)
            total += self._bucket_for(take) or take
            rows -= take
        return total

    def _flush(self, batch):
        now = time.monotonic()
        live = []
        for x, f, t in batch:
            dl = getattr(f, "_serving_deadline", None)
            if dl is not None and now >= dl:
                # expired while queued: cancel, never compute
                telemetry.counter(
                    "dl4j_serving_deadline_expired_total",
                    "requests whose deadline passed while queued — "
                    "cancelled before compute").inc(model=self.name)
                _deadline_shed_counter().inc(model=self.name,
                                             where="queue")
                if f.set_running_or_notify_cancel():
                    f.set_exception(DeadlineExceeded(
                        f"deadline passed {now - dl:.3f}s before "
                        f"flush"))
                continue
            if f.set_running_or_notify_cancel():
                live.append((x, f, t))
        if not live:
            return
        lat = _latency()
        if telemetry.enabled():
            for _, _, t in live:
                lat.observe(now - t, model=self.name, stage="queue")
            telemetry.histogram(
                "dl4j_inference_batch_occupancy",
                "aggregated-batch fill fraction per flush "
                "(requests / batch_limit)",
                buckets=telemetry.RATIO_BUCKETS).observe(
                    len(live) / max(1, self.batch_limit))
            rows = sum(int(np.asarray(x).shape[0])
                       for x, _, _ in live)
            telemetry.histogram(
                "dl4j_serving_batch_occupancy",
                "live rows / bucket-padded rows per serving flush — "
                "how full the warm buckets actually run (1.0 = no "
                "padding waste; continuous batching should push this "
                "up under load)",
                buckets=telemetry.RATIO_BUCKETS).observe(
                    rows / max(1, self._padded_rows(rows)),
                    model=self.name, policy=self.flush_policy)
        t0 = time.perf_counter()
        t_dev0 = time.monotonic()
        try:
            with telemetry.span("serving.flush", model=self.name,
                                requests=len(live)):
                outs = self.output_batched([x for x, _, _ in live])
        except BaseException as e:           # noqa: BLE001
            for _, f, _ in live:
                f.set_exception(e)
            return
        t_dev1 = time.monotonic()
        lat.observe(time.perf_counter() - t0, model=self.name,
                    stage="compute")
        end = time.monotonic()
        occ = None
        for (_, f, t), o in zip(live, outs):
            lat.observe(end - t, model=self.name, stage="total")
            ctx = getattr(f, "_trace_ctx", None)
            if ctx is not None:
                # request timeline: queue (submit -> this flush),
                # batch_wait (deadline/occupancy bookkeeping before
                # the device dispatch), device (the flush forward)
                if occ is None:
                    r = sum(int(np.asarray(x).shape[0])
                            for x, _, _ in live)
                    occ = round(r / max(1, self._padded_rows(r)), 3)
                ctx.phase_at("queue", t, now)
                ctx.phase_at("batch_wait", now, t_dev0)
                ctx.phase_at("device", t_dev0, t_dev1)
                ctx.note(batch=len(live), occupancy=occ)
            f.set_result(o)
