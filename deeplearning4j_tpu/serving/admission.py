"""Admission control for the inference server.

A serving stack that accepts every request melts down under overload:
queues grow without bound, every request blows its latency SLO, and
the process eventually OOMs. Admission control bounds the damage —
requests beyond a per-model in-flight budget are *shed* immediately
(HTTP 429 + ``Retry-After``) so the requests already admitted still
meet their deadlines, and shutdown *drains*: no new admissions, wait
for in-flight work to finish, then stop.

Per-request deadlines ride through the batcher: an admitted request
whose deadline expires while queued is cancelled, not computed
(``ServingBatcher._flush`` checks before spending device time).
"""
from __future__ import annotations

import math
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from deeplearning4j_tpu.common import telemetry


class ShedError(RuntimeError):
    """Raised by :meth:`AdmissionController.admit` when a request is
    rejected. ``reason`` is ``"queue_full"`` (HTTP 429) or
    ``"draining"`` (HTTP 503); ``retry_after_s`` seeds the
    ``Retry-After`` header."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"request shed: {reason}")
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """A request's deadline passed before its batch was computed; the
    batcher cancels it instead of spending device time (HTTP 504)."""


def deadline_after_ms(ms: Optional[float]) -> Optional[float]:
    """A ``time.monotonic()`` deadline ``ms`` from now (None passes
    through: no deadline)."""
    return None if ms is None else time.monotonic() + float(ms) / 1e3


class AdmissionController:
    """Bounded per-model admission with load shedding and graceful
    drain.

    - ``max_queue``: in-flight budget per model (queued + computing).
      Request ``max_queue + 1`` sheds with 429.
    - ``retry_after_s``: hint returned to shed clients. Defaults to
      one batch window's worth of drain headroom (1s floor) — by then
      at least one flush has happened and capacity likely freed.
    - :meth:`drain`: flip to draining (new requests shed with 503),
      block until in-flight reaches zero or ``timeout`` passes.
    """

    def __init__(self, max_queue: int = 64,
                 retry_after_s: float = 1.0):
        self.max_queue = int(max_queue)
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight: Dict[str, int] = {}
        self._draining = False
        self._gauge = telemetry.gauge(
            "dl4j_serving_inflight",
            "admitted requests currently queued or computing, "
            "per model")
        self._shed = telemetry.counter(
            "dl4j_serving_shed_total",
            "requests rejected by admission control "
            "(reason=queue_full -> 429, reason=draining -> 503)")

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self, model: str) -> int:
        return self._inflight.get(model, 0)

    # ------------------------------------------------------------------
    def admit(self, model: str) -> None:
        """Admit one request for ``model`` or raise :class:`ShedError`.
        Pair every successful admit with a :meth:`release`."""
        with self._lock:
            if self._draining:
                self._shed.inc(model=model, reason="draining")
                raise ShedError("draining", self.retry_after_s)
            n = self._inflight.get(model, 0)
            if n >= self.max_queue:
                self._shed.inc(model=model, reason="queue_full")
                raise ShedError("queue_full", self.retry_after_s)
            self._inflight[model] = n + 1
            self._gauge.set(n + 1, model=model)

    def release(self, model: str) -> None:
        with self._lock:
            n = max(0, self._inflight.get(model, 0) - 1)
            self._inflight[model] = n
            self._gauge.set(n, model=model)
            if n == 0:
                self._idle.notify_all()

    @contextmanager
    def track(self, model: str):
        """``admit``/``release`` around a request's whole lifetime
        (queue wait + compute + response)."""
        self.admit(model)
        try:
            yield
        finally:
            self.release(model)

    # ------------------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting and wait for in-flight work to finish.
        Returns True when everything drained within ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._lock:
            self._draining = True
            while any(self._inflight.values()):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(left)
        return True

    def resume(self) -> None:
        """Leave draining mode (a drained server being restarted)."""
        with self._lock:
            self._draining = False

    def retry_after_header(self) -> str:
        """Integral seconds for the ``Retry-After`` header."""
        return str(max(1, int(math.ceil(self.retry_after_s))))
