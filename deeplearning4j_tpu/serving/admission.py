"""Admission control for the inference server.

A serving stack that accepts every request melts down under overload:
queues grow without bound, every request blows its latency SLO, and
the process eventually OOMs. Admission control bounds the damage —
requests beyond the in-flight budget are *shed* immediately (HTTP 429
+ ``Retry-After``) so the requests already admitted still meet their
deadlines, and shutdown *drains*: no new admissions, wait for
in-flight work to finish, then stop.

Two budget regimes:

- **static** (no SLO configured): the classic per-model in-flight cap
  (``max_queue``), exactly the PR-3 behavior.
- **SLO-adaptive**: when a model carries a ``latency_slo_ms``, the
  budget is a *controller output*, not a constant. Every completed
  request reports its total latency (the same observations that feed
  the ``dl4j_serving_total_seconds`` histogram); the controller
  compares the windowed p95 against the SLO and moves the budget
  AIMD-style — multiplicative shrink while p95 violates the SLO,
  additive regrow once p95 sits comfortably under it (≤80%). The live
  budget is exported as ``dl4j_serving_admission_budget``.

``Retry-After`` is likewise *measured*, not guessed: completions per
second over a sliding window give the drain rate, and a shed client is
told to come back after ``excess_inflight / drain_rate`` seconds
(floored at ``retry_after_s``, capped at ``RETRY_AFTER_CAP_S``). With
zero observations (cold start) the floor is the answer.

Per-request deadlines ride through the batcher: an admitted request
whose deadline expires while queued is cancelled, not computed
(``ServingBatcher._flush`` checks before spending device time), and a
request whose deadline is *already* expired at admission is fast-
failed 504 without ever occupying a bucket slot — both paths count
into ``dl4j_serving_deadline_shed_total{where=admission|queue}``.
"""
from __future__ import annotations

import logging
import math
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, Optional, Tuple

from deeplearning4j_tpu.common import telemetry

log = logging.getLogger("deeplearning4j_tpu")

#: never tell a client to back off longer than this (seconds)
RETRY_AFTER_CAP_S = 60.0

#: AIMD shrink factor while p95 > SLO / regrow threshold under it
_SHRINK = 0.7
_REGROW_AT = 0.8


class ShedError(RuntimeError):
    """Raised by :meth:`AdmissionController.admit` when a request is
    rejected. ``reason`` is ``"queue_full"`` (HTTP 429) or
    ``"draining"`` (HTTP 503); ``retry_after_s`` seeds the
    ``Retry-After`` header (drain-rate-derived when observations
    exist)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"request shed: {reason}")
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """A request's deadline passed before its batch was computed; the
    batcher cancels it instead of spending device time (HTTP 504).
    Also raised at admission when the deadline is already expired on
    arrival — the request never occupies a slot."""


def deadline_after_ms(ms: Optional[float]) -> Optional[float]:
    """A ``time.monotonic()`` deadline ``ms`` from now (None passes
    through: no deadline)."""
    return None if ms is None else time.monotonic() + float(ms) / 1e3


def _deadline_shed_counter() -> telemetry.Counter:
    return telemetry.counter(
        "dl4j_serving_deadline_shed_total",
        "requests dropped because their deadline expired — "
        "where=admission (already expired on arrival, fast-failed 504 "
        "before occupying a slot) or where=queue (expired while "
        "queued, cancelled at flush before compute)")


class AdmissionController:
    """Bounded per-model admission with load shedding, SLO-adaptive
    budgets, measured ``Retry-After``, and graceful drain.

    - ``max_queue``: in-flight ceiling per model (queued + computing).
      Without an SLO this is the whole story: request ``max_queue + 1``
      sheds with 429.
    - ``latency_slo_ms``: default SLO for every model (per-model
      overrides via :meth:`set_slo`, usually wired from
      ``ModelRegistry.register(latency_slo_ms=)``). Arms the AIMD
      budget controller described in the module docstring.
    - ``retry_after_s``: the ``Retry-After`` floor and the cold-start
      answer before any completion has been observed.
    - :meth:`drain`: flip to draining (new requests shed with 503),
      block until in-flight reaches zero or ``timeout`` passes.
    """

    def __init__(self, max_queue: int = 64,
                 retry_after_s: float = 1.0, *,
                 latency_slo_ms: Optional[float] = None,
                 adapt_window: int = 64,
                 rate_window_s: float = 30.0,
                 min_budget: int = 1):
        self.max_queue = int(max_queue)
        self.retry_after_s = float(retry_after_s)
        self.latency_slo_ms = latency_slo_ms
        self.adapt_window = int(adapt_window)
        self.rate_window_s = float(rate_window_s)
        self.min_budget = int(min_budget)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._inflight: Dict[str, int] = {}
        self._draining = False
        self._slo_ms: Dict[str, float] = {}
        self._budget: Dict[str, int] = {}
        #: recent total (submit->response) latencies, per model
        self._totals: Dict[str, Deque[float]] = {}
        #: completion timestamps for the measured drain rate
        self._done_ts: Dict[str, Deque[float]] = {}
        self._gauge = telemetry.gauge(
            "dl4j_serving_inflight",
            "admitted requests currently queued or computing, "
            "per model")
        self._shed = telemetry.counter(
            "dl4j_serving_shed_total",
            "requests rejected by admission control "
            "(reason=queue_full -> 429, reason=draining -> 503)")

    @property
    def draining(self) -> bool:
        return self._draining

    def inflight(self, model: str) -> int:
        return self._inflight.get(model, 0)

    # -- SLO controller ------------------------------------------------
    def set_slo(self, model: str, slo_ms: Optional[float]) -> None:
        """Install (or clear) a per-model latency SLO; the registry
        wires this from ``register(latency_slo_ms=)``."""
        with self._lock:
            if slo_ms is None:
                self._slo_ms.pop(model, None)
            else:
                self._slo_ms[model] = float(slo_ms)

    def budget(self, model: str) -> int:
        """The live in-flight budget for ``model`` (== ``max_queue``
        until the SLO controller has reason to move it)."""
        return self._budget.get(model, self.max_queue)

    def observed_p95_ms(self, model: str) -> Optional[float]:
        with self._lock:
            window = self._totals.get(model)
            if not window:
                return None
            lats = sorted(window)
        return lats[min(len(lats) - 1,
                        int(math.ceil(0.95 * len(lats))) - 1)] * 1e3

    def observe_total(self, model: str, seconds: float,
                      now: Optional[float] = None,
                      trace_id: Optional[str] = None) -> None:
        """Report one completed request's total latency. Feeds the
        ``dl4j_serving_total_seconds`` histogram (with the request's
        trace id as an exemplar when tracing is on), the drain-rate
        window behind ``Retry-After``, the AIMD budget controller,
        and the SLO error-budget tracker.
        ``now`` is injectable for deterministic tests."""
        now = time.monotonic() if now is None else now
        hist = telemetry.histogram(
            "dl4j_serving_total_seconds",
            "total submit->response latency of completed predict "
            "requests — the observation stream the SLO-adaptive "
            "admission controller compares against latency_slo_ms "
            "(seconds)")
        if trace_id:
            hist.observe_with_exemplar(seconds,
                                       {"trace_id": trace_id},
                                       model=model)
        else:
            hist.observe(seconds, model=model)
        slo_ms = self._slo_ms.get(model, self.latency_slo_ms)
        if slo_ms is not None:
            from deeplearning4j_tpu.serving.slo import SLOTracker
            SLOTracker.get().observe(model, seconds, slo_ms, now=now)
        with self._lock:
            self._totals.setdefault(
                model, deque(maxlen=self.adapt_window)).append(
                    float(seconds))
            done = self._done_ts.setdefault(model, deque(maxlen=512))
            done.append(now)
            rate = self._drain_rate_locked(model, now)
            self._adapt_locked(model)
        if telemetry.enabled() and rate is not None:
            telemetry.gauge(
                "dl4j_serving_drain_rate_rps",
                "measured request completion rate per model over the "
                "admission controller's sliding window — the "
                "denominator of the derived Retry-After"
            ).set(rate, model=model)

    def _adapt_locked(self, model: str) -> None:
        slo_ms = self._slo_ms.get(model, self.latency_slo_ms)
        if slo_ms is None:
            return
        window = self._totals.get(model)
        if not window:
            return
        lats = sorted(window)
        p95_ms = lats[min(len(lats) - 1,
                          int(math.ceil(0.95 * len(lats))) - 1)] * 1e3
        budget = self._budget.get(model, self.max_queue)
        if p95_ms > slo_ms:
            budget = max(self.min_budget, int(budget * _SHRINK))
            if budget < self._budget.get(model, self.max_queue):
                # log the SLO burn rate against the shrink decision:
                # "the budget dropped because the fast window was
                # burning at X" is answerable after the fact
                from deeplearning4j_tpu.serving.slo import SLOTracker
                burn = SLOTracker.get().burn_rate(model, "fast")
                log.info(
                    "admission: shrinking %s budget -> %d "
                    "(p95 %.1fms > SLO %.1fms; fast burn rate %s)",
                    model, budget, p95_ms, slo_ms,
                    f"{burn:.2f}" if burn is not None else "n/a")
                telemetry.instant(
                    "admission.shrink", model=model, budget=budget,
                    p95_ms=round(p95_ms, 3),
                    burn_rate_fast=burn)
        elif p95_ms < _REGROW_AT * slo_ms and budget < self.max_queue:
            budget += 1
        self._budget[model] = budget
        if telemetry.enabled():
            telemetry.gauge(
                "dl4j_serving_admission_budget",
                "live SLO-adaptive in-flight budget per model (AIMD "
                "on windowed p95 vs latency_slo_ms; == the static "
                "max_queue when no SLO is set)").set(budget,
                                                     model=model)

    # -- measured Retry-After ------------------------------------------
    def _drain_rate_locked(self, model: str,
                           now: float) -> Optional[float]:
        """Completions per second over the sliding window (None before
        the first observation — the cold start).

        Cold-window guard: until >= 2 samples actually span the
        window, ``len(recent) / (now - recent[0])`` is degenerate —
        one completion observed "just now" used to divide by the 1e-3
        floor and report an absurd ~1000 rps drain rate, which
        collapsed the derived Retry-After to its floor right after
        startup. With too little signal we instead report the
        conservative floor rate (those completions spread over the
        FULL window), which can only over-estimate the wait, never
        promise a drain that is not happening."""
        done = self._done_ts.get(model)
        if not done:
            return None
        horizon = now - self.rate_window_s
        recent = [t for t in done if t >= horizon]
        if not recent:
            return None
        span = now - recent[0]
        if len(recent) < 2 or span <= 1e-3:
            return len(recent) / self.rate_window_s
        return len(recent) / span

    def retry_after_s_for(self, model: Optional[str] = None,
                          now: Optional[float] = None) -> float:
        """Seconds a shed client should wait, derived from the measured
        drain rate: time for the excess in-flight depth to drain,
        floored at ``retry_after_s`` and capped at
        ``RETRY_AFTER_CAP_S``. Cold start (zero observations) returns
        the floor."""
        now = time.monotonic() if now is None else now
        with self._lock:
            rate = (self._drain_rate_locked(model, now)
                    if model is not None else None)
            if not rate:
                return self.retry_after_s
            excess = max(1, self._inflight.get(model, 0)
                         - self._budget.get(model, self.max_queue) + 1)
        return min(RETRY_AFTER_CAP_S,
                   max(self.retry_after_s, excess / rate))

    def retry_after_header(self, model: Optional[str] = None) -> str:
        """Integral seconds for the ``Retry-After`` header (≥ 1)."""
        return str(max(1, int(math.ceil(self.retry_after_s_for(model)))))

    # ------------------------------------------------------------------
    def admit(self, model: str,
              deadline: Optional[float] = None, *,
              cost: int = 1) -> None:
        """Admit one request for ``model`` or raise. Pair every
        successful admit with a :meth:`release` (same ``cost``).

        ``cost`` is the request's weight against the in-flight budget
        — 1 for a predict, the prompt's token-block cost for a
        generative prefill (ISSUE 16: prefill is admitted by
        *token-cost* through the same AIMD controller, so one long
        prompt spends the budget many short ones would). An oversized
        request is still admitted when the model is idle — otherwise a
        prompt longer than the budget could never run.

        Raises :class:`DeadlineExceeded` when ``deadline`` (a
        ``time.monotonic()`` instant) is already past — the fast-fail
        path: an already-dead request must never occupy a slot.
        Raises :class:`ShedError` on drain or budget exhaustion."""
        cost = max(1, int(cost))
        if deadline is not None and time.monotonic() >= deadline:
            _deadline_shed_counter().inc(model=model, where="admission")
            raise DeadlineExceeded(
                "deadline already expired at admission")
        with self._lock:
            if self._draining:
                self._shed.inc(model=model, reason="draining")
                raise ShedError("draining", self.retry_after_s)
            n = self._inflight.get(model, 0)
            limit = min(self._budget.get(model, self.max_queue),
                        self.max_queue)
            if n >= limit or (n > 0 and n + cost > limit):
                self._shed.inc(model=model, reason="queue_full")
                rate = self._drain_rate_locked(model, time.monotonic())
                retry = (self.retry_after_s if not rate else
                         min(RETRY_AFTER_CAP_S,
                             max(self.retry_after_s, cost / rate)))
                raise ShedError("queue_full", retry)
            self._inflight[model] = n + cost
            self._gauge.set(n + cost, model=model)

    def release(self, model: str, *, cost: int = 1) -> None:
        with self._lock:
            n = max(0, self._inflight.get(model, 0) - max(1, int(cost)))
            self._inflight[model] = n
            self._gauge.set(n, model=model)
            if n == 0:
                self._idle.notify_all()

    @contextmanager
    def track(self, model: str, deadline: Optional[float] = None, *,
              cost: int = 1):
        """``admit``/``release`` around a request's whole lifetime
        (queue wait + compute + response)."""
        self.admit(model, deadline, cost=cost)
        try:
            yield
        finally:
            self.release(model, cost=cost)

    # ------------------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting and wait for in-flight work to finish.
        Returns True when everything drained within ``timeout``."""
        deadline = time.monotonic() + timeout
        with self._lock:
            self._draining = True
            while any(self._inflight.values()):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(left)
        return True

    def resume(self) -> None:
        """Leave draining mode (a drained server being restarted)."""
        with self._lock:
            self._draining = False
