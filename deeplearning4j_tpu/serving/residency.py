"""Sharded-model residency for serving.

A dense checkpoint too big for one chip becomes servable by keeping
its parameters *resident sharded* between requests — the training-side
ZeRO layouts (:mod:`parallel.zero`, PRs 10/12) applied to the
inference path:

- ``mode="sharded"`` — ZeRO-1-shaped: params live as per-dtype flat
  vectors sharded ``P(data)`` (1/N resident per chip); the jitted
  forward gathers the WHOLE tree up front (one fused all-gather wall
  at trace start), then runs the exact dense math.
- ``mode="fsdp"`` — ZeRO-3-shaped: same residency, but each entry's
  all-gather is emitted at its point of use inside the forward walk
  (:class:`~deeplearning4j_tpu.parallel.zero.FsdpParamView`), so peak
  live memory is one layer's dense params, not the whole model's.
- either mode **×tp**: on a 2D ``(data, model)`` mesh,
  :class:`~deeplearning4j_tpu.parallel.speclayout.SpecLayout` infers
  megatron-style splits and the matching leaves ride under ``TP_KEY``
  sharded over ``model`` (and ``data`` too where a free dim divides —
  1/(dp·tp) resident).

Serving differs from training in one deliberate way: the **compute**
spec of every tp leaf is forced to ``P()`` (fully replicated). Sharded
residency must be a pure placement choice — gather the exact bytes
back and run the same dense program — so outputs stay *bitwise* equal
to the single-chip path. Row-sharded compute would lower matmuls to
partial-sum ``psum`` chains whose float addition order differs from
dense; that is a fine training trade and a wrong serving default.
The model axis here buys memory, not FLOPs.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              DEFAULT_MODEL_AXIS)
from deeplearning4j_tpu.parallel.speclayout import SpecLayout, TpLeafSpec
from deeplearning4j_tpu.parallel.zero import (FsdpParamView,
                                              params_to_fsdp,
                                              place_fsdp_params)

#: parameter residency modes a ServingBatcher understands
MODES = ("dense", "sharded", "fsdp")

#: low-precision residency storage dtypes (``register(param_dtype=)``)
PARAM_DTYPES = ("bf16", "int8")

#: per-dtype dequant scales of an int8-at-rest entry, riding beside
#: FSDP_KEY in the placed tree (replicated f32 scalars)
QSCALE_KEY = "__qscale__"


def resolve_param_dtype(param_dtype) -> Optional[str]:
    """Normalize a ``param_dtype`` knob to ``None`` (full-precision
    residency), ``"bf16"`` or ``"int8"``."""
    if param_dtype is None:
        return None
    s = str(param_dtype).lower()
    if s in ("", "f32", "fp32", "float32", "dense"):
        return None
    if s in ("bf16", "bfloat16"):
        return "bf16"
    if s in ("int8", "i8"):
        return "int8"
    raise ValueError(f"param_dtype must be one of {PARAM_DTYPES} "
                     f"(or None/'float32'), got {param_dtype!r}")


def serving_tp_specs(mesh, dense_params,
                     model_axis: str = DEFAULT_MODEL_AXIS,
                     data_axis: str = DEFAULT_DATA_AXIS
                     ) -> Dict[str, Dict[str, TpLeafSpec]]:
    """Tensor-parallel residency specs for serving: SpecLayout's
    inferred splits with every **compute** spec replaced by ``P()``
    (gather-to-replicated before the math — see the module docstring
    for why serving insists on this)."""
    layout = SpecLayout(mesh, model_axis, data_axis)
    inferred = layout.infer(dense_params, shard_over_data=True)
    return {k: {n: TpLeafSpec(P(), ls.resident)
                for n, ls in sub.items()}
            for k, sub in inferred.items()}


def _quantize_flat(flat):
    """Symmetric int8 quantization of one float flat vector. Returns
    ``(q, scale)`` with ``q = round(flat / scale)`` clipped to ±127 and
    ``scale`` an f32 scalar (1.0 for an all-zero vector)."""
    import jax.numpy as jnp
    v = jnp.asarray(flat)
    amax = float(jnp.max(jnp.abs(v)))
    scale = np.float32(amax / 127.0 if amax > 0 else 1.0)
    q = jnp.clip(jnp.round(v / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _store_low_precision(flat_tree, storage: str):
    """Apply the at-rest storage dtype to an fsdp-flat tree BEFORE
    placement. ``bf16`` casts float flats (and tp float leaves) to
    bfloat16; ``int8`` quantizes each float flat against a per-flat
    symmetric scale (tp leaves fall back to bf16 — their gather path
    bypasses the flat dequant). Returns ``(tree, scales)`` with
    ``scales[entry][dtype_key] -> np.float32``."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.common.dtypes import cast_floats
    from deeplearning4j_tpu.parallel.zero import FSDP_KEY, TP_KEY, is_fsdp
    out, scales = {}, {}
    for k, sub in flat_tree.items():
        if not is_fsdp(sub):
            out[k] = sub
            continue
        flats, entry_scales = {}, {}
        for dt, v in sub[FSDP_KEY].items():
            if not jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating):
                flats[dt] = v
            elif storage == "bf16":
                flats[dt] = jnp.asarray(v).astype(jnp.bfloat16)
            else:
                flats[dt], entry_scales[dt] = _quantize_flat(v)
        new = {FSDP_KEY: flats}
        if TP_KEY in sub:
            new[TP_KEY] = cast_floats(sub[TP_KEY], jnp.bfloat16)
        out[k] = new
        if entry_scales:
            scales[k] = entry_scales
    return out, scales


def serving_layouts(mesh, dense_params, mode: str,
                    tensor_parallel: Optional[int] = None, *,
                    name: str = "model", param_dtype=None
                    ) -> Tuple[dict, dict, dict]:
    """Place a dense param tree resident-sharded for serving.

    Returns ``(placed, fsdp_specs, tp_specs)`` — the flat-layout tree
    device_put at its resident shardings, the per-entry
    :class:`~deeplearning4j_tpu.learning.updaters.DpFlatSpec` map, and
    the serving tp specs (empty off the tp path). ``tensor_parallel``
    defaults to the mesh's ``model``-axis extent; pass 1 to force
    dp-only sharding on a 2D mesh.

    ``param_dtype`` (``"bf16"`` | ``"int8"``) stores the resident flats
    low-precision — half (bf16) or a quarter (int8 + per-flat scale)
    of the dense bytes per chip; :func:`serving_param_view` restores
    float32 compute post-gather through ``FsdpParamView.cast``."""
    if mode not in MODES or mode == "dense":
        raise ValueError(f"serving residency mode must be one of "
                         f"{MODES[1:]}, got {mode!r}")
    storage = resolve_param_dtype(param_dtype)
    tp = int(mesh.shape.get(DEFAULT_MODEL_AXIS, 1)
             if tensor_parallel is None else tensor_parallel)
    if tp > 1 and mesh.shape.get(DEFAULT_MODEL_AXIS, 1) != tp:
        raise ValueError(
            f"tensor_parallel={tp} needs a mesh with a "
            f"'{DEFAULT_MODEL_AXIS}' axis of that extent, got "
            f"{dict(mesh.shape)}")
    tp_specs = (serving_tp_specs(mesh, dense_params) if tp > 1 else {})
    n_shards = int(mesh.shape[DEFAULT_DATA_AXIS])
    flat, fsdp_specs = params_to_fsdp(
        dense_params, n_shards,
        tp_specs={k: tuple(sub) for k, sub in tp_specs.items()})
    scales = {}
    if storage is not None:
        flat, scales = _store_low_precision(flat, storage)
    placed = place_fsdp_params(mesh, flat, DEFAULT_DATA_AXIS,
                               tp_specs=tp_specs)
    if scales:
        import jax

        from deeplearning4j_tpu.parallel.zero import replicated
        full = replicated(mesh)
        for k, entry_scales in scales.items():
            placed[k] = {**placed[k],
                         QSCALE_KEY: {dt: jax.device_put(s, full)
                                      for dt, s in entry_scales.items()}}
    if telemetry.enabled():
        telemetry.gauge(
            "dl4j_serving_param_resident_bytes",
            "per-chip resident parameter bytes of a serving model by "
            "residency mode — ~1/N of dense under sharded/fsdp, "
            "1/(dp*tp) for tensor-parallel leaves").set(
                resident_param_bytes(placed), model=name, mode=mode)
    return placed, fsdp_specs, tp_specs


def _dequantize_tree(placed):
    """Trace-time inverse of the int8 at-rest quantization: each flat
    with a :data:`QSCALE_KEY` scale dequantizes to float32 on its 1/N
    resident shard (before the all-gather, so the wire carries f32 but
    the resident bytes stayed int8). Entries without scales pass
    through untouched."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.zero import FSDP_KEY
    out = {}
    for k, sub in placed.items():
        if not (isinstance(sub, dict) and QSCALE_KEY in sub):
            out[k] = sub
            continue
        sc = sub[QSCALE_KEY]
        flats = {dt: (v.astype(jnp.float32) * sc[dt] if dt in sc else v)
                 for dt, v in sub[FSDP_KEY].items()}
        out[k] = {**{kk: vv for kk, vv in sub.items()
                     if kk != QSCALE_KEY},
                  FSDP_KEY: flats}
    return out


def serving_param_view(placed, fsdp_specs, mesh, tp_specs, mode: str,
                       param_dtype=None):
    """The params object the jitted serving forward consumes (traced
    inside jit, once per XLA signature).

    ``fsdp``: the lazy :class:`FsdpParamView` — each entry's gather is
    emitted where the forward walk touches it. ``sharded``: the same
    view, eagerly materialized into a dense dict up front, so XLA sees
    one gather wall before any compute (ZeRO-1 shape).

    With a low-precision ``param_dtype`` the int8 flats dequantize on
    their resident shards and the view is re-cast float32 through
    :meth:`FsdpParamView.cast`, so the forward math runs full-precision
    on values that round-tripped the storage dtype once."""
    storage = resolve_param_dtype(param_dtype)
    tree = _dequantize_tree(placed) if storage == "int8" else placed
    view = FsdpParamView(tree, fsdp_specs, mesh, DEFAULT_DATA_AXIS,
                         prefetch=(mode == "fsdp"),
                         tp_specs=tp_specs)
    if storage is not None:
        view = view.cast(np.float32)
    if mode == "sharded":
        return {k: view.get(k) for k in tree}
    return view


def resident_param_bytes(placed) -> int:
    """Per-chip resident bytes of a placed serving param tree (the
    sharding-aware accounting from ``common.diagnostics``)."""
    from deeplearning4j_tpu.common.diagnostics import \
        _tree_resident_bytes
    return int(_tree_resident_bytes(placed))


def densify(placed, fsdp_specs) -> dict:
    """Host-side inverse of :func:`serving_layouts` (checkpoint /
    teardown boundaries). Int8-at-rest flats dequantize first; bf16
    flats densify as bf16 (cast back at the caller if needed)."""
    from deeplearning4j_tpu.parallel.zero import params_to_dense
    return params_to_dense(_dequantize_tree(placed), fsdp_specs)


def assert_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(f"unknown residency mode {mode!r}; expected "
                         f"one of {MODES}")
    return mode
