"""Sharded-model residency for serving.

A dense checkpoint too big for one chip becomes servable by keeping
its parameters *resident sharded* between requests — the training-side
ZeRO layouts (:mod:`parallel.zero`, PRs 10/12) applied to the
inference path:

- ``mode="sharded"`` — ZeRO-1-shaped: params live as per-dtype flat
  vectors sharded ``P(data)`` (1/N resident per chip); the jitted
  forward gathers the WHOLE tree up front (one fused all-gather wall
  at trace start), then runs the exact dense math.
- ``mode="fsdp"`` — ZeRO-3-shaped: same residency, but each entry's
  all-gather is emitted at its point of use inside the forward walk
  (:class:`~deeplearning4j_tpu.parallel.zero.FsdpParamView`), so peak
  live memory is one layer's dense params, not the whole model's.
- either mode **×tp**: on a 2D ``(data, model)`` mesh,
  :class:`~deeplearning4j_tpu.parallel.speclayout.SpecLayout` infers
  megatron-style splits and the matching leaves ride under ``TP_KEY``
  sharded over ``model`` (and ``data`` too where a free dim divides —
  1/(dp·tp) resident).

Serving differs from training in one deliberate way: the **compute**
spec of every tp leaf is forced to ``P()`` (fully replicated). Sharded
residency must be a pure placement choice — gather the exact bytes
back and run the same dense program — so outputs stay *bitwise* equal
to the single-chip path. Row-sharded compute would lower matmuls to
partial-sum ``psum`` chains whose float addition order differs from
dense; that is a fine training trade and a wrong serving default.
The model axis here buys memory, not FLOPs.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.parallel.mesh import (DEFAULT_DATA_AXIS,
                                              DEFAULT_MODEL_AXIS)
from deeplearning4j_tpu.parallel.speclayout import SpecLayout, TpLeafSpec
from deeplearning4j_tpu.parallel.zero import (FsdpParamView,
                                              params_to_fsdp,
                                              place_fsdp_params)

#: parameter residency modes a ServingBatcher understands
MODES = ("dense", "sharded", "fsdp")


def serving_tp_specs(mesh, dense_params,
                     model_axis: str = DEFAULT_MODEL_AXIS,
                     data_axis: str = DEFAULT_DATA_AXIS
                     ) -> Dict[str, Dict[str, TpLeafSpec]]:
    """Tensor-parallel residency specs for serving: SpecLayout's
    inferred splits with every **compute** spec replaced by ``P()``
    (gather-to-replicated before the math — see the module docstring
    for why serving insists on this)."""
    layout = SpecLayout(mesh, model_axis, data_axis)
    inferred = layout.infer(dense_params, shard_over_data=True)
    return {k: {n: TpLeafSpec(P(), ls.resident)
                for n, ls in sub.items()}
            for k, sub in inferred.items()}


def serving_layouts(mesh, dense_params, mode: str,
                    tensor_parallel: Optional[int] = None, *,
                    name: str = "model"
                    ) -> Tuple[dict, dict, dict]:
    """Place a dense param tree resident-sharded for serving.

    Returns ``(placed, fsdp_specs, tp_specs)`` — the flat-layout tree
    device_put at its resident shardings, the per-entry
    :class:`~deeplearning4j_tpu.learning.updaters.DpFlatSpec` map, and
    the serving tp specs (empty off the tp path). ``tensor_parallel``
    defaults to the mesh's ``model``-axis extent; pass 1 to force
    dp-only sharding on a 2D mesh."""
    if mode not in MODES or mode == "dense":
        raise ValueError(f"serving residency mode must be one of "
                         f"{MODES[1:]}, got {mode!r}")
    tp = int(mesh.shape.get(DEFAULT_MODEL_AXIS, 1)
             if tensor_parallel is None else tensor_parallel)
    if tp > 1 and mesh.shape.get(DEFAULT_MODEL_AXIS, 1) != tp:
        raise ValueError(
            f"tensor_parallel={tp} needs a mesh with a "
            f"'{DEFAULT_MODEL_AXIS}' axis of that extent, got "
            f"{dict(mesh.shape)}")
    tp_specs = (serving_tp_specs(mesh, dense_params) if tp > 1 else {})
    n_shards = int(mesh.shape[DEFAULT_DATA_AXIS])
    flat, fsdp_specs = params_to_fsdp(
        dense_params, n_shards,
        tp_specs={k: tuple(sub) for k, sub in tp_specs.items()})
    placed = place_fsdp_params(mesh, flat, DEFAULT_DATA_AXIS,
                               tp_specs=tp_specs)
    if telemetry.enabled():
        telemetry.gauge(
            "dl4j_serving_param_resident_bytes",
            "per-chip resident parameter bytes of a serving model by "
            "residency mode — ~1/N of dense under sharded/fsdp, "
            "1/(dp*tp) for tensor-parallel leaves").set(
                resident_param_bytes(placed), model=name, mode=mode)
    return placed, fsdp_specs, tp_specs


def serving_param_view(placed, fsdp_specs, mesh, tp_specs, mode: str):
    """The params object the jitted serving forward consumes (traced
    inside jit, once per XLA signature).

    ``fsdp``: the lazy :class:`FsdpParamView` — each entry's gather is
    emitted where the forward walk touches it. ``sharded``: the same
    view, eagerly materialized into a dense dict up front, so XLA sees
    one gather wall before any compute (ZeRO-1 shape)."""
    view = FsdpParamView(placed, fsdp_specs, mesh, DEFAULT_DATA_AXIS,
                         prefetch=(mode == "fsdp"),
                         tp_specs=tp_specs)
    if mode == "sharded":
        return {k: view.get(k) for k in placed}
    return view


def resident_param_bytes(placed) -> int:
    """Per-chip resident bytes of a placed serving param tree (the
    sharding-aware accounting from ``common.diagnostics``)."""
    from deeplearning4j_tpu.common.diagnostics import \
        _tree_resident_bytes
    return int(_tree_resident_bytes(placed))


def densify(placed, fsdp_specs) -> dict:
    """Host-side inverse of :func:`serving_layouts` (checkpoint /
    teardown boundaries)."""
    from deeplearning4j_tpu.parallel.zero import params_to_dense
    return params_to_dense(placed, fsdp_specs)


def assert_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(f"unknown residency mode {mode!r}; expected "
                         f"one of {MODES}")
    return mode
